"""The fault-tolerant long-context (sequence-parallel) training plane.

Single-process stand-ins for N ring hosts driven entirely by the
caller's virtual clock (``now`` arguments) — no wall-clock anywhere, so
every drill on this plane is bit-reproducible. The reliability contract
mirrors the PR 17 parameter-server fleet and the PR 18 MoE plane,
applied to ring attention (Liu et al., Ring Attention with Blockwise
Transformers) and Ulysses sequence parallelism:

- every sequence shard's K/V block lives on a **primary** and a
  **follower** host (consistent-hash placement,
  :class:`~.ps.sharding.HashRing`); the per-step distribute commits the
  new batch's blocks transactionally (liveness phase first, nothing
  written on abort) to the primary and ships a full-copy replica to the
  follower, priced on the fabric between their slices;
- a dead host is detected at the next **probe sweep**
  (:meth:`SeqHostFleet.maybe_probe` — the lazily-anchored cadence of
  ``health.py``), so detection latency is INSIDE the gated MTTR;
- promotion is a placement recomputation — the ring guarantees the dead
  primary's first distinct successor is exactly the current follower,
  so the K/V bytes are already there; the blockwise RING RE-FORMS over
  the survivors (the rotation order is recomputed from the live
  placement on the next pass) and only the replacement follower pays a
  full-copy resync (priced per link class);
- ``kill_seq_host`` chaos enters through the same per-op gate as every
  real op (:meth:`SeqHostFleet._op` — the distribute walk, the
  pass-start block read, EVERY ring hop), raising the typed
  :class:`SeqHostFailedError` — a ``TransientStepError`` — so a
  :class:`~.fault_tolerance.reliable.ReliableStep`-wrapped step replays
  BITWISE once the probe sweep heals the placement. The property that
  makes the replay bitwise: a partial ring pass commits NOTHING. The
  online-softmax ``(o, lse)`` accumulator is a step-local value merged
  only on a COMPLETED pass, so the replayed step starts from exactly
  the pre-step state;
- correctness is audited by the **LSE-merge conservation ledger**
  (:meth:`LongSeqPlane._audit`): after every step and every chaos
  event, every query block's merged output is re-derived in float64
  from the recorded per-block partials (the softmax weights of a
  merged block must sum to EXACTLY one, and the weighted block outputs
  must reproduce the merged output) and checked against the float64
  full-attention oracle (:func:`block_attn_lse_np` over the whole
  sequence, causal masking included). Exact means exact at f64
  resolution: the gate tolerance (1e-9) sits six orders of magnitude
  above the observed f64 re-association noise (~1e-13 for the lane's
  shapes) and six below any real accumulator corruption.

Transport is priced per ICI/DCN link class through
:class:`CollectiveTraffic`: each ring hop is a point-to-point K/V block
pass between consecutive ring members (slice-contiguous member order
pays one DCN α per slice boundary per rotation; the interleaved "flat"
order pays one per hop — the lane requires the flat schedule to FAIL
the step budget), and each Ulysses all-to-all is priced from its exact
per-pair byte matrix via ``add_all_to_all_matrix`` — the PR 14
α-dominance discipline.

Numerics note (load-bearing for the bitwise gates): the blockwise merge
order for query chunk ``i`` is the canonical ring arrival order
``j = i, i-1, ..., i-n+1 (mod n)`` — a function of SHARD ids only.
Failover moves a shard's bytes to a different HOST and the transport
schedule decides which fabric carries each hop, but neither changes the
merge order, which is why the 8-host ring, the post-failover ring, and
the single-host full-attention twin (same blockwise arithmetic, no
fleet) agree bitwise.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..observability import metrics
from ..observability.cost_model import (CollectiveTraffic, LinkModel,
                                        pipeline_bubble_fraction,
                                        sparse_transfer_seconds)
from .fault_tolerance import chaos
from .fault_tolerance.health import HealthReport
from .fault_tolerance.reliable import ReliableStep, TransientStepError
from .moe_fleet import params_crc, price_all_to_all
from .ps.client import VirtualClock
from .ps.sharding import HashRing
from .sep import HeadShardingError

__all__ = ["LongSeqPlaneError", "SeqHostFailedError", "SeqHost",
           "SeqHostFleet", "LongSeqPlane", "seq_flight",
           "block_attn_lse_np", "merge_np", "causal_block_mask",
           "ring_attend_np", "full_attention_np", "head_step_np",
           "ring_member_slices", "model_long_context_step",
           "preferred_attention"]

_NEG = float("-inf")


def seq_flight(**fields) -> None:
    """One shared emitter for every sequence-parallel flight-recorder
    span (``kind="sep"``): host kills, failovers / ring re-formations,
    resyncs, LSE-ledger breaches — rendered by flight_doctor's
    SEQUENCE PARALLEL section. None-valued fields are dropped; the
    recorder keeps its one-attribute-load no-op when disabled."""
    from .fault_tolerance import flight_recorder
    flight_recorder.record("sep", **{k: v for k, v in fields.items()
                                     if v is not None})


class LongSeqPlaneError(RuntimeError):
    """Base for sequence-parallel plane failures."""


class SeqHostFailedError(LongSeqPlaneError, TransientStepError):
    """A ring host died under an op (distribute walk, pass-start block
    read, or a mid-pass ring hop). Transient: the partial ``(o, lse)``
    accumulator is discarded (a partial pass commits NOTHING), the
    probe sweep promotes the shard's follower and re-forms the ring,
    and a ReliableStep retry after backoff replays the step bitwise."""

    def __init__(self, host: int, shard: int = -1, op: str = "?"):
        self.host, self.shard, self.op = int(host), int(shard), op
        LongSeqPlaneError.__init__(
            self, f"seq host {host} failed during {op!r}"
            + (f" (shard {shard})" if shard >= 0 else ""))


# ------------------------------------------------------------------ oracle
# float64 numpy mirrors of sep.py's jnp _block_attn_lse / _merge — the
# arithmetic is IDENTICAL term for term (same m_safe clamp, same 1e-30
# floor, same masked-row conventions) so the plane's blockwise math IS
# the oracle's math, just blockwise vs full-sequence.

def block_attn_lse_np(q: np.ndarray, k: np.ndarray, v: np.ndarray,
                      scale: float, mask: Optional[np.ndarray] = None
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Full (small-block) attention in float64 returning ``(out, lse)``.

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D]; mask: None or a bool
    [Sq, Sk] matrix (True = attend). Fully-masked rows return
    ``lse = -inf`` and a zero output row (weight 0 under
    :func:`merge_np`)."""
    qh = np.swapaxes(np.asarray(q, np.float64), 1, 2)
    kh = np.swapaxes(np.asarray(k, np.float64), 1, 2)
    vh = np.swapaxes(np.asarray(v, np.float64), 1, 2)
    s = np.einsum("bhsd,bhtd->bhst", qh, kh) * float(scale)
    if mask is not None:
        s = np.where(mask, s, _NEG)
    m = np.max(s, axis=-1)                                   # [B,H,Sq]
    m_safe = np.where(m == _NEG, 0.0, m)
    p = np.exp(s - m_safe[..., None])
    p = np.where(s == _NEG, 0.0, p)
    l = np.sum(p, axis=-1)                                   # [B,H,Sq]
    o = np.einsum("bhst,bhtd->bhsd", p, vh)
    o = o / np.maximum(l, 1e-30)[..., None]
    lse = np.where(l == 0.0, _NEG,
                   m_safe + np.log(np.maximum(l, 1e-30)))
    return np.swapaxes(o, 1, 2), lse


def merge_np(o1: np.ndarray, lse1: np.ndarray,
             o2: np.ndarray, lse2: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray]:
    """Log-sum-exp merge of two partial attention results in float64 —
    sep.py's ``_merge`` term for term. Stable under large-negative lse
    (the exp is always of a non-positive shifted value) and under
    fully-masked ``-inf`` blocks (weight exactly 0, so merging with an
    ``-inf`` accumulator returns the other side BITWISE — which is why
    the zero-init accumulator never perturbs the first block)."""
    o1 = np.asarray(o1, np.float64)
    o2 = np.asarray(o2, np.float64)
    m = np.maximum(lse1, lse2)
    m_safe = np.where(m == _NEG, 0.0, m)
    with np.errstate(invalid="ignore"):
        w1 = np.where(lse1 == _NEG, 0.0, np.exp(lse1 - m_safe))
        w2 = np.where(lse2 == _NEG, 0.0, np.exp(lse2 - m_safe))
    tot = np.maximum(w1 + w2, 1e-30)
    o = (o1 * np.swapaxes(w1, 1, 2)[..., None]
         + o2 * np.swapaxes(w2, 1, 2)[..., None]) \
        / np.swapaxes(tot, 1, 2)[..., None]
    with np.errstate(divide="ignore"):
        lse = np.where((w1 + w2) == 0.0, _NEG, m_safe + np.log(tot))
    return o, lse


def causal_block_mask(i: int, j: int, chunk: int
                      ) -> Optional[np.ndarray]:
    """The ring's causal block predicate (sep.py's ``_ring_body``
    convention, block-major token order): query rows live at global
    indices ``[i*chunk, (i+1)*chunk)`` and the held KV block originated
    on chunk ``j`` — so ``j < i`` attends the full block, ``j == i`` is
    intra-chunk lower-triangular, ``j > i`` is fully masked (every KV
    column is in the future). Returns None for the full block (no mask
    needed), else the bool [chunk, chunk] mask."""
    if j < i:
        return None
    if j == i:
        return np.tril(np.ones((chunk, chunk), bool))
    return np.zeros((chunk, chunk), bool)


def ring_attend_np(q: np.ndarray, k: np.ndarray, v: np.ndarray, *,
                   n: int, scale: float, causal: bool = True,
                   blocks: Optional[Dict[int, Dict[str, np.ndarray]]]
                   = None
                   ) -> Tuple[np.ndarray, np.ndarray,
                              List[List[Tuple[int, np.ndarray,
                                              np.ndarray]]]]:
    """The blockwise ring-attention arithmetic in float64, shared by
    the fleet-mediated plane and the single-host twin so their outputs
    are BITWISE equal: query chunk ``i`` merges KV blocks in the
    canonical ring arrival order ``j = (i - t) mod n``. ``blocks``
    optionally supplies the KV bytes (the plane passes the
    fleet-stored replicas; the twin slices locally). Returns
    ``(o [B,S,H,D], lse [B,H,S], partials)`` where ``partials[i]`` is
    the per-block ``(j, o_b, lse_b)`` list the conservation ledger
    re-derives the merge from."""
    q = np.asarray(q, np.float64)
    B, S, H, D = q.shape
    if S % n != 0:
        raise LongSeqPlaneError(
            f"seq len {S} not divisible by ring degree {n}")
    chunk = S // n
    if blocks is None:
        k = np.asarray(k, np.float64)
        v = np.asarray(v, np.float64)
        blocks = {j: {"k": k[:, j * chunk:(j + 1) * chunk],
                      "v": v[:, j * chunk:(j + 1) * chunk]}
                  for j in range(n)}
    o = np.zeros((B, S, H, D), np.float64)
    lse = np.full((B, H, S), _NEG, np.float64)
    partials: List[List[Tuple[int, np.ndarray, np.ndarray]]] = []
    for i in range(n):
        qi = q[:, i * chunk:(i + 1) * chunk]
        oi = np.zeros((B, chunk, H, D), np.float64)
        li = np.full((B, H, chunk), _NEG, np.float64)
        parts: List[Tuple[int, np.ndarray, np.ndarray]] = []
        for t in range(n):
            j = (i - t) % n
            mask = causal_block_mask(i, j, chunk) if causal else None
            o_b, lse_b = block_attn_lse_np(
                qi, blocks[j]["k"], blocks[j]["v"], scale, mask)
            parts.append((j, o_b, lse_b))
            oi, li = merge_np(oi, li, o_b, lse_b)
        o[:, i * chunk:(i + 1) * chunk] = oi
        lse[:, :, i * chunk:(i + 1) * chunk] = li
        partials.append(parts)
    return o, lse, partials


def full_attention_np(q: np.ndarray, k: np.ndarray, v: np.ndarray, *,
                      scale: float, causal: bool = True
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """The float64 full-softmax oracle: one global block, one global
    causal mask — what every ring/Ulysses result is audited against."""
    q = np.asarray(q, np.float64)
    S = q.shape[1]
    mask = np.tril(np.ones((S, S), bool)) if causal else None
    return block_attn_lse_np(q, k, v, scale, mask)


def head_step_np(o: np.ndarray, y: np.ndarray, wo: np.ndarray,
                 lr: float) -> Tuple[float, np.ndarray]:
    """The plane's (deliberately small) trainable tail: a linear output
    head under MSE, closed-form gradient, shared by plane and twin so
    the training trajectory is bitwise-comparable. Returns
    ``(loss, updated wo)``."""
    B, S, H, D = o.shape
    flat = o.reshape(B * S, H * D)
    err = flat @ wo - np.asarray(y, np.float64).reshape(B * S, -1)
    loss = float(np.mean(err * err))
    grad = (2.0 / err.size) * (flat.T @ err)
    return loss, wo - float(lr) * grad


# ------------------------------------------------------------------- fleet
class SeqHost:
    """One modeled ring host: alive flag + the K/V sequence-shard
    replicas it currently holds (primary AND follower roles — the
    fleet's placement says which is which)."""

    def __init__(self, host_id: int):
        self.id = int(host_id)
        self.alive = True
        self.shards: Dict[int, Dict[str, np.ndarray]] = {}
        self.ops = 0


class SeqHostFleet:
    """N modeled ring hosts holding one sequence shard each (shard s =
    sequence chunk s of the current batch's K/V). All methods take the
    caller's virtual ``now``. Hosts are grouped into ICI slices of
    ``hosts_per_slice`` consecutive ids; traffic between slices rides
    the DCN."""

    def __init__(self, num_hosts: int = 8, hosts_per_slice: int = 2,
                 probe_interval_s: float = 0.02,
                 link: Optional[LinkModel] = None, seed: int = 0):
        if probe_interval_s <= 0:
            raise ValueError(
                f"probe_interval_s must be > 0, got {probe_interval_s}")
        self.num_hosts = int(num_hosts)
        self.num_shards = int(num_hosts)
        self.hosts_per_slice = max(1, int(hosts_per_slice))
        self.probe_interval_s = float(probe_interval_s)
        self.ring = HashRing(num_hosts, num_shards=self.num_shards,
                             seed=seed)
        self.hosts = [SeqHost(i) for i in range(self.num_hosts)]
        self.link = link or LinkModel()
        self.traffic = CollectiveTraffic()
        self.placement: Dict[int, Tuple[int, Optional[int]]] = \
            self.ring.placement(tuple(range(self.num_hosts)))
        self.events: List[Dict[str, Any]] = []
        self.mttrs: List[float] = []
        self.repair_s = 0.0
        self.resyncs = 0
        self.failovers = 0
        self.reformations = 0
        self._next_probe_t: Optional[float] = None
        self._kill_t: Dict[int, float] = {}
        self._handled_failures: set = set()
        # flips True after the first COMMITTED distribute: before that,
        # a failover has no bytes to inherit or resync (the replayed
        # step re-attaches onto the re-formed placement from scratch)
        self._attached = False

    # -- placement ------------------------------------------------------
    def _alive_ids(self) -> Tuple[int, ...]:
        return tuple(h.id for h in self.hosts if h.alive)

    def slice_of(self, host_id: int) -> int:
        return int(host_id) // self.hosts_per_slice

    def _link_class(self, a: int, b: int) -> str:
        """Link class of a transfer between two hosts: co-located ⇒
        the PCIe-class host channel (no fabric α), same slice ⇒ ICI,
        cross-slice ⇒ DCN."""
        if a == b:
            return "host"
        return "ici" if self.slice_of(a) == self.slice_of(b) else "dcn"

    def primary_of(self, shard: int) -> int:
        primary, _ = self.placement[int(shard)]
        if primary is None:
            raise LongSeqPlaneError(f"shard {shard} has no primary")
        return primary

    def worker_of(self, shard: int) -> int:
        """The compute rank a shard's Q/K/V chunk is materialized on —
        the fixed data-parallel home, independent of where the K/V
        BYTES currently live (failover moves bytes, not compute)."""
        return int(shard) % self.num_hosts

    def ring_order(self, schedule: str = "hierarchical"
                   ) -> List[Tuple[int, int]]:
        """The transport schedule: ``(shard, primary host)`` pairs in
        ring-member order, recomputed from the LIVE placement — which
        is what "ring re-formation" means after a failover. The order
        is the pricing lever only (the merge order is canonical, see
        the module docstring):

        - ``hierarchical``: slice-contiguous — consecutive members
          share a slice wherever possible, one DCN α per slice
          boundary per rotation;
        - ``flat``: round-robin across slices — every hop crosses a
          slice boundary, one DCN α per hop (the order the lane
          requires to FAIL the budget).
        """
        if schedule not in ("hierarchical", "flat"):
            raise ValueError(f"schedule={schedule!r}")
        pairs = sorted(
            ((s, self.primary_of(s)) for s in range(self.num_shards)),
            key=lambda p: (self.slice_of(p[1]), p[1], p[0]))
        if schedule == "hierarchical":
            return pairs
        groups: Dict[int, List[Tuple[int, int]]] = {}
        for p in pairs:
            groups.setdefault(self.slice_of(p[1]), []).append(p)
        out: List[Tuple[int, int]] = []
        chains = [groups[k] for k in sorted(groups)]
        i = 0
        while any(chains):
            chain = chains[i % len(chains)]
            if chain:
                out.append(chain.pop(0))
            i += 1
        return out

    def attach_shards(self, kv: Dict[int, Dict[str, np.ndarray]],
                      now: float = 0.0) -> float:
        """First-time placement of every shard's K/V block (primary +
        follower), then :meth:`distribute` per step."""
        if any(h.shards for h in self.hosts):
            raise LongSeqPlaneError(
                "shards already attached to this fleet")
        return self.distribute(kv, now)

    # -- liveness / chaos entry of every op -----------------------------
    def _op(self, hid: int, op: str, shard: int, now: float) -> SeqHost:
        host = self.hosts[hid]
        host.ops += 1
        if chaos.maybe_kill_seq_host(hid, op=op):
            self.kill_host(hid, now)
        if not host.alive:
            raise SeqHostFailedError(hid, shard, op)
        return host

    def kill_host(self, hid: int, now: float) -> None:
        host = self.hosts[hid]
        if not host.alive:
            return
        host.alive = False
        self._kill_t[hid] = float(now)
        self.events.append({"event": "host_kill", "host": hid,
                            "t": float(now)})
        seq_flight(event="host_kill", host=hid, t=float(now))

    # -- per-step K/V placement -----------------------------------------
    def distribute(self, kv: Dict[int, Dict[str, np.ndarray]],
                   now: float) -> float:
        """TRANSACTIONAL placement of the step's K/V blocks: phase 1
        walks each shard's primary through the per-op chaos/liveness
        gate WITHOUT writing, phase 2 commits primaries and ships
        follower replicas, priced per link class. A host death in
        phase 1 aborts the whole transaction with nothing written, so
        the ReliableStep replay re-distributes the SAME bytes onto the
        re-formed placement — the property the bitwise-vs-clean-twin
        gate rests on."""
        if len(kv) != self.num_shards:
            raise LongSeqPlaneError(
                f"expected {self.num_shards} shards, got {len(kv)}")
        staged: List[Tuple[int, int, Optional[int],
                           Dict[str, np.ndarray]]] = []
        seconds = 0.0
        for s in sorted(kv):
            primary, follower = self.placement[s]
            if primary is None or not self.hosts[primary].alive:
                raise SeqHostFailedError(
                    -1 if primary is None else primary, s, "distribute")
            self._op(primary, "distribute", s, now)
            staged.append((s, primary, follower, kv[s]))
        for s, primary, follower, blk in staged:
            clean = {k: np.ascontiguousarray(np.asarray(v)).copy()
                     for k, v in blk.items()}
            nbytes = int(sum(a.nbytes for a in clean.values()))
            wcls = self._link_class(self.worker_of(s), primary)
            self.traffic.add(
                "sep_kv_distribute", nbytes,
                axes=("dcn",) if wcls == "dcn" else ("ici",),
                group_size=2)
            seconds += sparse_transfer_seconds(nbytes, wcls,
                                               link=self.link)
            self.hosts[primary].shards[s] = clean
            if follower is not None and self.hosts[follower].alive:
                rcls = self._link_class(primary, follower)
                self.traffic.add(
                    "sep_kv_replica", nbytes,
                    axes=("dcn",) if rcls == "dcn" else ("ici",),
                    group_size=2)
                seconds += sparse_transfer_seconds(nbytes, rcls,
                                                   link=self.link)
                self.hosts[follower].shards[s] = {
                    k: v.copy() for k, v in clean.items()}
        self._attached = True
        return seconds

    # -- the ring pass transport ----------------------------------------
    def read_block(self, shard: int, now: float
                   ) -> Dict[str, np.ndarray]:
        """Pass-start read of a shard's K/V bytes on its CURRENT
        primary (after a failover this is the promoted follower — the
        attention consumes the replica bytes, so replica fidelity is
        load-bearing, not decorative). On-host, so no wire cost; still
        a chaos/liveness-gated op."""
        primary, _ = self.placement[int(shard)]
        if primary is None or not self.hosts[primary].alive:
            raise SeqHostFailedError(
                -1 if primary is None else primary, shard, "ring_read")
        host = self._op(primary, "ring_read", shard, now)
        blk = host.shards.get(int(shard))
        if blk is None:
            raise LongSeqPlaneError(
                f"shard {shard}: primary {primary} holds no bytes")
        return {k: v.copy() for k, v in blk.items()}

    def hop(self, src: int, dst: int, shard: int, block_bytes: int,
            now: float) -> float:
        """One ring hop: the member on ``src`` forwards its held K/V
        block to its ring successor on ``dst``, chaos/liveness-gated on
        the SENDER (a mid-pass death surfaces here) and priced per the
        link class between their slices."""
        self._op(src, "ring_hop", shard, now)
        cls = self._link_class(src, dst)
        self.traffic.add("sep_ring_hop", block_bytes,
                         axes=("dcn",) if cls == "dcn" else ("ici",),
                         group_size=2)
        return sparse_transfer_seconds(block_bytes, cls,
                                       link=self.link)

    # -- probe sweeps / failover ----------------------------------------
    def maybe_probe(self, now: float) -> None:
        """Lazily-anchored probe cadence (the health-prober idiom): the
        first call anchors the sweep clock; each elapsed interval runs
        one sweep. Failover happens HERE, so detection latency is part
        of the gated MTTR."""
        if self._next_probe_t is None:
            self._next_probe_t = float(now) + self.probe_interval_s
            return
        while now >= self._next_probe_t:
            self.probe_now(self._next_probe_t)
            self._next_probe_t += self.probe_interval_s

    def probe_now(self, t: float) -> List[HealthReport]:
        """One sweep: a HealthReport per host; newly-dead hosts get
        their shards failed over (promotion + follower recruit) and
        the ring re-forms."""
        reports, newly_dead = [], []
        for host in self.hosts:
            rep = HealthReport(ok=host.alive, probe="sep_liveness",
                               reason="" if host.alive
                               else f"seq host {host.id} unreachable")
            reports.append(rep)
            if not rep.ok and host.id not in self._handled_failures:
                self._handled_failures.add(host.id)
                newly_dead.append(host.id)
                metrics.inc("sep_host_failures_total")
        if newly_dead:
            self._failover(newly_dead, t)
        return reports

    def _failover(self, newly_dead: List[int], t: float) -> None:
        old = dict(self.placement)
        self.placement = self.ring.placement(self._alive_ids())
        for s, (new_p, new_f) in sorted(self.placement.items()):
            old_p, old_f = old[s]
            if new_p != old_p:
                # the ring guarantees the successor is the old
                # follower: the K/V bytes are already on new_p —
                # promotion is a placement recomputation, not a copy.
                # Before the first committed distribute there are no
                # bytes anywhere, so there is nothing to have lost.
                if self._attached and s not in self.hosts[new_p].shards:
                    raise LongSeqPlaneError(
                        f"shard {s}: promoted host {new_p} holds no "
                        f"replica — both replicas lost")
                self.failovers += 1
                metrics.inc("sep_failovers_total")
                if old_p in self._kill_t:
                    self.mttrs.append(float(t) - self._kill_t[old_p])
                self.events.append({"event": "failover", "shard": s,
                                    "old": old_p, "new": new_p,
                                    "t": float(t)})
                seq_flight(event="failover", shard=s, host=new_p,
                           old_host=old_p, t=float(t))
            if new_f is not None and self._attached \
                    and s not in self.hosts[new_f].shards:
                # recruit: the replacement follower starts empty — a
                # full-copy resync from the (possibly just-promoted)
                # primary, priced on the fabric between their slices
                self.repair_s += self._resync(s, new_p, new_f, t,
                                              reason="recruit")
        # the rotation schedule is recomputed from the live placement
        # on the next pass — record the re-formation as its own event
        self.reformations += 1
        metrics.inc("sep_ring_reformations_total")
        self.events.append({"event": "ring_reform",
                            "members": [h for _, h in
                                        self.ring_order()],
                            "t": float(t)})
        seq_flight(event="ring_reform", t=float(t),
                   hosts=len(self._alive_ids()))
        for hid in newly_dead:
            self.hosts[hid].shards.clear()

    def _resync(self, shard: int, src: int, dst: int, t: float,
                reason: str) -> float:
        blk = {k: v.copy()
               for k, v in self.hosts[src].shards[shard].items()}
        self.hosts[dst].shards[shard] = blk
        nbytes = int(sum(a.nbytes for a in blk.values()))
        cls = self._link_class(src, dst)
        self.resyncs += 1
        metrics.inc("sep_resyncs_total", reason=reason)
        self.traffic.add("sep_resync", nbytes,
                         axes=("dcn",) if cls == "dcn" else ("ici",),
                         group_size=2)
        seconds = sparse_transfer_seconds(nbytes, cls, link=self.link)
        self.events.append({"event": "resync", "shard": shard,
                            "reason": reason, "bytes": nbytes,
                            "t": float(t)})
        seq_flight(event="resync", shard=shard, reason=reason,
                   bytes=nbytes, t=float(t))
        return seconds

    def last_mttr_s(self) -> float:
        return max(self.mttrs) if self.mttrs else 0.0

    def quiesce(self, now: float) -> None:
        """Run one forced sweep so anything dead-but-undetected fails
        over before the ledger is audited."""
        self.probe_now(float(now))

    # -- the cross-host shard ledger ------------------------------------
    def ledger(self) -> Dict[str, Any]:
        """Exact bookkeeping at drill end: every shard owned by exactly
        one alive primary, the shard partition covering
        range(num_shards), and every follower CRC-equal to its
        primary."""
        owned: List[int] = []
        one_primary = True
        crc_equal = True
        for s in range(self.num_shards):
            primary, follower = self.placement[s]
            if primary is None or not self.hosts[primary].alive \
                    or s not in self.hosts[primary].shards:
                one_primary = False
                continue
            owned.append(s)
            pp = self.hosts[primary].shards[s]
            if follower is not None and self.hosts[follower].alive:
                fp = self.hosts[follower].shards.get(s)
                if fp is None or params_crc(fp) != params_crc(pp):
                    crc_equal = False
        partition_exact = (sorted(owned)
                           == list(range(self.num_shards)))
        return {"ok": bool(one_primary and partition_exact
                           and crc_equal),
                "one_primary_per_shard": bool(one_primary),
                "shard_partition_exact": bool(partition_exact),
                "replicas_crc_equal": bool(crc_equal),
                "shards": self.num_shards,
                "alive_hosts": list(self._alive_ids())}


# ------------------------------------------------------------------- plane
class LongSeqPlane:
    """The long-context training plane: ring (or Ulysses) attention
    over a :class:`SeqHostFleet`, each step driven through
    :class:`ReliableStep` on a virtual clock.

    One step = transactionally distribute the batch's K/V blocks onto
    the placement (priced), run the blockwise pass THROUGH the fleet
    (pass-start reads + every ring hop chaos/liveness-gated and priced;
    Ulysses prices its two all-to-alls from the exact per-pair matrix),
    merge the ``(o, lse)`` accumulator only on pass COMPLETION, train
    the linear head (closed-form gradient), then audit the LSE-merge
    conservation ledger. ``SeqHostFailedError`` anywhere in the step
    aborts it with nothing committed; the injected ``sleep`` advances
    the virtual clock THROUGH the fleet's probe cadence, so backoff is
    also when failover detection happens — MTTR is modeled, not
    elided."""

    def __init__(self, fleet: SeqHostFleet, *, seq_len: int = 512,
                 heads: int = 4, head_dim: int = 8, batch: int = 1,
                 causal: bool = True, attn: str = "ring",
                 schedule: str = "hierarchical",
                 link: Optional[LinkModel] = None, lr: float = 0.05,
                 ledger_tol: float = 1e-9, retry_base_s: float = 0.02,
                 max_retries: int = 8, retry_budget: int = 32,
                 seed: int = 0):
        if attn not in ("ring", "ulysses"):
            raise ValueError(f"attn={attn!r}")
        if schedule not in ("hierarchical", "flat"):
            raise ValueError(f"schedule={schedule!r}")
        n = fleet.num_hosts
        if seq_len % n != 0:
            raise LongSeqPlaneError(
                f"seq len {seq_len} not divisible by ring degree {n}")
        if attn == "ulysses" and heads % n != 0:
            raise HeadShardingError(
                f"num_heads {heads} not divisible by sep degree {n}")
        self.fleet = fleet
        self.link = link or fleet.link
        self.seq_len, self.heads, self.head_dim = seq_len, heads, \
            head_dim
        self.batch, self.causal = batch, bool(causal)
        self.attn, self.schedule = attn, schedule
        self.chunk = seq_len // n
        self.scale = 1.0 / math.sqrt(head_dim)
        self.lr = float(lr)
        self.ledger_tol = float(ledger_tol)
        E = heads * head_dim
        rng = np.random.RandomState(seed)
        # frozen projections; only the output head trains (closed-form
        # MSE gradient — real state evolution, replay-testable)
        self.wq = rng.standard_normal((E, E)) / math.sqrt(E)
        self.wk = rng.standard_normal((E, E)) / math.sqrt(E)
        self.wv = rng.standard_normal((E, E)) / math.sqrt(E)
        self.head = _HeadHolder(rng.standard_normal((E, E))
                                / math.sqrt(E))
        self.opt = _NullOptimizer()
        self.clock = VirtualClock()
        self.reliable = ReliableStep(
            model=self.head, optimizer=self.opt, snapshot_every=1,
            max_retries=max_retries, retry_budget=retry_budget,
            base_delay=retry_base_s, max_delay=2.0, check_finite=False,
            sleep=self._sleep)
        self.step_no = 0
        self.ring_passes = 0
        self.hop_counts = {"ici": 0, "dcn": 0}
        self.comm_seconds: List[float] = []
        self.lse_audits: List[Dict[str, Any]] = []
        self.last_output: Optional[np.ndarray] = None

    # backoff sleeps advance the virtual clock THROUGH the probe
    # cadence: waiting is when the prober finds the corpse
    def _sleep(self, seconds: float) -> None:
        self.clock.advance(seconds)
        self.fleet.maybe_probe(self.clock.t)

    def project(self, x: np.ndarray
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """x [B, S, E] -> (q, k, v) [B, S, H, D] in float64, through
        the frozen projections — shared with the twin."""
        x = np.asarray(x, np.float64)
        B, S, _ = x.shape
        shp = (B, S, self.heads, self.head_dim)
        return ((x @ self.wq).reshape(shp),
                (x @ self.wk).reshape(shp),
                (x @ self.wv).reshape(shp))

    def train_step(self, x: np.ndarray, y: np.ndarray) -> float:
        before = self.reliable.stats["retries"]
        loss = self.reliable.run(self._step_fn, x, y)
        if self.reliable.stats["retries"] > before:
            metrics.inc("sep_replayed_steps_total")
        self.step_no += 1
        return loss

    def _step_fn(self, x: np.ndarray, y: np.ndarray) -> float:
        fleet, clock = self.fleet, self.clock
        fleet.maybe_probe(clock.t)
        q, k, v = self.project(x)
        kv = {s: {"k": k[:, s * self.chunk:(s + 1) * self.chunk],
                  "v": v[:, s * self.chunk:(s + 1) * self.chunk]}
              for s in range(fleet.num_shards)}
        clock.advance(fleet.distribute(kv, clock.t))
        if self.attn == "ring":
            o, lse, partials, comm_s = self._ring_pass(q)
        else:
            o, lse, partials, comm_s = self._ulysses_pass(q)
        clock.advance(comm_s)
        self.comm_seconds.append(comm_s)
        # pass COMPLETED — only now does anything commit
        loss, new_wo = head_step_np(o, y, self.head.wo, self.lr)
        self.head.wo = new_wo
        self.last_output = o
        self._audit(q, k, v, o, lse, partials)
        metrics.inc("sep_steps_total")
        return loss

    def _ring_pass(self, q: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray, list, float]:
        """The fleet-mediated blockwise pass: pass-start block reads on
        every primary, then n-1 rotations of chaos-gated, per-link-
        class-priced hops between consecutive ring members in the
        chosen transport order. The ``(o, lse)`` accumulator is merged
        ONLY after the transport completed — a mid-pass death leaves
        step-local garbage for the collector, never a partial merge."""
        fleet, now = self.fleet, self.clock.t
        n = fleet.num_shards
        blocks = {s: fleet.read_block(s, now) for s in range(n)}
        block_bytes = int(sum(a.nbytes
                              for a in blocks[0].values()))
        order = fleet.ring_order(self.schedule)
        seconds = 0.0
        for _t in range(1, n):
            for pos, (s, h) in enumerate(order):
                succ = order[(pos + 1) % n][1]
                seconds += fleet.hop(h, succ, s, block_bytes, now)
                cls = fleet._link_class(h, succ)
                if cls != "host":
                    self.hop_counts[cls] += 1
        o, lse, partials = ring_attend_np(
            q, None, None, n=n, scale=self.scale, causal=self.causal,
            blocks=blocks)
        self.ring_passes += 1
        metrics.inc("sep_ring_passes_total")
        return o, lse, partials, seconds

    def _ulysses_pass(self, q: np.ndarray
                      ) -> Tuple[np.ndarray, np.ndarray, list, float]:
        """The Ulysses alternative: two all-to-alls (seq-shard ->
        head-shard, then back) priced from the exact uniform per-pair
        matrix, full attention per head group (numerically the global
        oracle). Chaos/liveness-gated per participating host."""
        fleet, now = self.fleet, self.clock.t
        n = fleet.num_shards
        for s in range(n):
            fleet._op(fleet.primary_of(s), "a2a", s, now)
        blocks = {s: fleet.read_block(s, now) for s in range(n)}
        k = np.concatenate([blocks[s]["k"] for s in range(n)], axis=1)
        v = np.concatenate([blocks[s]["v"] for s in range(n)], axis=1)
        # per pair: q+k+v chunks out (seq->head) and o back
        per_pair = 4.0 * self.batch * self.chunk \
            * (self.heads // n) * self.head_dim * 8.0
        pair = np.full((n, n), per_pair, np.float64)
        np.fill_diagonal(pair, 0.0)
        seconds, counts, t = price_all_to_all(
            pair, fleet.hosts_per_slice, link=self.link,
            hierarchical=(self.schedule == "hierarchical"))
        fleet.traffic.entries.extend(t.entries)
        self.hop_counts["ici"] += counts["ici"]
        self.hop_counts["dcn"] += counts["dcn"]
        o, lse = full_attention_np(q, k, v, scale=self.scale,
                                   causal=self.causal)
        partials = [[(i, o[:, i * self.chunk:(i + 1) * self.chunk],
                      lse[:, :, i * self.chunk:(i + 1) * self.chunk])]
                    for i in range(n)]
        return o, lse, partials, seconds

    # -- the LSE-merge conservation ledger ------------------------------
    def _audit(self, q, k, v, o, lse, partials) -> Dict[str, Any]:
        """After every step (and re-run after every chaos event via
        :meth:`audit_now`): for each query block, (a) CONSERVATION —
        re-derive the merge single-pass from the recorded per-block
        partials: the softmax weights ``exp(lse_b - lse_merged)`` must
        sum to exactly 1 and reproduce the merged output; (b) ORACLE —
        the merged ``(o, lse)`` must equal the float64 full-attention
        softmax over the whole sequence, causal mask included. Both at
        f64 resolution (``ledger_tol``)."""
        n = self.fleet.num_shards
        chunk = self.chunk
        max_cons = 0.0
        max_orac = 0.0
        o_ref, lse_ref = full_attention_np(
            q, k, v, scale=self.scale, causal=self.causal)
        for i in range(n):
            oi = o[:, i * chunk:(i + 1) * chunk]
            li = lse[:, :, i * chunk:(i + 1) * chunk]
            live = li != _NEG
            wsum = np.zeros_like(li)
            osum = np.zeros_like(oi)
            for j, o_b, lse_b in partials[i]:
                with np.errstate(invalid="ignore"):
                    w = np.where(lse_b == _NEG, 0.0,
                                 np.exp(lse_b - np.where(live, li,
                                                         0.0)))
                wsum += w
                osum += o_b * np.swapaxes(w, 1, 2)[..., None]
            if live.any():
                max_cons = max(max_cons, float(
                    np.max(np.abs(wsum[live] - 1.0))))
                rows = np.swapaxes(live, 1, 2)[..., None] \
                    & np.ones_like(oi, bool)
                max_cons = max(max_cons, float(
                    np.max(np.abs(osum[rows] - oi[rows]))))
            max_orac = max(max_orac, float(np.max(np.abs(
                oi - o_ref[:, i * chunk:(i + 1) * chunk]))))
            lref = lse_ref[:, :, i * chunk:(i + 1) * chunk]
            both = live & (lref != _NEG)
            if both.any():
                max_orac = max(max_orac, float(
                    np.max(np.abs(li[both] - lref[both]))))
        ok = (max_cons <= self.ledger_tol
              and max_orac <= self.ledger_tol)
        audit = {"step": self.step_no, "ok": bool(ok),
                 "max_conservation_err": max_cons,
                 "max_oracle_err": max_orac}
        self.lse_audits.append(audit)
        metrics.inc("sep_lse_audits_total")
        if not ok:
            seq_flight(event="lse_ledger_breach", step=self.step_no,
                       conservation_err=round(max_cons, 12),
                       oracle_err=round(max_orac, 12), t=self.clock.t)
        self._last_audit_inputs = (q, k, v, o, lse, partials)
        return audit

    def audit_now(self) -> Optional[Dict[str, Any]]:
        """Re-run the ledger on the last completed step's recorded
        pass — the post-chaos audit the lane runs after ``quiesce``
        (a healed placement must not have changed what was merged)."""
        if getattr(self, "_last_audit_inputs", None) is None:
            return None
        return self._audit(*self._last_audit_inputs)

    def audits_ok(self) -> bool:
        return bool(self.lse_audits) and \
            all(a["ok"] for a in self.lse_audits)


class _HeadHolder:
    """ReliableStep holder for the trainable output head."""

    def __init__(self, wo: np.ndarray):
        self.wo = np.asarray(wo, np.float64)

    def state_dict(self) -> Dict[str, np.ndarray]:
        return {"wo": self.wo.copy()}

    def set_state_dict(self, state: Dict[str, np.ndarray]) -> None:
        self.wo = np.asarray(state["wo"], np.float64).copy()


class _NullOptimizer:
    """Stateless-SGD stand-in holder (the head's update is closed-form
    inside the step); ReliableStep still snapshots/restores it."""

    def state_dict(self) -> Dict[str, Any]:
        return {}

    def set_state_dict(self, state: Dict[str, Any]) -> None:
        pass


# --------------------------------------------------- 32k modeled pricing
def ring_member_slices(num_hosts: int, hosts_per_slice: int,
                       schedule: str = "hierarchical") -> List[int]:
    """Slice id of each ring member IN RING ORDER for the two transport
    schedules (the :meth:`CollectiveTraffic.add_ring_hops` input):
    slice-contiguous (``hierarchical``) vs round-robin interleaved
    (``flat``)."""
    hps = max(1, int(hosts_per_slice))
    num_slices = (int(num_hosts) + hps - 1) // hps
    if schedule == "hierarchical":
        return [h // hps for h in range(int(num_hosts))]
    if schedule == "flat":
        return [h % num_slices for h in range(int(num_hosts))]
    raise ValueError(f"schedule={schedule!r}")


def model_long_context_step(*, seq_len: int = 32768, heads: int = 8,
                            head_dim: int = 64, batch: int = 1,
                            layers: int = 8, dtype_bytes: int = 2,
                            num_hosts: int = 8, hosts_per_slice: int = 2,
                            schedule: str = "hierarchical",
                            attn: str = "ring", pp: int = 4,
                            microbatches: int = 8,
                            virtual_stages: int = 4,
                            grad_bytes: float = 64e6,
                            flops_per_s: float = 180e12,
                            mfu: float = 0.4,
                            link: Optional[LinkModel] = None
                            ) -> Dict[str, Any]:
    """Deterministic cost-only model of ONE 32k-sequence training step
    composing SEP with interleaved-VPP and hierarchical collectives —
    the lane's budget-gate surface (the real numerics run on the small
    fleet; this prices the target shape, the multichip-ladder
    discipline). Attention comm per layer:

    - **ring**: n-1 rotations, each a full ring of K/V block hops
      (block = this host's K+V chunk), via ``add_ring_hops`` under the
      chosen member order;
    - **ulysses**: two all-to-alls (q/k/v out, o back) from the exact
      uniform per-pair matrix.

    Plus ONE grad sync per step (hierarchical reduce-scatter / DCN
    all-reduce / all-gather when ``schedule="hierarchical"``, flat DCN
    all-reduce otherwise) and the interleaved-VPP bubble stretching the
    whole step. Returns the decomposed seconds and dispatch counts so
    the lane can gate hierarchical-fits / flat-fails both ways."""
    link = link or LinkModel()
    n = int(num_hosts)
    chunk = int(seq_len) // n
    t = CollectiveTraffic()
    if attn == "ring":
        block_bytes = 2.0 * batch * chunk * heads * head_dim \
            * dtype_bytes
        counts = {"ici": 0, "dcn": 0}
        for _ in range(int(layers)):
            c = t.add_ring_hops(
                block_bytes,
                ring_member_slices(n, hosts_per_slice, schedule))
            counts["ici"] += c["ici"]
            counts["dcn"] += c["dcn"]
    elif attn == "ulysses":
        if heads % n != 0:
            raise HeadShardingError(
                f"num_heads {heads} not divisible by sep degree {n}")
        per_pair = 4.0 * batch * chunk * (heads // n) * head_dim \
            * dtype_bytes
        pair = np.full((n, n), per_pair, np.float64)
        np.fill_diagonal(pair, 0.0)
        counts = {"ici": 0, "dcn": 0}
        for _ in range(int(layers)):
            c = t.add_all_to_all_matrix(
                pair, hosts_per_slice, op="sep_a2a",
                hierarchical=(schedule == "hierarchical"))
            counts["ici"] += c["ici"]
            counts["dcn"] += c["dcn"]
    else:
        raise ValueError(f"attn={attn!r}")
    attn_comm_s = t.seconds(link)
    gs = CollectiveTraffic()
    num_slices = (n + hosts_per_slice - 1) // hosts_per_slice
    if schedule == "hierarchical":
        gs.add_hierarchical_all_reduce(
            grad_bytes, ici_axes=("ici",), dcn_axes=("dcn",),
            ici_group=hosts_per_slice, dcn_group=num_slices)
    else:
        gs.add("all_reduce_sum", grad_bytes, axes=("dcn",),
               group_size=n)
    grad_sync_s = gs.seconds(link)
    # causal attention flops per chip: 2 matmuls over S^2/2 scores
    attn_flops = 2.0 * 2.0 * batch * heads * (seq_len ** 2 / 2.0) \
        * head_dim * layers / n
    compute_s = attn_flops / (flops_per_s * mfu)
    bubble = pipeline_bubble_fraction(pp, microbatches,
                                      virtual_stages=virtual_stages)
    step_s = (compute_s + attn_comm_s + grad_sync_s) * (1.0 + bubble)
    tokens = float(batch * seq_len)
    return {"attn": attn, "schedule": schedule,
            "attn_comm_s": attn_comm_s, "counts": counts,
            "grad_sync_s": grad_sync_s, "compute_s": compute_s,
            "bubble_fraction": bubble, "step_s": step_s,
            "tokens_per_s": tokens / step_s if step_s > 0 else 0.0}


def preferred_attention(*, seq_len: int, heads: int, head_dim: int,
                        batch: int = 1, layers: int = 8,
                        dtype_bytes: int = 2, num_hosts: int = 8,
                        hosts_per_slice: int = 2,
                        link: Optional[LinkModel] = None
                        ) -> Dict[str, Any]:
    """Ring-vs-Ulysses selection from the priced hierarchical comm
    costs of the same shape: Ulysses moves ~4·S·E/n bytes per rank per
    layer (two a2a) against the ring's (n-1)·2·S·E/n — the ring wins
    on bytes as n grows, Ulysses wins on dispatch count; head
    divisibility is a hard constraint (no integral head group -> ring
    is the only option). Returns the decision and both priced costs —
    the README's selection table is generated from exactly this."""
    ring = model_long_context_step(
        seq_len=seq_len, heads=heads, head_dim=head_dim, batch=batch,
        layers=layers, dtype_bytes=dtype_bytes, num_hosts=num_hosts,
        hosts_per_slice=hosts_per_slice, attn="ring",
        schedule="hierarchical", link=link)
    if heads % num_hosts != 0:
        return {"choice": "ring", "reason": "heads_not_divisible",
                "ring_comm_s": ring["attn_comm_s"],
                "ulysses_comm_s": None}
    uly = model_long_context_step(
        seq_len=seq_len, heads=heads, head_dim=head_dim, batch=batch,
        layers=layers, dtype_bytes=dtype_bytes, num_hosts=num_hosts,
        hosts_per_slice=hosts_per_slice, attn="ulysses",
        schedule="hierarchical", link=link)
    choice = "ring" if ring["attn_comm_s"] <= uly["attn_comm_s"] \
        else "ulysses"
    return {"choice": choice, "reason": "priced_comm",
            "ring_comm_s": ring["attn_comm_s"],
            "ulysses_comm_s": uly["attn_comm_s"]}
