"""Global device mesh — the TPU-native ProcessGroup topology.

The reference builds rank topologies out of NCCL communicators
(``paddle/phi/core/distributed/collective/process_group.h:48``,
``fleet/base/topology.py:189`` HybridCommunicateGroup). On TPU the native
equivalent is a single ``jax.sharding.Mesh`` over all chips whose NAMED AXES
are the communication groups: collectives compile to XLA HLO over an axis
(ICI ring), sub-groups are sub-axes, and hybrid parallelism is an N-D mesh
with axes ordered [dp, pp, sharding, sep, mp] like the reference's
``topology.py:195-199`` axis order.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

# axis order mirrors HybridCommunicateGroup (topology.py:195-199)
HYBRID_AXES = ("dp", "pp", "sharding", "sep", "mp")

_state: Dict[str, Optional[Mesh]] = {"mesh": None}


def init_mesh(axes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Create (and install) the global mesh.

    ``axes`` maps axis name -> degree in rank-major order; total must equal
    the device count. Default: one data-parallel axis over every device.
    """
    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {"dp": len(devices)}
    names = tuple(axes.keys())
    sizes = tuple(int(v) for v in axes.values())
    n = int(np.prod(sizes))
    if n != len(devices):
        raise ValueError(
            f"mesh {dict(axes)} needs {n} devices, have {len(devices)}")
    mesh = Mesh(np.array(devices).reshape(sizes), names)
    _state["mesh"] = mesh
    return mesh


def set_mesh(mesh: Mesh) -> None:
    _state["mesh"] = mesh


def get_mesh(auto_init: bool = True) -> Optional[Mesh]:
    if _state["mesh"] is None and auto_init:
        init_mesh()
    return _state["mesh"]


def mesh_initialized() -> bool:
    return _state["mesh"] is not None


def axis_size(name: str) -> int:
    mesh = get_mesh()
    return int(mesh.shape[name])


def axis_degrees() -> Dict[str, int]:
    """Axis name -> degree of the installed mesh, in rank-major order
    (outermost first — the DCN-tolerant end; see spec_layout)."""
    return {k: int(v) for k, v in get_mesh().shape.items()}


def traced_axis_size(name: str) -> int:
    """Degree of mesh axis ``name`` as seen INSIDE a traced
    shard_map/pmap body: prefers ``jax.lax.axis_size`` (the axis bound
    in the trace — correct even for a caller-constructed Mesh that was
    never installed via :func:`init_mesh`), falling back to the
    installed mesh on old jax without the API. The ONE axis-size
    resolution shared by the hierarchical collectives, the compiled
    pipelines, and the collective-matmul kernels."""
    import jax
    if hasattr(jax.lax, "axis_size"):
        return int(jax.lax.axis_size(name))
    return axis_size(name)


def group_size(axes: Sequence[str]) -> int:
    """Number of ranks in the communication group spanned by ``axes``
    (the group-size input to wire-traffic accounting)."""
    mesh = get_mesh()
    n = 1
    for a in axes:
        n *= int(mesh.shape[a])
    return n


def dcn_axes() -> set:
    """Mesh axes mapped onto the data-center network, per the cost
    model's :class:`~paddle2_tpu.observability.cost_model.LinkModel`
    convention (ONE owner of the rule): the ``PADDLE_DCN_AXES`` env
    list, any installed axis whose name contains ``"dcn"``, and the
    dcn axes of the :func:`~paddle2_tpu.distributed.spec_layout.\
hybrid_mesh`-installed layout — the same set its link model prices
    traffic with."""
    from ..observability.cost_model import LinkModel
    link = LinkModel()
    named = set(link.dcn_axes)
    mesh = get_mesh(auto_init=False)
    if mesh is not None:
        named |= {a for a in mesh.axis_names if link.is_dcn(a)}
    from .spec_layout import installed_layout
    layout = installed_layout()
    if layout is not None:
        declared = set(layout.dcn_axes)
        if mesh is not None:
            # a later init_mesh may have replaced the hybrid mesh with
            # different axes — only honor declarations that still name
            # an installed axis
            declared &= set(mesh.axis_names)
        named |= declared
    return named


def world_size() -> int:
    return int(np.prod(list(get_mesh().shape.values())))


def replicated(x: jax.Array) -> jax.Array:
    """Commit an array as fully replicated over the mesh."""
    return jax.device_put(x, NamedSharding(get_mesh(), P()))


def constrain(x: jax.Array, spec: PartitionSpec) -> jax.Array:
    """Sharding annotation that works both eagerly and under tracing.

    Eager: a real device_put (resharding collective). Traced: a GSPMD
    sharding constraint, the pjit idiom.
    """
    sharding = NamedSharding(get_mesh(), spec)
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, sharding)
    return jax.device_put(x, sharding)
