"""Global device mesh — the TPU-native ProcessGroup topology.

The reference builds rank topologies out of NCCL communicators
(``paddle/phi/core/distributed/collective/process_group.h:48``,
``fleet/base/topology.py:189`` HybridCommunicateGroup). On TPU the native
equivalent is a single ``jax.sharding.Mesh`` over all chips whose NAMED AXES
are the communication groups: collectives compile to XLA HLO over an axis
(ICI ring), sub-groups are sub-axes, and hybrid parallelism is an N-D mesh
with axes ordered [dp, pp, sharding, sep, mp] like the reference's
``topology.py:195-199`` axis order.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

# axis order mirrors HybridCommunicateGroup (topology.py:195-199)
HYBRID_AXES = ("dp", "pp", "sharding", "sep", "mp")

_state: Dict[str, Optional[Mesh]] = {"mesh": None}


def init_mesh(axes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Create (and install) the global mesh.

    ``axes`` maps axis name -> degree in rank-major order; total must equal
    the device count. Default: one data-parallel axis over every device.
    """
    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {"dp": len(devices)}
    names = tuple(axes.keys())
    sizes = tuple(int(v) for v in axes.values())
    n = int(np.prod(sizes))
    if n != len(devices):
        raise ValueError(
            f"mesh {dict(axes)} needs {n} devices, have {len(devices)}")
    mesh = Mesh(np.array(devices).reshape(sizes), names)
    _state["mesh"] = mesh
    return mesh


def set_mesh(mesh: Mesh) -> None:
    _state["mesh"] = mesh


def get_mesh(auto_init: bool = True) -> Optional[Mesh]:
    if _state["mesh"] is None and auto_init:
        init_mesh()
    return _state["mesh"]


def mesh_initialized() -> bool:
    return _state["mesh"] is not None


def axis_size(name: str) -> int:
    mesh = get_mesh()
    return int(mesh.shape[name])


def world_size() -> int:
    return int(np.prod(list(get_mesh().shape.values())))


def replicated(x: jax.Array) -> jax.Array:
    """Commit an array as fully replicated over the mesh."""
    return jax.device_put(x, NamedSharding(get_mesh(), P()))


def constrain(x: jax.Array, spec: PartitionSpec) -> jax.Array:
    """Sharding annotation that works both eagerly and under tracing.

    Eager: a real device_put (resharding collective). Traced: a GSPMD
    sharding constraint, the pjit idiom.
    """
    sharding = NamedSharding(get_mesh(), spec)
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, sharding)
    return jax.device_put(x, sharding)
