"""The fault-tolerant expert-parallel MoE training plane.

Single-process stand-ins for N expert hosts driven entirely by the
caller's virtual clock (``now`` arguments) — no wall-clock anywhere, so
every drill on this plane is bit-reproducible. The reliability contract
mirrors the PR 17 parameter-server fleet, applied to MoE experts:

- every expert's weights live on a **primary** and a **follower** host
  (consistent-hash placement, :class:`~.ps.sharding.HashRing`); the
  transactional post-step store commits to the primary and ships a
  full-copy replica to the follower, priced on the fabric between their
  slices;
- a dead host is detected at the next **probe sweep**
  (:meth:`ExpertHostFleet.maybe_probe` — the lazily-anchored cadence of
  ``health.py``), so detection latency is INSIDE the gated MTTR;
- promotion is a placement recomputation: the ring guarantees the dead
  primary's first distinct successor is exactly the current follower,
  so the bytes are already there; only the replacement follower pays a
  full-copy resync (priced per link class);
- ``kill_expert_host`` chaos enters through the same per-op gate as
  every real op (:meth:`ExpertHostFleet._op`), raising the typed
  :class:`ExpertHostFailedError` — a ``TransientStepError`` — so a
  :class:`~.fault_tolerance.reliable.ReliableStep`-wrapped step replays
  BITWISE once the probe sweep heals the placement;
- the router is watched: a per-expert load histogram whose normalized
  entropy stays under the floor for ``window`` consecutive steps raises
  the typed, flight-recorded :class:`RouterCollapseError` (NOT
  transient — retrying a collapsed router wastes the fleet);
- token conservation is EXACT: the dispatch ledger
  (:func:`~..incubate.moe.token_ledger_closes`) must close after every
  step, chaos included.

The all-to-all dispatch/combine is priced from the step's actual
routing decisions (an exact per-pair byte matrix) through
:meth:`CollectiveTraffic.add_all_to_all_matrix`: α dominates at small
per-expert payloads, so the hierarchical slice-bucketing lever is
load-bearing and the flat configuration is required to FAIL the lane's
dispatch budget — the PR 14 discipline.
"""

from __future__ import annotations

import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..observability import metrics
from ..observability.cost_model import (CollectiveTraffic, LinkModel,
                                        sparse_transfer_seconds)
from .fault_tolerance import chaos
from .fault_tolerance.health import HealthReport
from .fault_tolerance.reliable import ReliableStep, TransientStepError
from .ps.client import VirtualClock
from .ps.sharding import HashRing

__all__ = ["MoEPlaneError", "ExpertHostFailedError", "RouterCollapseError",
           "ExpertHost", "ExpertHostFleet", "RouterWatchdog",
           "ExpertParallelMoE", "moe_flight", "params_crc",
           "price_all_to_all"]


def moe_flight(**fields) -> None:
    """One shared emitter for every MoE flight-recorder span
    (``kind="moe"``): host kills, failovers, resyncs, router collapse,
    ledger violations — rendered by flight_doctor's MoE section.
    None-valued fields are dropped; the recorder keeps its
    one-attribute-load no-op when disabled."""
    from .fault_tolerance import flight_recorder
    flight_recorder.record("moe", **{k: v for k, v in fields.items()
                                     if v is not None})


class MoEPlaneError(RuntimeError):
    """Base for expert-parallel plane failures."""


class ExpertHostFailedError(MoEPlaneError, TransientStepError):
    """An expert host died under an op. Transient: the probe sweep
    recomputes the placement (the follower already holds the bytes), so
    a ReliableStep retry after backoff replays the step bitwise."""

    def __init__(self, host: int, expert: int = -1, op: str = "?"):
        self.host, self.expert, self.op = int(host), int(expert), op
        MoEPlaneError.__init__(
            self, f"expert host {host} failed during {op!r}"
            + (f" (expert {expert})" if expert >= 0 else ""))


class RouterCollapseError(MoEPlaneError):
    """The router degenerated: per-expert load entropy stayed under the
    floor for ``window`` consecutive steps. NOT transient — replaying
    the step reproduces the same logits; the fix is a training-recipe
    change (aux-loss weight, z-loss, router LR), so this propagates."""

    def __init__(self, step: int, entropy: float, floor: float,
                 window: int):
        self.step, self.entropy = int(step), float(entropy)
        self.floor, self.window = float(floor), int(window)
        super().__init__(
            f"router collapse at step {step}: normalized load entropy "
            f"{entropy:.4f} < floor {floor:.4f} for {window} "
            f"consecutive steps")


def params_crc(params: Dict[str, np.ndarray]) -> int:
    """Order-independent CRC32 over a named param dict — the
    replica-equality check the fleet ledger audits."""
    crc = 0
    for name in sorted(params):
        crc = zlib.crc32(name.encode(), crc)
        crc = zlib.crc32(np.ascontiguousarray(params[name]).tobytes(),
                         crc)
    return crc


def _params_nbytes(params: Dict[str, np.ndarray]) -> int:
    return int(sum(int(np.asarray(a).nbytes) for a in params.values()))


def price_all_to_all(pair_bytes: np.ndarray, ranks_per_slice: int,
                     link: Optional[LinkModel] = None,
                     hierarchical: bool = False
                     ) -> Tuple[float, Dict[str, int], CollectiveTraffic]:
    """Price ONE routed all-to-all from its exact per-pair byte matrix:
    returns ``(seconds, {"ici": n, "dcn": n} dispatch counts, traffic)``
    so callers can advance the virtual clock, gate α-dominance, and
    merge the entries into a fleet-wide ledger."""
    link = link or LinkModel()
    t = CollectiveTraffic()
    counts = t.add_all_to_all_matrix(pair_bytes, ranks_per_slice,
                                     hierarchical=hierarchical)
    return t.seconds(link), counts, t


class ExpertHost:
    """One modeled host: alive flag + the expert replicas it currently
    holds (primary AND follower roles — the fleet's placement says
    which is which)."""

    def __init__(self, host_id: int):
        self.id = int(host_id)
        self.alive = True
        self.experts: Dict[int, Dict[str, np.ndarray]] = {}
        self.ops = 0


class ExpertHostFleet:
    """N modeled expert hosts serving one MoE layer's expert weights.
    All methods take the caller's virtual ``now``. Hosts are grouped
    into ICI slices of ``hosts_per_slice`` consecutive ids; traffic
    between slices rides the DCN."""

    def __init__(self, num_hosts: int = 4, num_experts: int = 8,
                 hosts_per_slice: int = 2,
                 probe_interval_s: float = 0.02,
                 link: Optional[LinkModel] = None, seed: int = 0):
        if probe_interval_s <= 0:
            raise ValueError(
                f"probe_interval_s must be > 0, got {probe_interval_s}")
        self.ring = HashRing(num_hosts, num_shards=num_experts, seed=seed)
        self.hosts = [ExpertHost(i) for i in range(int(num_hosts))]
        self.num_hosts = int(num_hosts)
        self.num_experts = int(num_experts)
        self.hosts_per_slice = max(1, int(hosts_per_slice))
        self.probe_interval_s = float(probe_interval_s)
        self.link = link or LinkModel()
        self.traffic = CollectiveTraffic()
        self.placement: Dict[int, Tuple[int, Optional[int]]] = {}
        self.events: List[Dict[str, Any]] = []
        self.mttrs: List[float] = []
        self.repair_s = 0.0
        self.resyncs = 0
        self.failovers = 0
        self._next_probe_t: Optional[float] = None
        self._kill_t: Dict[int, float] = {}
        self._handled_failures: set = set()

    # -- placement ------------------------------------------------------
    def _alive_ids(self) -> Tuple[int, ...]:
        return tuple(h.id for h in self.hosts if h.alive)

    def slice_of(self, host_id: int) -> int:
        return int(host_id) // self.hosts_per_slice

    def _link_class(self, a: int, b: int) -> str:
        """Link class of a transfer between two hosts: co-located ⇒
        the PCIe-class host channel (no fabric α), same slice ⇒ ICI,
        cross-slice ⇒ DCN."""
        if a == b:
            return "host"
        return "ici" if self.slice_of(a) == self.slice_of(b) else "dcn"

    def primary_of(self, expert: int) -> int:
        primary, _ = self.placement[int(expert)]
        if primary is None:
            raise MoEPlaneError(f"expert {expert} has no primary")
        return primary

    def worker_of(self, expert: int) -> int:
        """The compute rank an expert's batch is materialized on —
        the fixed round-robin home, independent of where the WEIGHTS
        currently live (failover moves weights, not compute)."""
        return int(expert) % self.num_hosts

    def attach_experts(self,
                       init_params: Dict[int, Dict[str, np.ndarray]]
                       ) -> None:
        """Place primary+follower replicas of every expert's initial
        weights on the ring placement."""
        if self.placement:
            raise MoEPlaneError("experts already attached to this fleet")
        if len(init_params) != self.num_experts:
            raise MoEPlaneError(
                f"expected {self.num_experts} experts, got "
                f"{len(init_params)}")
        self.placement = self.ring.placement(self._alive_ids())
        for e in range(self.num_experts):
            params = {k: np.asarray(v).copy()
                      for k, v in init_params[e].items()}
            primary, follower = self.placement[e]
            for hid in (primary, follower):
                if hid is None:
                    continue
                self.hosts[hid].experts[e] = {
                    k: v.copy() for k, v in params.items()}

    # -- liveness / chaos entry of every op -----------------------------
    def _op(self, hid: int, op: str, expert: int, now: float
            ) -> ExpertHost:
        host = self.hosts[hid]
        host.ops += 1
        if chaos.maybe_kill_expert_host(hid, op=op):
            self.kill_host(hid, now)
        if not host.alive:
            raise ExpertHostFailedError(hid, expert, op)
        return host

    def kill_host(self, hid: int, now: float) -> None:
        host = self.hosts[hid]
        if not host.alive:
            return
        host.alive = False
        self._kill_t[hid] = float(now)
        self.events.append({"event": "host_kill", "host": hid,
                            "t": float(now)})
        moe_flight(event="host_kill", host=hid, t=float(now))

    # -- serving --------------------------------------------------------
    def fetch(self, expert: int, now: float
              ) -> Tuple[Dict[str, np.ndarray], float]:
        """Pull an expert's weights from its primary to its compute
        rank at step start. Returns ``(params copy, modeled seconds)``;
        raises the typed transient when the primary is dead (or chaos
        kills it under this very op)."""
        primary, _ = self.placement[int(expert)]
        if primary is None or not self.hosts[primary].alive:
            raise ExpertHostFailedError(
                -1 if primary is None else primary, expert, "fetch")
        host = self._op(primary, "fetch", expert, now)
        params = {k: v.copy() for k, v in host.experts[expert].items()}
        nbytes = _params_nbytes(params)
        cls = self._link_class(primary, self.worker_of(expert))
        self.traffic.add("moe_fetch", nbytes,
                         axes=("dcn",) if cls == "dcn" else ("ici",),
                         group_size=2)
        seconds = sparse_transfer_seconds(nbytes, cls, link=self.link)
        metrics.inc("moe_expert_fetches_total")
        return params, seconds

    def store_all(self, updates: Dict[int, Dict[str, np.ndarray]],
                  now: float) -> float:
        """TRANSACTIONAL post-step commit of every expert's updated
        weights: phase 1 walks each primary through the per-op
        chaos/liveness gate WITHOUT writing, phase 2 commits primaries
        and ships follower replicas. A host death in phase 1 aborts the
        whole transaction with nothing written, so the ReliableStep
        replay restarts from exactly the pre-step fleet state — the
        property the bitwise-vs-clean-twin gate rests on."""
        staged: List[Tuple[int, int, Optional[int],
                           Dict[str, np.ndarray]]] = []
        seconds = 0.0
        for e in sorted(updates):
            primary, follower = self.placement[e]
            if primary is None or not self.hosts[primary].alive:
                raise ExpertHostFailedError(
                    -1 if primary is None else primary, e, "store")
            self._op(primary, "store", e, now)
            staged.append((e, primary, follower, updates[e]))
        for e, primary, follower, params in staged:
            clean = {k: np.asarray(v).copy() for k, v in params.items()}
            nbytes = _params_nbytes(clean)
            wcls = self._link_class(self.worker_of(e), primary)
            self.traffic.add(
                "moe_store", nbytes,
                axes=("dcn",) if wcls == "dcn" else ("ici",),
                group_size=2)
            seconds += sparse_transfer_seconds(nbytes, wcls,
                                               link=self.link)
            self.hosts[primary].experts[e] = clean
            metrics.inc("moe_expert_stores_total")
            if follower is not None and self.hosts[follower].alive:
                rcls = self._link_class(primary, follower)
                self.traffic.add(
                    "moe_replica", nbytes,
                    axes=("dcn",) if rcls == "dcn" else ("ici",),
                    group_size=2)
                seconds += sparse_transfer_seconds(nbytes, rcls,
                                                   link=self.link)
                self.hosts[follower].experts[e] = {
                    k: v.copy() for k, v in clean.items()}
        return seconds

    # -- probe sweeps / failover ----------------------------------------
    def maybe_probe(self, now: float) -> None:
        """Lazily-anchored probe cadence (the health-prober idiom): the
        first call anchors the sweep clock; each elapsed interval runs
        one sweep. Failover happens HERE, so detection latency is part
        of the gated MTTR."""
        if self._next_probe_t is None:
            self._next_probe_t = float(now) + self.probe_interval_s
            return
        while now >= self._next_probe_t:
            self.probe_now(self._next_probe_t)
            self._next_probe_t += self.probe_interval_s

    def probe_now(self, t: float) -> List[HealthReport]:
        """One sweep: a HealthReport per host; newly-dead hosts get
        their experts failed over (promotion + follower recruit)."""
        reports, newly_dead = [], []
        for host in self.hosts:
            rep = HealthReport(ok=host.alive, probe="moe_liveness",
                               reason="" if host.alive
                               else f"expert host {host.id} unreachable")
            reports.append(rep)
            if not rep.ok and host.id not in self._handled_failures:
                self._handled_failures.add(host.id)
                newly_dead.append(host.id)
                metrics.inc("moe_expert_host_failures_total")
        if newly_dead:
            self._failover(newly_dead, t)
        return reports

    def _failover(self, newly_dead: List[int], t: float) -> None:
        old = dict(self.placement)
        self.placement = self.ring.placement(self._alive_ids())
        for e, (new_p, new_f) in sorted(self.placement.items()):
            old_p, old_f = old[e]
            if new_p != old_p:
                # the ring guarantees the successor is the old
                # follower: the bytes are already on new_p — promotion
                # is a placement recomputation, not a copy
                if e not in self.hosts[new_p].experts:
                    raise MoEPlaneError(
                        f"expert {e}: promoted host {new_p} holds no "
                        f"replica — both replicas lost")
                self.failovers += 1
                metrics.inc("moe_failovers_total")
                if old_p in self._kill_t:
                    self.mttrs.append(float(t) - self._kill_t[old_p])
                self.events.append({"event": "failover", "expert": e,
                                    "old": old_p, "new": new_p,
                                    "t": float(t)})
                moe_flight(event="failover", expert=e, host=new_p,
                           old_host=old_p, t=float(t))
            if new_f is not None \
                    and e not in self.hosts[new_f].experts:
                # recruit: the replacement follower starts empty — a
                # full-copy resync from the (possibly just-promoted)
                # primary, priced on the fabric between their slices
                self.repair_s += self._resync(e, new_p, new_f, t,
                                              reason="recruit")
        for hid in newly_dead:
            self.hosts[hid].experts.clear()

    def _resync(self, expert: int, src: int, dst: int, t: float,
                reason: str) -> float:
        params = {k: v.copy()
                  for k, v in self.hosts[src].experts[expert].items()}
        self.hosts[dst].experts[expert] = params
        nbytes = _params_nbytes(params)
        cls = self._link_class(src, dst)
        self.resyncs += 1
        metrics.inc("moe_resyncs_total", reason=reason)
        self.traffic.add("moe_resync", nbytes,
                         axes=("dcn",) if cls == "dcn" else ("ici",),
                         group_size=2)
        seconds = sparse_transfer_seconds(nbytes, cls, link=self.link)
        self.events.append({"event": "resync", "expert": expert,
                            "reason": reason, "bytes": nbytes,
                            "t": float(t)})
        moe_flight(event="resync", expert=expert, reason=reason,
                   bytes=nbytes, t=float(t))
        return seconds

    def last_mttr_s(self) -> float:
        return max(self.mttrs) if self.mttrs else 0.0

    def quiesce(self, now: float) -> None:
        """Run one forced sweep so anything dead-but-undetected fails
        over before the ledger is audited."""
        self.probe_now(float(now))

    # -- the cross-host expert ledger -----------------------------------
    def ledger(self) -> Dict[str, Any]:
        """Exact bookkeeping at drill end: every expert owned by
        exactly one alive primary, the expert partition covering
        range(num_experts), and every follower CRC-equal to its
        primary."""
        owned: List[int] = []
        one_primary = True
        crc_equal = True
        for e in range(self.num_experts):
            primary, follower = self.placement[e]
            if primary is None or not self.hosts[primary].alive \
                    or e not in self.hosts[primary].experts:
                one_primary = False
                continue
            owned.append(e)
            pp = self.hosts[primary].experts[e]
            if follower is not None and self.hosts[follower].alive:
                fp = self.hosts[follower].experts.get(e)
                if fp is None or params_crc(fp) != params_crc(pp):
                    crc_equal = False
        partition_exact = (sorted(owned)
                           == list(range(self.num_experts)))
        return {"ok": bool(one_primary and partition_exact
                           and crc_equal),
                "one_primary_per_expert": bool(one_primary),
                "expert_partition_exact": bool(partition_exact),
                "replicas_crc_equal": bool(crc_equal),
                "experts": self.num_experts,
                "alive_hosts": list(self._alive_ids())}


class RouterWatchdog:
    """Router-collapse detection on the virtual clock: per-expert load
    histogram → normalized entropy (f64, base ``num_experts``); under
    the floor for ``window`` CONSECUTIVE steps raises the typed
    :class:`RouterCollapseError` before a degenerate gate silently
    wastes the fleet. One healthy step resets the streak."""

    def __init__(self, num_experts: int, entropy_floor: float = 0.35,
                 window: int = 3):
        if not 0.0 <= entropy_floor <= 1.0:
            raise ValueError(
                f"entropy_floor must be in [0, 1], got {entropy_floor}")
        self.num_experts = int(num_experts)
        self.entropy_floor = float(entropy_floor)
        self.window = max(1, int(window))
        self.entropies: List[float] = []
        self._streak = 0

    @staticmethod
    def normalized_entropy(load: np.ndarray) -> float:
        """H(load) / log(E) in float64: 1.0 = perfectly balanced,
        0.0 = every token on one expert. An all-zero histogram (no
        tokens routed at all) is maximal collapse."""
        p = np.asarray(load, np.float64)
        total = p.sum()
        if total <= 0:
            return 0.0
        p = p / total
        nz = p[p > 0]
        h = float(-(nz * np.log(nz)).sum())
        return h / float(np.log(len(p))) if len(p) > 1 else 1.0

    def observe(self, load_per_expert: np.ndarray, now: float,
                step: int) -> float:
        h = self.normalized_entropy(load_per_expert)
        self.entropies.append(h)
        if h < self.entropy_floor:
            self._streak += 1
        else:
            self._streak = 0
        if self._streak >= self.window:
            metrics.inc("moe_router_collapses_total")
            moe_flight(event="router_collapse", step=int(step),
                       entropy=round(h, 6),
                       floor=self.entropy_floor, t=float(now))
            raise RouterCollapseError(step, h, self.entropy_floor,
                                      self.window)
        return h


class ExpertParallelMoE:
    """The expert-parallel training plane: wires a
    :class:`~..incubate.moe.MoELayer` to an :class:`ExpertHostFleet`
    and drives each step through :class:`ReliableStep` on a virtual
    clock.

    One step = fetch every expert's weights from its primary (priced),
    forward/backward/optimizer on the layer (loss = task + aux·w),
    price the routed all-to-all from the step's EXACT dispatch ledger,
    transactionally store the updated experts (primary + follower
    replica), then audit: token-conservation ledger + router watchdog.
    ``ExpertHostFailedError`` anywhere in the step aborts it with
    nothing committed; the injected ``sleep`` advances the virtual
    clock THROUGH the fleet's probe cadence, so backoff is also when
    failover detection happens — MTTR is modeled, not elided."""

    def __init__(self, layer: Any, optimizer: Any,
                 fleet: ExpertHostFleet,
                 link: Optional[LinkModel] = None,
                 aux_weight: float = 0.01,
                 a2a_mode: str = "hierarchical",
                 entropy_floor: float = 0.35, watchdog_window: int = 3,
                 retry_base_s: float = 0.02, max_retries: int = 8,
                 retry_budget: int = 32):
        if a2a_mode not in ("hierarchical", "flat"):
            raise ValueError(f"a2a_mode={a2a_mode!r}")
        self.layer = layer
        self.optimizer = optimizer
        self.fleet = fleet
        self.link = link or fleet.link
        self.aux_weight = float(aux_weight)
        self.a2a_mode = a2a_mode
        # the ledger needs the routing pieces on host — opt the layer in
        self.layer.collect_stats = True
        self.watchdog = RouterWatchdog(layer.num_experts,
                                       entropy_floor=entropy_floor,
                                       window=watchdog_window)
        self.clock = VirtualClock()
        self.reliable = ReliableStep(
            model=layer, optimizer=optimizer, snapshot_every=1,
            max_retries=max_retries, retry_budget=retry_budget,
            base_delay=retry_base_s, max_delay=2.0, check_finite=False,
            sleep=self._sleep)
        self.step_no = 0
        self._last_a2a_s = 0.0
        self.dispatch_seconds: List[float] = []
        self.a2a_counts = {"ici": 0, "dcn": 0}
        self.ledgers_ok: List[bool] = []
        self.last_pair_bytes: Optional[np.ndarray] = None
        fleet.attach_experts({
            e: {k: np.asarray(v.numpy()).copy()
                for k, v in expert.state_dict().items()}
            for e, expert in enumerate(layer.experts)})

    # backoff sleeps advance the virtual clock THROUGH the probe
    # cadence: waiting is when the prober finds the corpse
    def _sleep(self, seconds: float) -> None:
        self.clock.advance(seconds)
        self.fleet.maybe_probe(self.clock.t)

    def train_step(self, x: Any, y: Any) -> Any:
        out = self.reliable.run(self._step_fn, x, y)
        self.step_no += 1
        return out

    def _step_fn(self, x: Any, y: Any) -> Any:
        from ..nn import functional as F
        fleet, layer, clock = self.fleet, self.layer, self.clock
        fleet.maybe_probe(clock.t)
        # fetch: every expert's weights from its current primary
        for e, expert in enumerate(layer.experts):
            params, secs = fleet.fetch(e, clock.t)
            clock.advance(secs)
            expert.set_state_dict(params)
        out = layer(x)
        loss = F.mse_loss(out, y) + layer.aux_loss * self.aux_weight
        loss.backward()
        self.optimizer.step()
        self.optimizer.clear_grad()
        stats = layer.last_stats
        secs, counts = self._price_dispatch(stats)
        clock.advance(secs)
        self._last_a2a_s = secs
        # transactional commit; ExpertHostFailedError in its liveness
        # phase leaves the fleet at pre-step bytes → bitwise replay
        store_s = fleet.store_all({
            e: {k: np.asarray(v.numpy())
                for k, v in expert.state_dict().items()}
            for e, expert in enumerate(layer.experts)}, clock.t)
        clock.advance(store_s)
        self._account(stats, counts)
        return loss

    def _price_dispatch(self, stats: Dict[str, Any]
                        ) -> Tuple[float, Dict[str, int]]:
        """Exact per-pair byte matrix of this step's dispatch+combine:
        token source rank = contiguous split of the flat token batch
        over hosts; destination = the chosen expert's CURRENT primary
        (failover visibly reroutes traffic). Each kept pick pays its
        row both ways (dispatch there, combine back)."""
        idx = np.asarray(stats["idx"])                          # [k, S]
        keep = np.asarray(stats["keep"])                        # [k, S]
        H = self.fleet.num_hosts
        k, S = idx.shape
        row_bytes = float(self.layer.d_model * 4)               # f32 row
        src = (np.arange(S, dtype=np.int64) * H) // S           # [S]
        prim = np.asarray([self.fleet.primary_of(e)
                           for e in range(self.fleet.num_experts)],
                          np.int64)
        pair = np.zeros((H, H), np.float64)
        for j in range(k):
            kj = keep[j]
            dst = prim[idx[j][kj]]
            np.add.at(pair, (src[kj], dst), row_bytes)
            np.add.at(pair, (dst, src[kj]), row_bytes)
        self.last_pair_bytes = pair
        seconds, counts, t = price_all_to_all(
            pair, self.fleet.hosts_per_slice, link=self.link,
            hierarchical=(self.a2a_mode == "hierarchical"))
        self.fleet.traffic.entries.extend(t.entries)
        return seconds, counts

    def _account(self, stats: Dict[str, Any],
                 counts: Dict[str, int]) -> None:
        from ..incubate.moe import token_ledger_closes
        self.dispatch_seconds.append(self._last_a2a_s)
        self.a2a_counts["ici"] += counts["ici"]
        self.a2a_counts["dcn"] += counts["dcn"]
        ok = token_ledger_closes(stats)
        self.ledgers_ok.append(ok)
        if not ok:
            moe_flight(event="ledger_violation", step=self.step_no,
                       t=self.clock.t)
        metrics.inc("moe_steps_total")
        # router health last: a collapse propagates OUT of the step
        # (non-transient), after the ledger has already been audited
        self.watchdog.observe(np.asarray(stats["assigned_per_expert"]),
                              self.clock.t, self.step_no)
