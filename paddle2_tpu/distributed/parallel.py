"""init_parallel_env + DataParallel (python/paddle/distributed/parallel.py:978,219).

TPU-native data parallelism: instead of an EagerReducer bucketing gradients
into NCCL all-reduces (reducer.cc), parameters are committed REPLICATED over
the mesh and the input batch is SHARDED over the 'dp' axis. Every eager op
then executes as an SPMD program; XLA inserts the gradient all-reduce itself
when the weight-grad contraction crosses the sharded batch dim — the GSPMD
equivalent of bucketed allreduce, fused and async-scheduled by the compiler.
"""

from __future__ import annotations

import contextlib
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..framework.tensor import Tensor
from ..nn.layer.layers import Layer
from . import mesh as mesh_mod
from .env import ParallelEnv, get_rank, get_world_size
from .collective import Group, _world_group

P = PartitionSpec

__all__ = ["init_parallel_env", "DataParallel", "ParallelEnv", "get_rank",
           "ParallelMode", "get_backend", "is_available", "gloo_barrier",
           "gloo_init_parallel_env", "gloo_release",
           "get_world_size"]

_initialized = {"flag": False}


class _AliasTensor(Tensor):
    """Placement-changed view of an input tensor: leaf gradient accumulation
    routes back to the user's tensor (x.grad must populate, parallel.py:219
    DataParallel contract)."""

    __slots__ = ("_origin",)

    def _accumulate_grad(self, g):
        self._origin._accumulate_grad(g)


def _maybe_init_jax_distributed() -> bool:
    """Multi-host bootstrap (the reference's TCPStore rendezvous,
    parallel.py:1134 / tcp_store.h:121): when the launcher exported a
    coordinator address, join JAX's coordination service so every
    process's local chips form ONE global device set. Idempotent."""
    import os
    addr = (os.environ.get("JAX_COORDINATOR_ADDRESS")
            or os.environ.get("PADDLE_MASTER"))
    n = int(os.environ.get("JAX_NUM_PROCESSES")
            or os.environ.get("PADDLE_TRAINERS_NUM") or 1)
    if not addr or n <= 1:
        return False
    if getattr(jax.distributed, "is_initialized", lambda: False)():
        return True
    try:  # older jax: probe the global client instead
        from jax._src import distributed as _jd
        if _jd.global_state.client is not None:
            return True
    except Exception:
        pass
    pid = int(os.environ.get("JAX_PROCESS_ID")
              or os.environ.get("PADDLE_TRAINER_ID") or 0)
    jax.distributed.initialize(coordinator_address=addr,
                               num_processes=n, process_id=pid)
    return True


def init_parallel_env(mesh_axes: Optional[dict] = None) -> ParallelEnv:
    """Bring up the parallel environment (parallel.py:978 parity).

    Multi-host: when the launcher exported PADDLE_MASTER /
    JAX_COORDINATOR_ADDRESS, this first joins the JAX coordination
    service (`jax.distributed.initialize` — the TCPStore-rendezvous
    analog), after which jax.devices() spans every host and the global
    mesh covers the whole job. Single-host: the PJRT client already
    knows every chip, so this just installs the global mesh (all chips
    on one 'dp' axis unless ``mesh_axes`` says otherwise).
    """
    _maybe_init_jax_distributed()
    if mesh_axes is not None or not mesh_mod.mesh_initialized():
        mesh_mod.init_mesh(mesh_axes)
    _initialized["flag"] = True
    return ParallelEnv()


def parallel_initialized() -> bool:
    return _initialized["flag"]


class DataParallel(Layer):
    """paddle.DataParallel parity (parallel.py:219).

    Wraps a Layer: parameters/buffers are replicated over the mesh, Tensor
    inputs get their batch dim sharded over the dp axis. Gradient sync is
    performed by XLA (see module docstring) — loss and gradients match the
    single-device run up to reduction order.
    """

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size: int = 25,
                 last_comm_buffer_size: int = 1,
                 find_unused_parameters: bool = False,
                 group: Optional[Group] = None):
        super().__init__()
        if not mesh_mod.mesh_initialized():
            init_parallel_env()
        self._layers = layers
        self._group = group if group is not None else _world_group()
        self._axis = self._group.axes[0]
        self._mesh = mesh_mod.get_mesh()
        self._replicate_state()

    def _replicate_state(self):
        repl = NamedSharding(self._mesh, P())
        for p in self._layers.parameters():
            p._replace_data(jax.device_put(p._data, repl))
        for b in self._layers.buffers():
            if b is not None:
                b._replace_data(jax.device_put(b._data, repl))

    def _shard_batch(self, t: Tensor) -> Tensor:
        n = self._mesh.shape[self._axis]
        if t.ndim == 0 or t.shape[0] % n != 0:
            return t
        spec = P(self._axis, *([None] * (t.ndim - 1)))
        out = _AliasTensor.__new__(_AliasTensor)
        Tensor.__init__(out,
                        jax.device_put(t._data,
                                       NamedSharding(self._mesh, spec)),
                        stop_gradient=t.stop_gradient)
        out._grad_node = t._grad_node
        out._output_index = t._output_index
        out._hooks = t._hooks
        out._origin = t
        return out

    def forward(self, *args, **kwargs):
        args = jax.tree_util.tree_map(
            lambda x: self._shard_batch(x) if isinstance(x, Tensor) else x,
            args, is_leaf=lambda x: isinstance(x, Tensor))
        kwargs = jax.tree_util.tree_map(
            lambda x: self._shard_batch(x) if isinstance(x, Tensor) else x,
            kwargs, is_leaf=lambda x: isinstance(x, Tensor))
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        # grads come out globally averaged already (mean over global batch)
        return loss

    @contextlib.contextmanager
    def no_sync(self):
        # GSPMD fuses grad sync into the backward program; there is no
        # separate allreduce to skip. Accumulate on the sharded grads instead.
        yield

    # -- passthrough ------------------------------------------------------
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        out = self._layers.set_state_dict(state_dict, *args, **kwargs)
        self._replicate_state()
        return out

    set_dict = set_state_dict


class ParallelMode:
    """fleet/base/topology.py ParallelMode constants."""
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


def get_backend() -> str:
    """parallel.py get_backend: the comm backend name. All collectives
    compile to XLA HLO over ICI/DCN here."""
    return "xla"


def is_available() -> bool:
    """distributed.is_available (reference parallel.py)."""
    return True


def gloo_init_parallel_env(rank_id: int, rank_num: int,
                           server_endpoint: str) -> None:
    """Reference gloo bootstrap (CPU barrier service). The coordination
    service behind init_parallel_env covers it; kept callable."""
    init_parallel_env()


def gloo_barrier() -> None:
    from .collective import barrier
    barrier()


def gloo_release() -> None:
    """No gloo store to tear down (coordination service owns lifetime)."""
