"""paddle.distributed.ps — the parameter-server vertical, TPU-native.

The reference scales sparse embedding tables across commodity CPU hosts
with brpc parameter servers: workers ``pull`` rows and ``push`` gradients,
and the server applies a server-side sparse optimizer per touched row
(``paddle/fluid/distributed/ps/table/memory_sparse_table.cc``, update
rules ``sparse_sgd_rule.cc:47,96,211``, dense tables
``memory_dense_table.cc``; Python runtime
``python/paddle/distributed/ps/the_one_ps.py``).

On a TPU pod there are no heterogeneous server hosts — the pod IS the
parameter store. A table here is an array row-sharded over a mesh axis,
resident in HBM:

- ``pull``  = gather. Under jit GSPMD lowers the row lookup on a sharded
  table to the same masked-local-lookup + collective pattern
  ``VocabParallelEmbedding`` uses, riding ICI instead of brpc/NIC.
- ``push``  = SelectedRows-style merge (duplicate ids summed — the
  reference's merge-add before the table update) followed by the sparse
  optimizer rule applied ONLY to touched rows via scatter — one donated
  XLA executable, no host round-trip.
- server-side optimizer state (AdaGrad g2sum, Adam moments and per-row
  beta powers) lives beside the rows with the same sharding.
- frequency-gated entry (the accessor's show-count threshold,
  ``ctr_accessor.cc`` Show/Click): rows pull zeros until their access
  count passes ``entry_threshold``.

Modes: sync is exact. ``geo``/``async`` push-pull have no TPU analog by
design — the hardware's strength is synchronous SPMD; both raise with
the migration path (README "Deliberate omissions" decision record).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from .. import mesh as mesh_mod

P = PartitionSpec

__all__ = ["SparseTable", "DenseTable", "init_server", "run_server",
           "init_worker", "stop_worker", "is_server", "is_worker"]

_RULES = ("naive", "adagrad", "adam")


def _row_spec(num_rows: int, axis: Optional[str]) -> P:
    """Row-shard over the given (or first available) mesh axis when the
    row count divides; otherwise replicate."""
    mesh = mesh_mod.get_mesh()
    if axis is None:
        for name in ("sharding", "dp"):
            if name in mesh.axis_names:
                axis = name
                break
        else:
            axis = mesh.axis_names[0]
    if num_rows % int(mesh.shape[axis]) == 0:
        return P(axis, None)
    return P()


def _place(arr, spec: P):
    return jax.device_put(arr, NamedSharding(mesh_mod.get_mesh(), spec))


def _merge_push(ids, grads, sentinel: int):
    """SelectedRows merge-add: sum gradients of duplicate ids.

    Returns (uids, summed) of the same static length as ``ids``; slots
    beyond the unique count carry ``sentinel`` (dropped by the scatter).
    """
    n = ids.shape[0]
    uids, inv = jnp.unique(ids, return_inverse=True, size=n,
                           fill_value=sentinel)
    summed = jax.ops.segment_sum(grads, inv, num_segments=n)
    return uids, summed


class SparseTable:
    """HBM-resident row-sharded sparse table with a server-side rule.

    Rules (``sparse_sgd_rule.cc``):
      - ``naive``   (:47):  w -= lr * g
      - ``adagrad`` (:96):  w -= lr * g * sqrt(g0 / (g0 + g2sum));
                            g2sum += mean(g^2)   (scalar per row)
      - ``adam``    (:211): per-row moments AND per-row beta powers, so
                            bias correction tracks each row's own update
                            count — the property that makes sparse Adam
                            different from dense Adam.
    Weight bounds clip after every update (BoundValue).
    """

    def __init__(self, num_rows: int, dim: int, rule: str = "adagrad",
                 lr: float = 0.05, initial_range: float = 0.0,
                 initial_g2sum: float = 3e-6,
                 beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8,
                 weight_bounds: Optional[Tuple[float, float]] = None,
                 entry_threshold: int = 0, entry=None,
                 mesh_axis: Optional[str] = None,
                 mode: str = "sync", seed: int = 0):
        if rule not in _RULES:
            raise ValueError(f"rule must be one of {_RULES}, got {rule!r}")
        if entry is not None:
            from ..entry_attr import CountFilterEntry
            if isinstance(entry, CountFilterEntry):
                entry_threshold = entry._count_filter
            else:
                raise NotImplementedError(
                    f"{type(entry).__name__}: probabilistic/show-click "
                    "entry needs server-side sampling state with no "
                    "synchronous-SPMD analog; use CountFilterEntry "
                    "(see entry_attr.py decision record)")
        if mode != "sync":
            raise NotImplementedError(
                f"mode={mode!r}: asynchronous/geo push-pull has no TPU "
                "analog by design — the pod is a synchronous SPMD "
                "machine. Use sync tables (this class) or sharded "
                "nn.Embedding + collective mode; see README 'Deliberate "
                "omissions'.")
        self.num_rows, self.dim, self.rule = int(num_rows), int(dim), rule
        self.lr, self.initial_g2sum = float(lr), float(initial_g2sum)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.bounds = weight_bounds
        self.entry_threshold = int(entry_threshold)
        spec = _row_spec(self.num_rows, mesh_axis)
        if initial_range:
            key = jax.random.PRNGKey(seed)
            w = jax.random.uniform(key, (self.num_rows, self.dim),
                                   jnp.float32, -initial_range,
                                   initial_range)
        else:
            w = jnp.zeros((self.num_rows, self.dim), jnp.float32)
        self.weight = _place(w, spec)
        self._spec = spec
        row0 = P(spec[0]) if len(spec) else P()
        if rule == "adagrad":
            self.g2sum = _place(jnp.zeros((self.num_rows,), jnp.float32),
                                row0)
        elif rule == "adam":
            z = jnp.zeros((self.num_rows, self.dim), jnp.float32)
            self.gsum = _place(z, spec)
            self.g2sum = _place(z, spec)
            # beta powers START at beta (sparse_sgd_rule.cc:260-262) and
            # decay on each touch of that row
            self.beta1_pow = _place(
                jnp.full((self.num_rows,), beta1, jnp.float32), row0)
            self.beta2_pow = _place(
                jnp.full((self.num_rows,), beta2, jnp.float32), row0)
        self.counts = _place(jnp.zeros((self.num_rows,), jnp.int32), row0)

    # -- pull ----------------------------------------------------------
    def pull(self, ids, update_show: bool = True):
        """Gather rows; rows below the entry threshold read as zeros."""
        ids = jnp.asarray(ids, jnp.int32)
        if self.entry_threshold and update_show:
            self.counts = _pull_count(self.counts, ids)
        rows = _pull(self.weight, self.counts, ids,
                     self.entry_threshold)
        return rows

    # -- push ----------------------------------------------------------
    def push(self, ids, grads, scale: float = 1.0):
        """Apply the table's rule to the touched rows (merged over
        duplicate ids). ``scale`` divides the gradient (the reference's
        show-scale hook, sparse_sgd_rule.cc:102)."""
        ids = jnp.asarray(ids, jnp.int32)
        grads = jnp.asarray(grads, jnp.float32)
        if ids.ndim != 1:
            raise ValueError(f"push ids must be 1-D, got shape {ids.shape}")
        if grads.shape != ids.shape + (self.dim,):
            raise ValueError(
                f"push grads shape {grads.shape} != {(ids.shape[0], self.dim)}")
        if ids.shape[0] == 0:
            return
        bounds = self.bounds if self.bounds is not None else (0.0, 0.0)
        if self.rule == "naive":
            self.weight = _push_naive(
                self.weight, ids, grads, self.lr, float(scale),
                self.bounds is not None, *bounds)
        elif self.rule == "adagrad":
            self.weight, self.g2sum = _push_adagrad(
                self.weight, self.g2sum, ids, grads, self.lr,
                self.initial_g2sum, float(scale),
                self.bounds is not None, *bounds)
        else:
            (self.weight, self.gsum, self.g2sum, self.beta1_pow,
             self.beta2_pow) = _push_adam(
                self.weight, self.gsum, self.g2sum, self.beta1_pow,
                self.beta2_pow, ids, grads, self.lr, self.beta1,
                self.beta2, self.epsilon, float(scale),
                self.bounds is not None, *bounds)

    def state_dict(self):
        out = {"weight": self.weight, "counts": self.counts}
        for name in ("g2sum", "gsum", "beta1_pow", "beta2_pow"):
            if hasattr(self, name):
                out[name] = getattr(self, name)
        return out

    def set_state_dict(self, state):
        for k, v in state.items():
            setattr(self, k, _place(jnp.asarray(v),
                                    self._spec if jnp.ndim(v) == 2
                                    else P(self._spec[0])
                                    if len(self._spec) else P()))


def _clip(w, do_bound, lo, hi):
    return jnp.clip(w, lo, hi) if do_bound else w


@functools.partial(jax.jit, donate_argnums=(0,))
def _pull_count(counts, ids):
    return counts.at[ids.reshape(-1)].add(1)


@functools.partial(jax.jit, static_argnums=(3,))
def _pull(weight, counts, ids, threshold):
    rows = jnp.take(weight, ids, axis=0)
    if threshold:
        live = (jnp.take(counts, ids, axis=0) >= threshold)
        rows = rows * live[..., None].astype(rows.dtype)
    return rows


@functools.partial(jax.jit, donate_argnums=(0,),
                   static_argnums=(5, 6, 7))
def _push_naive(weight, ids, grads, lr, scale, do_bound, lo, hi):
    uids, g = _merge_push(ids, grads / scale, weight.shape[0])
    cur = jnp.take(weight, jnp.clip(uids, 0, weight.shape[0] - 1), axis=0)
    new = _clip(cur - lr * g, do_bound, lo, hi)
    return weight.at[uids].set(new, mode="drop")


@functools.partial(jax.jit, donate_argnums=(0, 1),
                   static_argnums=(7, 8, 9))
def _push_adagrad(weight, g2sum, ids, grads, lr, g0, scale,
                  do_bound, lo, hi):
    n_rows = weight.shape[0]
    uids, g = _merge_push(ids, grads / scale, n_rows)
    safe = jnp.clip(uids, 0, n_rows - 1)
    cur_w = jnp.take(weight, safe, axis=0)
    cur_s = jnp.take(g2sum, safe, axis=0)
    new_w = cur_w - lr * g * jnp.sqrt(g0 / (g0 + cur_s))[:, None]
    new_w = _clip(new_w, do_bound, lo, hi)
    new_s = cur_s + jnp.mean(g * g, axis=-1)
    return (weight.at[uids].set(new_w, mode="drop"),
            g2sum.at[uids].set(new_s, mode="drop"))


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4),
                   static_argnums=(11, 12, 13))
def _push_adam(weight, gsum, g2sum, b1p, b2p, ids, grads, lr, b1, b2,
               eps, scale, do_bound, lo, hi):
    n_rows = weight.shape[0]
    uids, g = _merge_push(ids, grads / scale, n_rows)
    safe = jnp.clip(uids, 0, n_rows - 1)
    w = jnp.take(weight, safe, axis=0)
    m = jnp.take(gsum, safe, axis=0)
    v = jnp.take(g2sum, safe, axis=0)
    p1 = jnp.take(b1p, safe, axis=0)
    p2 = jnp.take(b2p, safe, axis=0)
    lr_t = lr * jnp.sqrt(1.0 - p2) / (1.0 - p1)
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * g * g
    w = _clip(w - lr_t[:, None] * (m / (jnp.sqrt(v) + eps)),
              do_bound, lo, hi)
    return (weight.at[uids].set(w, mode="drop"),
            gsum.at[uids].set(m, mode="drop"),
            g2sum.at[uids].set(v, mode="drop"),
            b1p.at[uids].set(p1 * b1, mode="drop"),
            b2p.at[uids].set(p2 * b2, mode="drop"))


class DenseTable:
    """Replicated dense parameter block with a server-side rule
    (``memory_dense_table.cc``: sgd / adam / summary)."""

    def __init__(self, shape, rule: str = "sgd", lr: float = 0.01,
                 beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8, summary_decay: float = 0.999999):
        if rule not in ("sgd", "adam", "summary"):
            raise ValueError(f"unknown dense rule {rule!r}")
        self.rule, self.lr = rule, float(lr)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.summary_decay = summary_decay
        self.value = _place(jnp.zeros(tuple(shape), jnp.float32), P())
        if rule == "adam":
            self.m = jnp.zeros_like(self.value)
            self.v = jnp.zeros_like(self.value)
            self.t = 0

    def pull(self):
        return self.value

    def push(self, grad):
        grad = jnp.asarray(grad, jnp.float32)
        if self.rule == "sgd":
            self.value = self.value - self.lr * grad
        elif self.rule == "summary":
            # summary accumulates pushed statistics with decay
            self.value = self.value * self.summary_decay + grad
        else:
            self.t += 1
            self.m = self.beta1 * self.m + (1 - self.beta1) * grad
            self.v = self.beta2 * self.v + (1 - self.beta2) * grad * grad
            lr_t = self.lr * np.sqrt(1 - self.beta2 ** self.t) \
                / (1 - self.beta1 ** self.t)
            self.value = self.value - lr_t * (
                self.m / (jnp.sqrt(self.v) + self.epsilon))


# -- the_one_ps runtime facade ----------------------------------------
# In the reference, fleet PS mode splits processes into TRAINING_ROLE=
# PSERVER (run_server blocks serving tables) and TRAINER (init_worker
# connects). Single-controller SPMD has no server processes: every host
# runs the same program and the tables live sharded in HBM. The facade
# keeps reference scripts runnable: servers don't exist, so is_server()
# is always False and server entry points are no-ops.

def is_server() -> bool:
    return False


def is_worker() -> bool:
    return True


def init_server(*args, **kwargs) -> None:
    """No-op: tables are mesh-resident (see module docstring)."""


def run_server() -> None:
    """No-op: there is no server process to block in."""


def init_worker(scopes=None) -> None:
    """No-op: every SPMD process is a worker already."""


def stop_worker() -> None:
    """No-op counterpart of init_worker."""
