"""paddle.distributed.ps — the parameter-server vertical, TPU-native.

The reference scales sparse embedding tables across commodity CPU hosts
with brpc parameter servers: workers ``pull`` rows and ``push`` gradients,
and the server applies a server-side sparse optimizer per touched row
(``paddle/fluid/distributed/ps/table/memory_sparse_table.cc``, update
rules ``sparse_sgd_rule.cc:47,96,211``, dense tables
``memory_dense_table.cc``; Python runtime
``python/paddle/distributed/ps/the_one_ps.py``).

On a TPU pod there are no heterogeneous server hosts — the pod IS the
parameter store. A table here is an array row-sharded over a mesh axis,
resident in HBM:

- ``pull``  = gather. Under jit GSPMD lowers the row lookup on a sharded
  table to the same masked-local-lookup + collective pattern
  ``VocabParallelEmbedding`` uses, riding ICI instead of brpc/NIC.
- ``push``  = SelectedRows-style merge (duplicate ids summed — the
  reference's merge-add before the table update) followed by the sparse
  optimizer rule applied ONLY to touched rows via scatter — one donated
  XLA executable, no host round-trip.
- server-side optimizer state (AdaGrad g2sum, Adam moments and per-row
  beta powers) lives beside the rows with the same sharding.
- frequency-gated entry (the accessor's show-count threshold,
  ``ctr_accessor.cc`` Show/Click): rows pull zeros until their access
  count passes ``entry_threshold``.

Modes: sync is exact. ``geo``/``async`` push-pull have no TPU analog by
design — the hardware's strength is synchronous SPMD; both raise with
the migration path (README "Deliberate omissions" decision record).

**The fault-tolerant multi-host plane (ISSUE 18)** lives beside the
single-host table: :class:`ShardedSparseTable` splits rows across N
modeled PS servers by a stable hash ring (:mod:`.sharding`), replicates
every shard primary+follower with CRC-stamped deltas (:mod:`.replica`,
:mod:`.fleet`), retries dead-server pulls/pushes through typed
``TransientStepError`` subclasses (:mod:`.errors`), and serves
bounded-staleness reads while a shard re-forms. Both table classes
route through the SAME jitted kernels (:mod:`.kernels`), which is what
makes the ``staleness=0`` sharded table *step-bitwise* against the
single-host one. The pull/push math here moves unchanged; this module
now merely calls the shared programs.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from .. import mesh as mesh_mod
from . import kernels
from .errors import (PSError, PSReplicaCorruptError, PSServerFailedError,
                     PSTimeoutError, PSWorkerNotInitializedError)
from .sharding import HashRing, stable_hash64
from .replica import ShardState, ShardDelta, ResyncPayload, RULE_ARRAYS
from .fleet import PSServer, PSServerFleet
from .client import ShardedSparseTable, VirtualClock
from . import client as _client

P = PartitionSpec

__all__ = ["SparseTable", "DenseTable", "init_server", "run_server",
           "init_worker", "stop_worker", "is_server", "is_worker",
           "ShardedSparseTable", "VirtualClock", "PSServerFleet",
           "PSServer", "HashRing", "stable_hash64", "ShardState",
           "ShardDelta", "ResyncPayload", "RULE_ARRAYS",
           "PSError", "PSServerFailedError", "PSTimeoutError",
           "PSReplicaCorruptError", "PSWorkerNotInitializedError",
           "kernels"]

_RULES = ("naive", "adagrad", "adam")

# the shared merge program (kept under its historical private name —
# kernels.merge_push IS the old _merge_push, moved so the sharded plane
# can call it too)
_merge_push = kernels.merge_push


def _row_spec(num_rows: int, axis: Optional[str]) -> P:
    """Row-shard over the given (or first available) mesh axis when the
    row count divides; otherwise replicate."""
    mesh = mesh_mod.get_mesh()
    if axis is None:
        for name in ("sharding", "dp"):
            if name in mesh.axis_names:
                axis = name
                break
        else:
            axis = mesh.axis_names[0]
    if num_rows % int(mesh.shape[axis]) == 0:
        return P(axis, None)
    return P()


def _place(arr, spec: P):
    return jax.device_put(arr, NamedSharding(mesh_mod.get_mesh(), spec))


class SparseTable:
    """HBM-resident row-sharded sparse table with a server-side rule.

    Rules (``sparse_sgd_rule.cc``):
      - ``naive``   (:47):  w -= lr * g
      - ``adagrad`` (:96):  w -= lr * g * sqrt(g0 / (g0 + g2sum));
                            g2sum += mean(g^2)   (scalar per row)
      - ``adam``    (:211): per-row moments AND per-row beta powers, so
                            bias correction tracks each row's own update
                            count — the property that makes sparse Adam
                            different from dense Adam.
    Weight bounds clip after every update (BoundValue).
    """

    def __init__(self, num_rows: int, dim: int, rule: str = "adagrad",
                 lr: float = 0.05, initial_range: float = 0.0,
                 initial_g2sum: float = 3e-6,
                 beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8,
                 weight_bounds: Optional[Tuple[float, float]] = None,
                 entry_threshold: int = 0, entry=None,
                 mesh_axis: Optional[str] = None,
                 mode: str = "sync", seed: int = 0):
        if rule not in _RULES:
            raise ValueError(f"rule must be one of {_RULES}, got {rule!r}")
        if entry is not None:
            from ..entry_attr import CountFilterEntry
            if isinstance(entry, CountFilterEntry):
                entry_threshold = entry._count_filter
            else:
                raise NotImplementedError(
                    f"{type(entry).__name__}: probabilistic/show-click "
                    "entry needs server-side sampling state with no "
                    "synchronous-SPMD analog; use CountFilterEntry "
                    "(see entry_attr.py decision record)")
        if mode != "sync":
            raise NotImplementedError(
                f"mode={mode!r}: asynchronous/geo push-pull has no TPU "
                "analog by design — the pod is a synchronous SPMD "
                "machine. Use sync tables (this class) or sharded "
                "nn.Embedding + collective mode; see README 'Deliberate "
                "omissions'.")
        self.num_rows, self.dim, self.rule = int(num_rows), int(dim), rule
        self.lr, self.initial_g2sum = float(lr), float(initial_g2sum)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.bounds = weight_bounds
        self.entry_threshold = int(entry_threshold)
        spec = _row_spec(self.num_rows, mesh_axis)
        if initial_range:
            key = jax.random.PRNGKey(seed)
            w = jax.random.uniform(key, (self.num_rows, self.dim),
                                   jnp.float32, -initial_range,
                                   initial_range)
        else:
            w = jnp.zeros((self.num_rows, self.dim), jnp.float32)
        self.weight = _place(w, spec)
        self._spec = spec
        row0 = P(spec[0]) if len(spec) else P()
        if rule == "adagrad":
            self.g2sum = _place(jnp.zeros((self.num_rows,), jnp.float32),
                                row0)
        elif rule == "adam":
            # distinct allocations: _place is a no-op on an already-
            # placed array, and the donating adam kernel must never see
            # the two moments aliased to one buffer
            self.gsum = _place(
                jnp.zeros((self.num_rows, self.dim), jnp.float32), spec)
            self.g2sum = _place(
                jnp.zeros((self.num_rows, self.dim), jnp.float32), spec)
            # beta powers START at beta (sparse_sgd_rule.cc:260-262) and
            # decay on each touch of that row
            self.beta1_pow = _place(
                jnp.full((self.num_rows,), beta1, jnp.float32), row0)
            self.beta2_pow = _place(
                jnp.full((self.num_rows,), beta2, jnp.float32), row0)
        self.counts = _place(jnp.zeros((self.num_rows,), jnp.int32), row0)

    # -- pull ----------------------------------------------------------
    def pull(self, ids, update_show: bool = True):
        """Gather rows; rows below the entry threshold read as zeros."""
        ids = jnp.asarray(ids, jnp.int32)
        if self.entry_threshold and update_show:
            self.counts = kernels.pull_count(self.counts, ids)
        rows = kernels.pull_rows(self.weight, self.counts, ids,
                                 self.entry_threshold)
        return rows

    # -- push ----------------------------------------------------------
    def push(self, ids, grads, scale: float = 1.0):
        """Apply the table's rule to the touched rows (merged over
        duplicate ids). ``scale`` divides the gradient (the reference's
        show-scale hook, sparse_sgd_rule.cc:102)."""
        ids = jnp.asarray(ids, jnp.int32)
        grads = jnp.asarray(grads, jnp.float32)
        if ids.ndim != 1:
            raise ValueError(f"push ids must be 1-D, got shape {ids.shape}")
        if grads.shape != ids.shape + (self.dim,):
            raise ValueError(
                f"push grads shape {grads.shape} != {(ids.shape[0], self.dim)}")
        if ids.shape[0] == 0:
            return
        uids, g = kernels.merge_scaled(ids, grads, float(scale),
                                       self.num_rows)
        bounds = self.bounds if self.bounds is not None else (0.0, 0.0)
        if self.rule == "naive":
            self.weight = kernels.apply_naive(
                self.weight, uids, g, self.lr,
                self.bounds is not None, *bounds)
        elif self.rule == "adagrad":
            self.weight, self.g2sum = kernels.apply_adagrad(
                self.weight, self.g2sum, uids, g, self.lr,
                self.initial_g2sum, self.bounds is not None, *bounds)
        else:
            (self.weight, self.gsum, self.g2sum, self.beta1_pow,
             self.beta2_pow) = kernels.apply_adam(
                self.weight, self.gsum, self.g2sum, self.beta1_pow,
                self.beta2_pow, uids, g, self.lr, self.beta1,
                self.beta2, self.epsilon,
                self.bounds is not None, *bounds)

    def state_dict(self):
        out = {"weight": self.weight, "counts": self.counts}
        for name in ("g2sum", "gsum", "beta1_pow", "beta2_pow"):
            if hasattr(self, name):
                out[name] = getattr(self, name)
        return out

    def set_state_dict(self, state):
        for k, v in state.items():
            setattr(self, k, _place(jnp.asarray(v),
                                    self._spec if jnp.ndim(v) == 2
                                    else P(self._spec[0])
                                    if len(self._spec) else P()))


class DenseTable:
    """Replicated dense parameter block with a server-side rule
    (``memory_dense_table.cc``: sgd / adam / summary)."""

    def __init__(self, shape, rule: str = "sgd", lr: float = 0.01,
                 beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8, summary_decay: float = 0.999999):
        if rule not in ("sgd", "adam", "summary"):
            raise ValueError(f"unknown dense rule {rule!r}")
        self.rule, self.lr = rule, float(lr)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.summary_decay = summary_decay
        self.value = _place(jnp.zeros(tuple(shape), jnp.float32), P())
        if rule == "adam":
            self.m = jnp.zeros_like(self.value)
            self.v = jnp.zeros_like(self.value)
            self.t = 0

    def pull(self):
        return self.value

    def push(self, grad):
        grad = jnp.asarray(grad, jnp.float32)
        if self.rule == "sgd":
            self.value = self.value - self.lr * grad
        elif self.rule == "summary":
            # summary accumulates pushed statistics with decay
            self.value = self.value * self.summary_decay + grad
        else:
            self.t += 1
            self.m = self.beta1 * self.m + (1 - self.beta1) * grad
            self.v = self.beta2 * self.v + (1 - self.beta2) * grad * grad
            lr_t = self.lr * np.sqrt(1 - self.beta2 ** self.t) \
                / (1 - self.beta1 ** self.t)
            self.value = self.value - lr_t * (
                self.m / (jnp.sqrt(self.v) + self.epsilon))


# -- the_one_ps runtime facade ----------------------------------------
# In the reference, fleet PS mode splits processes into TRAINING_ROLE=
# PSERVER (run_server blocks serving tables) and TRAINER (init_worker
# connects). Single-controller SPMD has no server processes — is_server()
# stays False and every SPMD process is a worker — but the lifecycle is
# no longer a no-op: init_server stores the modeled fleet config,
# run_server marks it serving, and init_worker opens the session that
# ShardedSparseTable requires when constructed without an explicit
# fleet (PSWorkerNotInitializedError otherwise). Reference scripts keep
# running unchanged; new code gets a legible failure instead of a
# silent no-op when it skips the lifecycle.

def is_server() -> bool:
    return False


def is_worker() -> bool:
    return True


def init_server(num_servers: int = 2, num_shards: Optional[int] = None,
                probe_interval_s: float = 0.02, link=None,
                seed: int = 0, **_compat) -> None:
    """Record the modeled PS fleet config. Extra keyword arguments from
    reference scripts (dirnames, fleet descs) are accepted and ignored."""
    _client._LIFECYCLE["server_cfg"] = {
        "num_servers": int(num_servers), "num_shards": num_shards,
        "probe_interval_s": float(probe_interval_s), "link": link,
        "seed": int(seed)}


def run_server() -> None:
    """Mark the modeled fleet as serving (no process blocks — the
    'servers' live inside the same SPMD program)."""
    _client._LIFECYCLE["serving"] = True


def init_worker(scopes=None) -> None:
    """Open the worker session: after this, ShardedSparseTable may be
    constructed without an explicit fleet (it builds one from the
    init_server config)."""
    _client._LIFECYCLE["worker"] = True


def stop_worker() -> None:
    """Close the worker session opened by :func:`init_worker`."""
    _client._LIFECYCLE["worker"] = False
    _client._LIFECYCLE["serving"] = False
    _client._LIFECYCLE["server_cfg"] = None
