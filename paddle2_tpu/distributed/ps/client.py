"""Worker-side client of the sharded PS plane: ``ShardedSparseTable``.

What the single-host :class:`~paddle2_tpu.distributed.ps.SparseTable`
does in one HBM array, this class does against N modeled servers
(:mod:`.fleet`), with the reliability semantics ISSUE 18 asks for:

- **routing** — ids hash to shards (:mod:`.sharding`); pulls gather
  per-shard slices, pushes merge duplicate ids ONCE (the same jitted
  ``merge_scaled`` program the single-host table runs) and scatter the
  merged rows per shard. Traffic is priced per link class: a worker is
  co-located with one server (``host`` class), everything else rides
  the DCN — both through the PR 14 alpha+beta LinkModel.
- **retry/backoff** — a dead primary raises ``PSServerFailedError``,
  a dropped push raises ``PSTimeoutError``; both are
  ``TransientStepError`` subclasses retried through
  ``retry.backoff_delays`` on the VIRTUAL clock, probing the fleet at
  each rung so the sweep that promotes the follower actually runs.
- **bounded staleness** — every fresh pull stamps a per-worker mirror
  with the table version; while a shard is re-forming, reads within
  ``max_staleness`` versions degrade to the mirror (counted in
  ``ps_stale_reads_total`` + the staleness gauge) instead of stalling
  the worker fleet. ``max_staleness=0`` never serves the mirror — the
  transparency mode the bitwise parity gate runs in.
- **follower-read hot-key caching** — a per-worker cache of the
  hottest rows refreshed from FOLLOWER replicas every
  ``hot_cache_refresh`` versions; the ``auto`` policy enables it only
  when the observed key histogram says the saved pull bytes beat the
  refresh bytes (a uniform trace must decline — gated both ways).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ...observability import metrics
from ...observability.cost_model import LinkModel, sparse_transfer_seconds
from ..fault_tolerance import chaos
from ..fault_tolerance.retry import backoff_delays
from .errors import (PSServerFailedError, PSTimeoutError,
                     PSWorkerNotInitializedError)
from .fleet import PSServerFleet, ps_flight
from . import kernels

__all__ = ["VirtualClock", "ShardedSparseTable"]

# module-level lifecycle state, driven by the the_one_ps facade in
# __init__.py (init_server stores the fleet config; init_worker opens
# the session ShardedSparseTable() requires when no fleet is passed)
_LIFECYCLE: Dict[str, Any] = {"worker": False, "serving": False,
                              "server_cfg": None}


def require_worker(what: str) -> None:
    if not _LIFECYCLE["worker"]:
        raise PSWorkerNotInitializedError(what)


class VirtualClock:
    """The drill's deterministic clock: every modeled transfer and
    backoff sleep advances it; nothing reads the wall clock."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def advance(self, dt: float) -> float:
        self.t += float(dt)
        return self.t


class ShardedSparseTable:
    """Sharded, replicated, bounded-staleness sparse table."""

    def __init__(self, num_rows: int, dim: int, rule: str = "adagrad",
                 lr: float = 0.05, initial_range: float = 0.0,
                 initial_g2sum: float = 3e-6,
                 beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8,
                 weight_bounds: Optional[Tuple[float, float]] = None,
                 entry_threshold: int = 0,
                 max_staleness: int = 0,
                 fleet: Optional[PSServerFleet] = None,
                 num_servers: int = 2,
                 num_shards: Optional[int] = None,
                 probe_interval_s: float = 0.02,
                 link: Optional[LinkModel] = None,
                 hot_cache_rows: int = 0,
                 hot_cache_refresh: int = 8,
                 hot_cache_policy: str = "auto",
                 retry_base_s: Optional[float] = None,
                 retry_max_s: Optional[float] = None,
                 retry_attempts: int = 8,
                 rpc_timeout_s: float = 0.002,
                 clock: Optional[VirtualClock] = None,
                 seed: int = 0):
        if hot_cache_policy not in ("auto", "on", "off"):
            raise ValueError(
                f"hot_cache_policy must be auto/on/off, "
                f"got {hot_cache_policy!r}")
        self.num_rows, self.dim, self.rule = int(num_rows), int(dim), rule
        self.lr = float(lr)
        self.entry_threshold = int(entry_threshold)
        self.max_staleness = int(max_staleness)
        self.hot_cache_rows = int(hot_cache_rows)
        self.hot_cache_refresh = int(hot_cache_refresh)
        self.hot_cache_policy = hot_cache_policy
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.clock = clock or VirtualClock()
        if fleet is None:
            require_worker("ShardedSparseTable")
            cfg = dict(_LIFECYCLE["server_cfg"] or {})
            cfg.setdefault("num_servers", num_servers)
            cfg.setdefault("num_shards", num_shards)
            cfg.setdefault("probe_interval_s", probe_interval_s)
            cfg.setdefault("link", link)
            cfg.setdefault("seed", seed)
            fleet = PSServerFleet(**cfg)
        self.fleet = fleet
        self.link = fleet.link
        self.retry_base_s = (retry_base_s if retry_base_s is not None
                             else fleet.probe_interval_s / 4.0)
        self.retry_max_s = (retry_max_s if retry_max_s is not None
                            else fleet.probe_interval_s * 4.0)
        self.retry_attempts = int(retry_attempts)
        # same init program as the single-host table (bitwise parity)
        if initial_range:
            import jax
            import jax.numpy as jnp
            key = jax.random.PRNGKey(seed)
            init_w = np.asarray(jax.random.uniform(
                key, (self.num_rows, self.dim), jnp.float32,
                -initial_range, initial_range))
        else:
            init_w = None
        fleet.attach_table(self.num_rows, self.dim, rule, self.lr,
                           initial_g2sum, beta1, beta2, epsilon,
                           weight_bounds, init_weight=init_w)
        ring = fleet.ring
        self._shard_of = ring.shard_of_rows(np.arange(self.num_rows))
        self._local_of = np.zeros(self.num_rows, np.int64)
        self._shard_rows: Dict[int, int] = {}
        for shard in range(ring.num_shards):
            rows = ring.rows_of_shard(shard, self.num_rows)
            self._local_of[rows] = np.arange(len(rows))
            self._shard_rows[shard] = len(rows)
        self.counts = np.zeros(self.num_rows, np.int64)
        self.version = 0
        # per-worker state (lazily created)
        self._mirror: Dict[int, np.ndarray] = {}
        self._stamps: Dict[int, np.ndarray] = {}
        self._hist: Dict[int, np.ndarray] = {}
        self._hist_held: Dict[int, np.ndarray] = {}
        self._hist_flip: Dict[int, int] = {}
        self._hot: Dict[int, Dict[str, Any]] = {}
        self._cache_on: Dict[int, Optional[bool]] = {}
        # modeled-traffic ledgers (the hot-key gate reads these)
        self.pull_wire_bytes = 0
        self.push_wire_bytes = 0
        self.refresh_wire_bytes = 0
        self.pull_seconds = 0.0
        self.push_seconds = 0.0
        self.stale_reads = 0
        self.retries = 0

    # -- per-worker state ----------------------------------------------
    def _worker(self, w: int) -> int:
        w = int(w)
        if w not in self._mirror:
            self._mirror[w] = np.zeros((self.num_rows, self.dim),
                                       np.float32)
            self._stamps[w] = np.full(self.num_rows, -1, np.int64)
            self._hist[w] = np.zeros(self.num_rows, np.int64)
            self._hist_held[w] = np.zeros(self.num_rows, np.int64)
            self._hist_flip[w] = 0
            self._hot[w] = {"ids": None, "rows": None, "index": None,
                            "at": -1}
            self._cache_on[w] = (True if self.hot_cache_policy == "on"
                                 else False if self.hot_cache_policy == "off"
                                 else None)
        return w

    def _colocated(self, worker: int) -> int:
        return int(worker) % len(self.fleet.servers)

    def _link_class(self, worker: int, server: Optional[int]) -> str:
        return ("host" if server is not None
                and server == self._colocated(worker) else "dcn")

    # -- retry ----------------------------------------------------------
    def _retry(self, fn, first_exc):
        last = first_exc
        for d in backoff_delays(self.retry_base_s, self.retry_max_s,
                                self.retry_attempts, jitter=0.0):
            self.retries += 1
            self.clock.advance(d)
            self.fleet.maybe_probe(self.clock.t)
            try:
                return fn()
            except (PSServerFailedError, PSTimeoutError) as e:
                last = e
        raise last

    # -- pull -----------------------------------------------------------
    def pull(self, ids, worker: int = 0,
             update_show: bool = True) -> np.ndarray:
        """Gather rows for ``ids`` (duplicates allowed). Serving order:
        hot cache (when enabled + fresh) -> primary fetch -> bounded
        stale mirror while the shard re-forms."""
        w = self._worker(worker)
        ids = np.asarray(ids, np.int64).reshape(-1)
        self.fleet.maybe_probe(self.clock.t)
        metrics.inc("ps_pulls_total")
        if self.entry_threshold and update_show:
            # scatter-ADD like the jitted .at[ids].add(1): duplicate ids
            # in one pull tick the show count once each (fancy-index +=
            # would collapse them and break threshold parity)
            np.add.at(self.counts, ids, 1)
        # alternating pulls feed a held-out histogram so the auto-cache
        # decision can estimate its hit rate out-of-sample (picking
        # top-K on the SAME counts it scores against would make even a
        # uniform trace look hot — pure selection bias)
        if self._hist_flip[w] == 0:
            self._hist[w][ids] += 1
        else:
            self._hist_held[w][ids] += 1
        self._hist_flip[w] ^= 1
        out = np.zeros((len(ids), self.dim), np.float32)
        need = np.ones(len(ids), bool)
        if self.hot_cache_rows > 0:
            self._maybe_refresh_cache(w)
            hc = self._hot[w]
            if (self._cache_on[w] and hc["index"] is not None
                    and self.version - hc["at"] <= self.max_staleness):
                cpos = hc["index"][ids]
                hit = cpos >= 0
                out[hit] = hc["rows"][cpos[hit]]
                need[hit] = False
        max_served_age = 0
        for shard in np.unique(self._shard_of[ids[need]]):
            sel = need & (self._shard_of[ids] == shard)
            gids = ids[sel]
            lids = self._local_of[gids]
            rows, age = self._fetch_shard(int(shard), gids, lids, w)
            out[sel] = rows
            max_served_age = max(max_served_age, age)
        metrics.set_gauge("ps_staleness", float(max_served_age))
        if self.entry_threshold:
            live = (self.counts[ids] >= self.entry_threshold)
            out = out * live[:, None].astype(np.float32)
        ps_flight(event="pull", worker=w, rows=int(len(ids)),
                  t=self.clock.t)
        return out

    def _fetch_shard(self, shard: int, gids: np.ndarray,
                     lids: np.ndarray, w: int) -> Tuple[np.ndarray, int]:
        """Fetch one shard's slice from its primary; on a dead primary
        serve the bounded-stale mirror (counted) or block in retry
        until the probe sweep promotes the follower. Returns the rows
        and the served staleness (0 when fresh)."""

        def fetch():
            return self.fleet.serve_pull(shard, lids, self.clock.t)

        try:
            rows = fetch()
        except PSServerFailedError as e:
            stamps = self._stamps[w][gids]
            age = (self.version - int(stamps.min())
                   if len(stamps) and stamps.min() >= 0 else -1)
            if 0 <= age <= self.max_staleness and self.max_staleness > 0:
                self.stale_reads += 1
                metrics.inc("ps_stale_reads_total")
                ps_flight(event="stale_read", shard=shard,
                          server=e.server, worker=w, age=age,
                          t=self.clock.t)
                return self._mirror[w][gids], age
            rows = self._retry(fetch, e)
        payload = len(gids) * (self.dim * 4 + 4)
        primary = self.fleet.placement[shard][0]
        cls = self._link_class(w, primary)
        self.fleet.traffic.add("ps_pull", payload, axes=(cls,))
        seconds = sparse_transfer_seconds(payload, cls, link=self.link)
        self.pull_wire_bytes += payload
        self.pull_seconds += seconds
        self.clock.advance(seconds)
        self._mirror[w][gids] = rows
        self._stamps[w][gids] = self.version
        return rows, 0

    # -- hot-key cache ---------------------------------------------------
    def _maybe_refresh_cache(self, w: int) -> None:
        hc = self._hot[w]
        due = (hc["at"] < 0
               or self.version - hc["at"] >= self.hot_cache_refresh)
        if not due:
            return
        if self._cache_on[w] is None:
            # auto policy: first window only observes; decide at the
            # first boundary with a histogram to read
            if hc["at"] < 0:
                hc["at"] = self.version
                return
            self._cache_on[w] = self._decide(w)
        if not self._cache_on[w]:
            hc["at"] = self.version   # keep the decision point anchored
            return
        top = self._top_rows(w)
        rows = np.zeros((len(top), self.dim), np.float32)
        for shard in np.unique(self._shard_of[top]):
            sel = self._shard_of[top] == shard
            lids = self._local_of[top[sel]]
            primary, follower = self.fleet.placement[int(shard)]
            try:  # follower-read: the refresh never loads the primary
                rows[sel] = self.fleet.serve_pull(
                    int(shard), lids, self.clock.t, role="follower")
                src = follower
            except PSServerFailedError:
                try:
                    rows[sel] = self.fleet.serve_pull(
                        int(shard), lids, self.clock.t)
                    src = primary
                except PSServerFailedError:
                    return  # shard re-forming: keep the old cache,
                            # retry the refresh at the next pull
            payload = int(sel.sum()) * (self.dim * 4 + 4)
            cls = self._link_class(w, src)
            self.fleet.traffic.add("ps_cache_refresh", payload,
                                   axes=(cls,))
            self.refresh_wire_bytes += payload
            self.clock.advance(sparse_transfer_seconds(
                payload, cls, link=self.link))
        index = np.full(self.num_rows, -1, np.int64)
        index[top] = np.arange(len(top))
        hc.update(ids=top, rows=rows, index=index, at=self.version)
        ps_flight(event="cache_refresh", worker=w, rows=int(len(top)),
                  t=self.clock.t)

    def _top_rows(self, w: int,
                  h: Optional[np.ndarray] = None) -> np.ndarray:
        """The hottest ``hot_cache_rows`` ids by observed pull count —
        ties broken by id so the cache contents are deterministic."""
        if h is None:
            h = self._hist[w] + self._hist_held[w]
        order = np.lexsort((np.arange(self.num_rows), -h))
        top = order[:self.hot_cache_rows]
        return np.sort(top[h[top] > 0])

    def _decide(self, w: int) -> bool:
        """Cost-model the cache: expected saved pull bytes per version
        vs refresh bytes per version. The hit rate is estimated
        OUT-OF-SAMPLE — top-K picked on one half of the observed pulls,
        scored on the held-out half — and the margin keeps a break-even
        uniform trace on the DECLINE side."""
        held = self._hist_held[w]
        held_total = int(held.sum())
        total = int(held_total + self._hist[w].sum())
        if total == 0 or held_total == 0:
            return False
        top = self._top_rows(w, h=self._hist[w])
        if len(top) == 0:
            return False
        hit_frac = float(held[top].sum()) / float(held_total)
        versions = max(1, self.version)
        pulled_rows_per_version = float(total) / versions
        row_b = self.dim * 4 + 4
        saved = hit_frac * pulled_rows_per_version * row_b
        refresh = len(top) * row_b / float(self.hot_cache_refresh)
        decision = saved > 1.5 * refresh
        ps_flight(event="cache_decision", worker=w,
                  enabled=bool(decision),
                  hit_frac=round(hit_frac, 6), t=self.clock.t)
        return decision

    def cache_enabled(self, worker: int = 0) -> Optional[bool]:
        return self._cache_on.get(int(worker))

    # -- push -----------------------------------------------------------
    def push(self, ids, grads, worker: int = 0,
             scale: float = 1.0) -> None:
        """Merge duplicate ids once (the shared jitted program), route
        the merged rows per shard, apply on each primary, replicate."""
        import jax.numpy as jnp
        w = self._worker(worker)
        ids = np.asarray(ids, np.int64).reshape(-1) \
            if np.ndim(ids) == 1 else np.asarray(ids)
        if np.ndim(ids) != 1:
            raise ValueError(f"push ids must be 1-D, got shape "
                             f"{np.shape(ids)}")
        grads = np.asarray(grads, np.float32)
        if grads.shape != (len(ids), self.dim):
            raise ValueError(f"push grads shape {grads.shape} != "
                             f"{(len(ids), self.dim)}")
        if len(ids) == 0:
            return
        self.fleet.maybe_probe(self.clock.t)
        metrics.inc("ps_pushes_total")
        uids, g = kernels.merge_scaled(
            jnp.asarray(ids, jnp.int32), jnp.asarray(grads),
            float(scale), self.num_rows)
        uids_np = np.asarray(uids, np.int64)
        g_np = np.asarray(g)
        n = len(uids_np)
        real = uids_np < self.num_rows
        safe = np.clip(uids_np, 0, self.num_rows - 1)

        def send():
            if chaos.maybe_drop_push():
                self.clock.advance(self.rpc_timeout_s)
                raise PSTimeoutError("push", timeout_s=self.rpc_timeout_s)
            for shard in np.unique(self._shard_of[uids_np[real]]):
                sel = real & (self._shard_of[safe] == shard)
                local_full = np.full(n, self._shard_rows[int(shard)],
                                     np.int32)
                local_full[sel] = self._local_of[uids_np[sel]]

                def apply(shard=int(shard), local_full=local_full):
                    return self.fleet.apply_push(
                        shard, local_full, g_np, self.version + 1,
                        self.clock.t)

                try:
                    rep_s = apply()
                except (PSServerFailedError, PSTimeoutError) as e:
                    rep_s = self._retry(apply, e)
                payload = int(sel.sum()) * (self.dim * 4 + 4)
                primary = self.fleet.placement[int(shard)][0]
                cls = self._link_class(w, primary)
                self.fleet.traffic.add("ps_push", payload, axes=(cls,))
                seconds = sparse_transfer_seconds(payload, cls,
                                                  link=self.link)
                self.push_wire_bytes += payload
                self.push_seconds += seconds + rep_s
                self.clock.advance(seconds + rep_s)

        try:
            send()
        except PSTimeoutError as e:
            self._retry(send, e)
        self.version += 1
        ps_flight(event="push", worker=w, rows=int(real.sum()),
                  version=self.version, t=self.clock.t)

    # -- introspection ---------------------------------------------------
    def assembled_weight(self) -> np.ndarray:
        """The full table re-assembled from the shard primaries (the
        parity gate compares this bitwise vs the single-host table)."""
        out = np.zeros((self.num_rows, self.dim), np.float32)
        for shard in range(self.fleet.ring.num_shards):
            st = self.fleet.shard_state(shard, "primary")
            out[st.rows] = st.weight
        return out

    def state_dict(self) -> Dict[str, np.ndarray]:
        from .replica import RULE_ARRAYS
        out: Dict[str, np.ndarray] = {
            "weight": self.assembled_weight(),
            "counts": self.counts.copy()}
        for name in RULE_ARRAYS[self.rule][1:]:
            st0 = self.fleet.shard_state(0, "primary")
            shape = (self.num_rows,) + getattr(st0, name).shape[1:]
            arr = np.zeros(shape, np.float32)
            for shard in range(self.fleet.ring.num_shards):
                st = self.fleet.shard_state(shard, "primary")
                arr[st.rows] = getattr(st, name)
            out[name] = arr
        return out
