"""Typed failures of the parameter-server plane.

``PSServerFailedError`` and ``PSTimeoutError`` subclass
:class:`~paddle2_tpu.distributed.fault_tolerance.TransientStepError` on
purpose: a PS fault inside a training step is transient-by-contract
(the fleet promotes a follower at the next probe sweep; a dropped push
re-sends), so ``ReliableStep`` replays and the client's
``retry.backoff_delays`` loop both compose with it without a special
case. ``PSReplicaCorruptError`` is NOT transient: a CRC-mismatched
delta means the follower's bytes can no longer be trusted and the only
exit is a full-shard resync — retrying the apply would hide divergence.
"""

from __future__ import annotations

from ..fault_tolerance.reliable import TransientStepError

__all__ = ["PSError", "PSServerFailedError", "PSTimeoutError",
           "PSReplicaCorruptError", "PSWorkerNotInitializedError"]


class PSError(RuntimeError):
    """Base of every typed parameter-server failure."""


class PSServerFailedError(PSError, TransientStepError):
    """The shard's primary (or the addressed server) is dead. Retry
    through backoff; the probe sweep promotes the follower."""

    def __init__(self, server: int, shard: int = -1, op: str = "?"):
        self.server, self.shard, self.op = int(server), int(shard), op
        super().__init__(
            f"ps server {server} failed during {op}"
            + (f" (shard {shard})" if shard >= 0 else "")
            + "; retry after the next probe sweep promotes its follower")


class PSTimeoutError(PSError, TransientStepError):
    """An RPC was lost on the wire (modeled ``drop_push`` chaos): the
    client timed out waiting for the ack. Safe to re-send — a dropped
    push never reached the table, so the retry applies exactly once."""

    def __init__(self, op: str, shard: int = -1,
                 timeout_s: float = 0.0):
        self.op, self.shard, self.timeout_s = op, int(shard), timeout_s
        super().__init__(
            f"ps {op} timed out after {timeout_s:.6f}s"
            + (f" (shard {shard})" if shard >= 0 else "") + "; re-send")


class PSReplicaCorruptError(PSError):
    """A follower received a delta whose payload does not match its CRC
    stamp. Terminal for the incremental stream: the follower must drop
    to a full-shard resync from the primary."""

    def __init__(self, shard: int, server: int, expect: int, got: int):
        self.shard, self.server = int(shard), int(server)
        super().__init__(
            f"shard {shard} delta to follower {server}: payload crc "
            f"{got:#010x} != stamped {expect:#010x}; full resync required")


class PSWorkerNotInitializedError(PSError):
    """A worker API was called before ``ps.init_worker()``. The
    reference's the_one_ps trainer has the same precondition; the stub
    used to silently no-op, which hid the missing lifecycle call."""

    def __init__(self, what: str = "worker API"):
        super().__init__(
            f"{what} called before ps.init_worker(). Call "
            "ps.init_server(...) (builds the modeled server fleet), "
            "ps.run_server(), then ps.init_worker() — see README "
            "'Parameter-server recommender'.")
