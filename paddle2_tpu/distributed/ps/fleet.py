"""The modeled PS server fleet: replication, probe sweeps, failover.

Single-process stand-ins for N parameter-server hosts, driven entirely
by the caller's virtual clock (``now`` arguments) — no wall-clock
anywhere, so every drill that runs on this fleet is bit-reproducible.

The reliability contract mirrors the PR 11 serving fleet:

- every shard has a **primary** and a **follower** (consistent-hash
  placement, :mod:`.sharding`); pushes apply to the primary through the
  shared jitted kernels and ship a CRC-stamped delta to the follower
  (:mod:`.replica`);
- a dead server is detected at the next **probe sweep**
  (:meth:`PSServerFleet.maybe_probe`, the ``health.py`` prober idiom:
  lazily anchored cadence, one :class:`HealthReport` per server per
  sweep) — detection latency is INSIDE the gated MTTR;
- promotion is a placement recomputation: the ring guarantees the dead
  primary's first distinct successor is exactly the current follower,
  so the data is already there; only the replacement follower pays a
  full-shard resync (priced on the DCN);
- a CRC-mismatched delta (``corrupt_shard_delta`` chaos) drops the
  follower to the same full-shard resync instead of diverging.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ...observability import metrics
from ...observability.cost_model import (CollectiveTraffic, LinkModel,
                                         sparse_transfer_seconds)
from ..fault_tolerance import chaos
from ..fault_tolerance.health import HealthReport
from .errors import PSError, PSReplicaCorruptError, PSServerFailedError
from .replica import ShardState
from .sharding import HashRing
from . import kernels

__all__ = ["PSServer", "PSServerFleet", "ps_flight"]


def ps_flight(**fields) -> None:
    """One shared emitter for every PS flight-recorder span
    (``kind="ps"``): pull/push/failover/resync with shard + server ids,
    rendered by flight_doctor's PS section. None-valued fields are
    dropped; the recorder keeps its one-attribute-load no-op when
    disabled."""
    from ..fault_tolerance import flight_recorder
    flight_recorder.record("ps", **{k: v for k, v in fields.items()
                                    if v is not None})


class PSServer:
    """One modeled server host: alive flag + the shard replicas it
    currently holds (primary AND follower roles — the fleet's placement
    says which is which)."""

    def __init__(self, server_id: int):
        self.id = int(server_id)
        self.alive = True
        self.shards: Dict[int, ShardState] = {}
        self.ops = 0


class PSServerFleet:
    """N modeled servers serving ONE sharded table (a table builds its
    own fleet; the lifecycle facade hands each table the server-side
    config). All methods take the caller's virtual ``now``."""

    def __init__(self, num_servers: int = 2,
                 num_shards: Optional[int] = None,
                 probe_interval_s: float = 0.02,
                 link: Optional[LinkModel] = None,
                 seed: int = 0):
        if probe_interval_s <= 0:
            raise ValueError(
                f"probe_interval_s must be > 0, got {probe_interval_s}")
        self.ring = HashRing(num_servers, num_shards=num_shards, seed=seed)
        self.servers = [PSServer(i) for i in range(int(num_servers))]
        self.probe_interval_s = float(probe_interval_s)
        self.link = link or LinkModel()
        self.traffic = CollectiveTraffic()
        self.placement: Dict[int, Tuple[int, Optional[int]]] = {}
        self.events: List[Dict[str, Any]] = []
        self.mttrs: List[float] = []
        self.repair_s = 0.0
        self.resyncs = 0
        self.failovers = 0
        self._table: Optional[Dict[str, Any]] = None
        self._next_probe_t: Optional[float] = None
        self._kill_t: Dict[int, float] = {}
        self._handled_failures: set = set()

    # -- table hosting --------------------------------------------------
    def attach_table(self, num_rows: int, dim: int, rule: str,
                     lr: float, initial_g2sum: float,
                     beta1: float, beta2: float, epsilon: float,
                     bounds: Optional[Tuple[float, float]],
                     init_weight: Optional[np.ndarray] = None) -> None:
        """Build primary+follower ShardStates on the ring placement.
        ``init_weight`` is the FULL (num_rows, dim) initial table (the
        client computes it with the same PRNG as the single-host twin),
        sliced per shard here so staleness-0 parity starts bitwise."""
        if self._table is not None:
            raise PSError("this modeled fleet already hosts a table; "
                          "build one fleet per ShardedSparseTable")
        self._table = {
            "num_rows": int(num_rows), "dim": int(dim), "rule": rule,
            "lr": float(lr), "g0": float(initial_g2sum),
            "beta1": float(beta1), "beta2": float(beta2),
            "eps": float(epsilon), "bounds": bounds,
        }
        self.placement = self.ring.placement(self._alive_ids())
        for shard in range(self.ring.num_shards):
            rows = self.ring.rows_of_shard(shard, num_rows)
            init = (None if init_weight is None
                    else np.asarray(init_weight, np.float32)[rows])
            primary, follower = self.placement[shard]
            for sid in (primary, follower):
                if sid is None:
                    continue
                self.servers[sid].shards[shard] = ShardState(
                    shard, rows, dim, rule, beta1=beta1, beta2=beta2,
                    init_weight=init)

    @property
    def table(self) -> Dict[str, Any]:
        if self._table is None:
            raise PSError("no table attached to this fleet")
        return self._table

    def _alive_ids(self) -> Tuple[int, ...]:
        return tuple(s.id for s in self.servers if s.alive)

    def shard_state(self, shard: int, role: str = "primary") -> ShardState:
        primary, follower = self.placement[shard]
        sid = primary if role == "primary" else follower
        if sid is None:
            raise PSServerFailedError(-1, shard, f"{role} lookup")
        return self.servers[sid].shards[shard]

    # -- liveness / chaos entry of every op -----------------------------
    def _op(self, sid: int, op: str, shard: int, now: float) -> PSServer:
        srv = self.servers[sid]
        srv.ops += 1
        if chaos.maybe_kill_ps_server(sid, op=op):
            self.kill_server(sid, now)
        if not srv.alive:
            raise PSServerFailedError(sid, shard, op)
        return srv

    def kill_server(self, sid: int, now: float) -> None:
        srv = self.servers[sid]
        if not srv.alive:
            return
        srv.alive = False
        self._kill_t[sid] = float(now)
        self.events.append({"event": "server_kill", "server": sid,
                            "t": float(now)})
        ps_flight(event="server_kill", server=sid, t=float(now))

    # -- serving --------------------------------------------------------
    def serve_pull(self, shard: int, local_ids: np.ndarray,
                   now: float, role: str = "primary") -> np.ndarray:
        """Gather weight rows from the shard's primary (or follower for
        hot-key cache refreshes). Raises PSServerFailedError when the
        addressed replica's server is dead."""
        primary, follower = self.placement[shard]
        sid = primary if role == "primary" else follower
        if sid is None or not self.servers[sid].alive:
            raise PSServerFailedError(-1 if sid is None else sid,
                                      shard, f"pull[{role}]")
        srv = self._op(sid, f"pull[{role}]", shard, now)
        st = srv.shards[shard]
        return st.weight[np.asarray(local_ids, np.int64)]

    def apply_push(self, shard: int, local_uids: np.ndarray,
                   merged_g: np.ndarray, version: int,
                   now: float) -> float:
        """Apply pre-merged gradient rows to the shard primary through
        the SHARED jitted kernels, then ship the CRC-stamped delta to
        the follower. ``local_uids`` has the client's full static merge
        length; non-owned slots carry the shard's local sentinel
        (``num_rows`` of the shard) and are dropped by the scatter.
        Returns the modeled replication seconds (delta over the DCN)."""
        import jax.numpy as jnp
        primary, follower = self.placement[shard]
        if primary is None or not self.servers[primary].alive:
            raise PSServerFailedError(
                -1 if primary is None else primary, shard, "push")
        srv = self._op(primary, "push", shard, now)
        st = srv.shards[shard]
        cfg = self.table
        bounds = cfg["bounds"] if cfg["bounds"] is not None else (0.0, 0.0)
        bounded = cfg["bounds"] is not None
        uids = jnp.asarray(np.asarray(local_uids, np.int32))
        g = jnp.asarray(np.asarray(merged_g, np.float32))
        if cfg["rule"] == "naive":
            st.weight[...] = np.asarray(kernels.apply_naive(
                jnp.asarray(st.weight), uids, g, cfg["lr"],
                bounded, *bounds))
        elif cfg["rule"] == "adagrad":
            w, s = kernels.apply_adagrad(
                jnp.asarray(st.weight), jnp.asarray(st.g2sum), uids, g,
                cfg["lr"], cfg["g0"], bounded, *bounds)
            st.weight[...] = np.asarray(w)
            st.g2sum[...] = np.asarray(s)
        else:
            w, m, v, p1, p2 = kernels.apply_adam(
                jnp.asarray(st.weight), jnp.asarray(st.gsum),
                jnp.asarray(st.g2sum), jnp.asarray(st.beta1_pow),
                jnp.asarray(st.beta2_pow), uids, g, cfg["lr"],
                cfg["beta1"], cfg["beta2"], cfg["eps"], bounded, *bounds)
            st.weight[...] = np.asarray(w)
            st.gsum[...] = np.asarray(m)
            st.g2sum[...] = np.asarray(v)
            st.beta1_pow[...] = np.asarray(p1)
            st.beta2_pow[...] = np.asarray(p2)
        st.version = int(version)
        touched = np.asarray(local_uids, np.int64)
        touched = touched[touched < st.num_rows]
        return self._replicate(shard, st, touched, now)

    def _replicate(self, shard: int, primary_state: ShardState,
                   touched: np.ndarray, now: float) -> float:
        primary, follower = self.placement[shard]
        if follower is None or not self.servers[follower].alive:
            return 0.0
        delta = primary_state.make_delta(touched)
        if chaos.maybe_corrupt_shard_delta(delta.payload):
            ps_flight(event="delta_corrupt", shard=shard,
                      server=follower, t=float(now))
        self.traffic.add("ps_delta", delta.nbytes, axes=("dcn",))
        seconds = sparse_transfer_seconds(delta.nbytes, "dcn",
                                          link=self.link)
        fst = self.servers[follower].shards[shard]
        try:
            fst.apply_delta(delta, server=follower)
        except PSReplicaCorruptError:
            # bytes can't be trusted any more: full-shard resync, never
            # silent divergence
            seconds += self._resync(shard, fst, now, reason="corrupt_delta")
        return seconds

    def _resync(self, shard: int, follower_state: ShardState,
                now: float, reason: str) -> float:
        primary_state = self.shard_state(shard, "primary")
        rp = primary_state.make_resync()
        follower_state.load_resync(rp)
        self.resyncs += 1
        metrics.inc("ps_resyncs_total", reason=reason)
        self.traffic.add("ps_resync", rp.nbytes, axes=("dcn",))
        seconds = sparse_transfer_seconds(rp.nbytes, "dcn", link=self.link)
        self.events.append({"event": "resync", "shard": shard,
                            "reason": reason, "bytes": rp.nbytes,
                            "t": float(now)})
        ps_flight(event="resync", shard=shard, reason=reason,
                  bytes=rp.nbytes, t=float(now))
        return seconds

    # -- probe sweeps / failover ----------------------------------------
    def maybe_probe(self, now: float) -> None:
        """Lazily-anchored probe cadence (the EngineFailoverRouter /
        health prober idiom): the first call anchors the sweep clock;
        each elapsed interval runs one sweep. Failover happens HERE, so
        detection latency is part of the gated MTTR."""
        if self._next_probe_t is None:
            self._next_probe_t = float(now) + self.probe_interval_s
            return
        while now >= self._next_probe_t:
            self.probe_now(self._next_probe_t)
            self._next_probe_t += self.probe_interval_s

    def probe_now(self, t: float) -> List[HealthReport]:
        """One sweep: a HealthReport per server; newly-dead servers get
        their shards failed over (promotion + follower recruit)."""
        reports, newly_dead = [], []
        for srv in self.servers:
            rep = HealthReport(ok=srv.alive, probe="ps_liveness",
                               reason="" if srv.alive
                               else f"server {srv.id} unreachable")
            reports.append(rep)
            if not rep.ok and srv.id not in self._handled_failures:
                self._handled_failures.add(srv.id)
                newly_dead.append(srv.id)
                metrics.inc("ps_server_failures_total")
        if newly_dead:
            self._failover(newly_dead, t)
        return reports

    def _failover(self, newly_dead: List[int], t: float) -> None:
        old = dict(self.placement)
        self.placement = self.ring.placement(self._alive_ids())
        for shard, (new_p, new_f) in sorted(self.placement.items()):
            old_p, old_f = old[shard]
            if new_p != old_p:
                # the ring guarantees the successor is the old follower:
                # the data is already on new_p — promotion is placement
                if shard not in self.servers[new_p].shards:
                    raise PSError(
                        f"shard {shard}: promoted server {new_p} holds "
                        f"no replica — both replicas lost")
                self.failovers += 1
                metrics.inc("ps_failovers_total")
                if old_p in self._kill_t:
                    self.mttrs.append(float(t) - self._kill_t[old_p])
                self.events.append({"event": "failover", "shard": shard,
                                    "old": old_p, "new": new_p,
                                    "t": float(t)})
                ps_flight(event="failover", shard=shard, server=new_p,
                          old_server=old_p, t=float(t))
            if new_f is not None and shard not in self.servers[new_f].shards:
                # recruit: the replacement follower starts empty — full
                # resync from the (possibly just-promoted) primary
                rows = self.servers[new_p].shards[shard].rows
                cfg = self.table
                self.servers[new_f].shards[shard] = ShardState(
                    shard, rows, cfg["dim"], cfg["rule"],
                    beta1=cfg["beta1"], beta2=cfg["beta2"])
                self.repair_s += self._resync(
                    shard, self.servers[new_f].shards[shard], t,
                    reason="recruit")
        for sid in newly_dead:
            self.servers[sid].shards.clear()

    def last_mttr_s(self) -> float:
        return max(self.mttrs) if self.mttrs else 0.0

    def quiesce(self, now: float) -> None:
        """Run one forced sweep so anything dead-but-undetected fails
        over before the ledger is audited."""
        self.probe_now(float(now))

    # -- the cross-shard row ledger -------------------------------------
    def ledger(self) -> Dict[str, Any]:
        """Exact bookkeeping at drill end: every row owned by exactly
        one alive primary, the row partition covering range(num_rows)
        with no overlap, and every follower CRC-equal to its primary."""
        cfg = self.table
        rows_seen: List[np.ndarray] = []
        one_primary = True
        crc_equal = True
        for shard in range(self.ring.num_shards):
            primary, follower = self.placement[shard]
            if primary is None or not self.servers[primary].alive \
                    or shard not in self.servers[primary].shards:
                one_primary = False
                continue
            pst = self.servers[primary].shards[shard]
            rows_seen.append(pst.rows)
            if follower is not None and self.servers[follower].alive:
                fst = self.servers[follower].shards.get(shard)
                if fst is None or fst.crc() != pst.crc():
                    crc_equal = False
        allr = (np.concatenate(rows_seen) if rows_seen
                else np.zeros((0,), np.int64))
        partition_exact = (len(allr) == cfg["num_rows"]
                           and len(np.unique(allr)) == len(allr)
                           and bool(np.array_equal(
                               np.sort(allr),
                               np.arange(cfg["num_rows"], dtype=np.int64))))
        return {"ok": bool(one_primary and partition_exact and crc_equal),
                "one_primary_per_row": bool(one_primary),
                "row_partition_exact": bool(partition_exact),
                "replicas_crc_equal": bool(crc_equal),
                "shards": self.ring.num_shards,
                "alive_servers": list(self._alive_ids())}
