"""Shared jitted pull / merge / apply kernels for the PS tables.

The single-host :class:`~paddle2_tpu.distributed.ps.SparseTable` and the
sharded plane (:mod:`.fleet` / :mod:`.client`) both route through the
programs in this module. That is a correctness requirement, not a
convenience: the ISSUE 18 transparency gate says a ``staleness=0``
sharded table must be *step-bitwise* against the single-host table, and
float bitwise equality only survives when every update runs the exact
same compiled program shape. The split is therefore:

- :func:`merge_scaled` — ONE client-side SelectedRows merge (duplicate
  ids summed, gradient divided by the show-scale) producing static-length
  ``(uids, summed)`` arrays with a sentinel fill. Both paths merge once,
  at the full batch length.
- :func:`apply_naive` / :func:`apply_adagrad` / :func:`apply_adam` —
  the server-side rule applied to pre-merged rows. The sharded plane
  passes the SAME static-length merged arrays to every shard (non-owned
  slots carry the shard's local sentinel and are dropped by the
  ``mode="drop"`` scatter), so each owned row's arithmetic is the same
  per-row program in both worlds; only the gather/scatter endpoints
  (full table vs shard slice) differ, and those move bytes exactly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _clip(w, do_bound, lo, hi):
    return jnp.clip(w, lo, hi) if do_bound else w


@functools.partial(jax.jit, donate_argnums=(0,))
def pull_count(counts, ids):
    return counts.at[ids.reshape(-1)].add(1)


@functools.partial(jax.jit, static_argnums=(3,))
def pull_rows(weight, counts, ids, threshold):
    rows = jnp.take(weight, ids, axis=0)
    if threshold:
        live = (jnp.take(counts, ids, axis=0) >= threshold)
        rows = rows * live[..., None].astype(rows.dtype)
    return rows


def merge_push(ids, grads, sentinel: int):
    """SelectedRows merge-add: sum gradients of duplicate ids.

    Returns (uids, summed) of the same static length as ``ids``; slots
    beyond the unique count carry ``sentinel`` (dropped by the scatter).
    """
    n = ids.shape[0]
    uids, inv = jnp.unique(ids, return_inverse=True, size=n,
                           fill_value=sentinel)
    summed = jax.ops.segment_sum(grads, inv, num_segments=n)
    return uids, summed


@functools.partial(jax.jit, static_argnums=(3,))
def merge_scaled(ids, grads, scale, sentinel):
    """The client half of a push: show-scale division + duplicate-id
    merge, jitted standalone so the sharded plane can merge ONCE and
    route the same merged arrays to every shard."""
    return merge_push(ids, grads / scale, sentinel)


@functools.partial(jax.jit, donate_argnums=(0,),
                   static_argnums=(4, 5, 6))
def apply_naive(weight, uids, g, lr, do_bound, lo, hi):
    cur = jnp.take(weight, jnp.clip(uids, 0, weight.shape[0] - 1), axis=0)
    new = _clip(cur - lr * g, do_bound, lo, hi)
    return weight.at[uids].set(new, mode="drop")


@functools.partial(jax.jit, donate_argnums=(0, 1),
                   static_argnums=(6, 7, 8))
def apply_adagrad(weight, g2sum, uids, g, lr, g0, do_bound, lo, hi):
    n_rows = weight.shape[0]
    safe = jnp.clip(uids, 0, n_rows - 1)
    cur_w = jnp.take(weight, safe, axis=0)
    cur_s = jnp.take(g2sum, safe, axis=0)
    new_w = cur_w - lr * g * jnp.sqrt(g0 / (g0 + cur_s))[:, None]
    new_w = _clip(new_w, do_bound, lo, hi)
    new_s = cur_s + jnp.mean(g * g, axis=-1)
    return (weight.at[uids].set(new_w, mode="drop"),
            g2sum.at[uids].set(new_s, mode="drop"))


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4),
                   static_argnums=(11, 12, 13))
def apply_adam(weight, gsum, g2sum, b1p, b2p, uids, g, lr, b1, b2,
               eps, do_bound, lo, hi):
    n_rows = weight.shape[0]
    safe = jnp.clip(uids, 0, n_rows - 1)
    w = jnp.take(weight, safe, axis=0)
    m = jnp.take(gsum, safe, axis=0)
    v = jnp.take(g2sum, safe, axis=0)
    p1 = jnp.take(b1p, safe, axis=0)
    p2 = jnp.take(b2p, safe, axis=0)
    lr_t = lr * jnp.sqrt(1.0 - p2) / (1.0 - p1)
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * g * g
    w = _clip(w - lr_t[:, None] * (m / (jnp.sqrt(v) + eps)),
              do_bound, lo, hi)
    return (weight.at[uids].set(w, mode="drop"),
            gsum.at[uids].set(m, mode="drop"),
            g2sum.at[uids].set(v, mode="drop"),
            b1p.at[uids].set(p1 * b1, mode="drop"),
            b2p.at[uids].set(p2 * b2, mode="drop"))


__all__ = ["pull_count", "pull_rows", "merge_push", "merge_scaled",
           "apply_naive", "apply_adagrad", "apply_adam"]
