"""Shard replica state + the CRC-stamped delta/resync byte protocol.

The replication discipline is the PR 15 spill-tier one: **bytes, never
trust** — a delta ships the touched rows' raw float bytes (weights AND
optimizer state, so a promoted follower resumes the rule mid-stream
bitwise) plus the touched local ids, all covered by one CRC32 stamp.
The follower verifies the stamp before applying; a mismatch raises
:class:`~.errors.PSReplicaCorruptError` and the fleet drops that
follower to a full-shard resync instead of letting it silently diverge.
Resync payloads carry the whole shard under the same stamp.

State lives as host numpy arrays: a shard is a modeled remote server,
so its arrays are the serialization substrate — ``tobytes()`` IS the
wire format, and two replicas are equal iff their payload CRCs are.
The update rule itself never runs on these arrays directly; the fleet
round-trips through the shared jitted kernels (:mod:`.kernels`) so the
math is bit-identical to the single-host table.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

from .errors import PSReplicaCorruptError

__all__ = ["ShardState", "ShardDelta", "ResyncPayload",
           "RULE_ARRAYS", "crc32"]

# serialization order per rule — fixed, so payload layout is stable
RULE_ARRAYS: Dict[str, Tuple[str, ...]] = {
    "naive": ("weight",),
    "adagrad": ("weight", "g2sum"),
    "adam": ("weight", "gsum", "g2sum", "beta1_pow", "beta2_pow"),
}


def crc32(payload: bytes) -> int:
    return zlib.crc32(payload) & 0xFFFFFFFF


@dataclass
class ShardDelta:
    """Touched rows of one push, as shipped primary -> follower."""
    shard: int
    version: int
    local_ids: bytes        # int32 row indices within the shard
    payload: bytearray      # concatenated per-array row bytes
    crc: int                # stamp over local_ids + payload AT SHIP TIME

    @property
    def nbytes(self) -> int:
        return len(self.local_ids) + len(self.payload) + 4


@dataclass
class ResyncPayload:
    """The whole shard, CRC-stamped — recruit and corruption recovery."""
    shard: int
    version: int
    payload: bytes
    crc: int

    @property
    def nbytes(self) -> int:
        return len(self.payload) + 4


class ShardState:
    """One replica of one shard: the shard's rows (sorted global ids)
    plus per-rule arrays, dimensioned ``(rows, dim)`` / ``(rows,)``."""

    def __init__(self, shard: int, rows: np.ndarray, dim: int,
                 rule: str, beta1: float = 0.9, beta2: float = 0.999,
                 init_weight: Optional[np.ndarray] = None):
        if rule not in RULE_ARRAYS:
            raise ValueError(f"unknown rule {rule!r}")
        self.shard = int(shard)
        self.rows = np.asarray(rows, np.int64).reshape(-1)
        self.dim = int(dim)
        self.rule = rule
        n = len(self.rows)
        self.weight = (np.array(init_weight, np.float32, copy=True)
                       if init_weight is not None
                       else np.zeros((n, self.dim), np.float32))
        if rule == "adagrad":
            self.g2sum = np.zeros((n,), np.float32)
        elif rule == "adam":
            self.gsum = np.zeros((n, self.dim), np.float32)
            self.g2sum = np.zeros((n, self.dim), np.float32)
            self.beta1_pow = np.full((n,), beta1, np.float32)
            self.beta2_pow = np.full((n,), beta2, np.float32)
        self.version = 0

    # -- introspection --------------------------------------------------
    @property
    def num_rows(self) -> int:
        return len(self.rows)

    def arrays(self) -> Tuple[np.ndarray, ...]:
        return tuple(getattr(self, name) for name in RULE_ARRAYS[self.rule])

    # -- delta protocol -------------------------------------------------
    def make_delta(self, local_ids: np.ndarray) -> ShardDelta:
        """Serialize the given rows of every rule array (ship-side)."""
        lid = np.asarray(local_ids, np.int32).reshape(-1)
        ids_b = lid.tobytes()
        payload = bytearray()
        for arr in self.arrays():
            payload += arr[lid].tobytes()
        return ShardDelta(self.shard, self.version, ids_b, payload,
                          crc32(ids_b + bytes(payload)))

    def apply_delta(self, delta: ShardDelta, server: int = -1) -> int:
        """Verify the CRC stamp, then overwrite the named rows. Returns
        the number of rows applied; raises PSReplicaCorruptError on a
        stamp mismatch (the corrupt-delta chaos path)."""
        got = crc32(delta.local_ids + bytes(delta.payload))
        if got != delta.crc:
            raise PSReplicaCorruptError(delta.shard, server,
                                        delta.crc, got)
        lid = np.frombuffer(delta.local_ids, np.int32)
        buf = bytes(delta.payload)
        off = 0
        for name, arr in zip(RULE_ARRAYS[self.rule], self.arrays()):
            per_row = arr[0:1].nbytes if arr.ndim > 1 else arr.dtype.itemsize
            size = per_row * len(lid)
            chunk = np.frombuffer(buf[off:off + size], arr.dtype)
            arr[lid] = chunk.reshape((len(lid),) + arr.shape[1:])
            off += size
        self.version = delta.version
        return len(lid)

    # -- full-shard resync ----------------------------------------------
    def full_payload(self) -> bytes:
        return b"".join(arr.tobytes() for arr in self.arrays())

    def make_resync(self) -> ResyncPayload:
        p = self.full_payload()
        return ResyncPayload(self.shard, self.version, p, crc32(p))

    def load_resync(self, rp: ResyncPayload, server: int = -1) -> None:
        got = crc32(rp.payload)
        if got != rp.crc:
            raise PSReplicaCorruptError(rp.shard, server, rp.crc, got)
        off = 0
        for arr in self.arrays():
            size = arr.nbytes
            chunk = np.frombuffer(rp.payload[off:off + size], arr.dtype)
            arr[...] = chunk.reshape(arr.shape)
            off += size
        self.version = rp.version

    def crc(self) -> int:
        """Replica identity: CRC over the full payload — two replicas
        of a shard are in sync iff their crcs match (the ledger check)."""
        return crc32(self.full_payload())
