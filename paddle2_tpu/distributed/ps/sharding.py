"""Stable hash ring: rows -> shards -> (primary, follower) servers.

Two layers, both pure functions of the configuration (never of arrival
order), so every worker computes the same routing without coordination:

1. **row -> shard** is a fixed hash (:func:`stable_hash64` mod
   ``num_shards``) — it NEVER changes, so the cross-shard row ledger
   ("every row owned by exactly one primary") is closed by construction
   and auditable by re-hashing.
2. **shard -> servers** is consistent hashing: each server projects
   ``vnodes`` points onto a 64-bit ring; a shard's primary is the first
   *alive* server clockwise from the shard's own point, its follower
   the next *distinct* alive server. The property the failover plane
   leans on: when a server dies, the first distinct successor — exactly
   the shard's current follower — becomes the new primary, so promotion
   is a placement recomputation, not a data move; only the recruited
   replacement follower needs a resync. Shards whose primary survives
   keep their placement bit-for-bit (minimal disruption).

Python's builtin ``hash`` is process-seeded (PYTHONHASHSEED) and would
break cross-run determinism, hence the explicit splitmix64.
"""

from __future__ import annotations

import bisect
from typing import Dict, FrozenSet, Iterable, List, Optional, Tuple

import numpy as np

__all__ = ["stable_hash64", "HashRing"]

_MASK = (1 << 64) - 1


def stable_hash64(x: int, seed: int = 0) -> int:
    """splitmix64 of ``x`` (salted by ``seed``): deterministic across
    processes and runs, well-mixed enough that row->shard assignment is
    near-uniform even for dense integer id ranges."""
    z = (int(x) + 0x9E3779B97F4A7C15 * (int(seed) + 1)) & _MASK
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK
    return z ^ (z >> 31)


class HashRing:
    """Consistent-hash placement of ``num_shards`` shards over
    ``num_servers`` modeled PS servers, with one follower per shard."""

    def __init__(self, num_servers: int, num_shards: Optional[int] = None,
                 vnodes: int = 16, seed: int = 0):
        if num_servers < 2:
            raise ValueError(
                f"HashRing needs >= 2 servers for primary+follower "
                f"replication, got {num_servers}")
        self.num_servers = int(num_servers)
        self.num_shards = int(num_shards if num_shards is not None
                              else 2 * num_servers)
        self.seed = int(seed)
        points: List[Tuple[int, int]] = []
        for s in range(self.num_servers):
            for v in range(int(vnodes)):
                points.append(
                    (stable_hash64(s * 1_000_003 + v, seed=seed + 1), s))
        points.sort()
        self._points = points
        self._keys = [p for p, _ in points]
        # each shard's own ring point (where its clockwise walk starts)
        self._shard_points = [stable_hash64(sh, seed=seed + 2)
                              for sh in range(self.num_shards)]

    # -- row -> shard ---------------------------------------------------
    def shard_of_row(self, row_id: int) -> int:
        return stable_hash64(row_id, seed=self.seed) % self.num_shards

    def shard_of_rows(self, row_ids: np.ndarray) -> np.ndarray:
        """Vectorized :meth:`shard_of_row` (same values, one pass)."""
        return np.array([self.shard_of_row(int(r))
                         for r in np.asarray(row_ids).reshape(-1)],
                        dtype=np.int64)

    def rows_of_shard(self, shard: int, num_rows: int) -> np.ndarray:
        """Sorted global row ids this shard owns out of
        ``range(num_rows)`` — the audit inverse of shard_of_row."""
        return np.array([r for r in range(int(num_rows))
                         if self.shard_of_row(r) == int(shard)],
                        dtype=np.int64)

    # -- shard -> servers -----------------------------------------------
    def owners(self, shard: int,
               alive: Iterable[int]) -> Tuple[int, Optional[int]]:
        """(primary, follower) for ``shard`` given the alive set: the
        first alive server clockwise from the shard's point, then the
        next distinct alive server (None when only one survives)."""
        alive_set = frozenset(int(a) for a in alive)
        if not alive_set:
            raise ValueError(f"shard {shard}: no alive servers")
        start = bisect.bisect_left(self._keys, self._shard_points[shard])
        n = len(self._points)
        primary: Optional[int] = None
        for i in range(n):
            srv = self._points[(start + i) % n][1]
            if srv not in alive_set:
                continue
            if primary is None:
                primary = srv
            elif srv != primary:
                return primary, srv
        return primary, None  # type: ignore[return-value]

    def placement(self, alive: Iterable[int]
                  ) -> Dict[int, Tuple[int, Optional[int]]]:
        alive_f: FrozenSet[int] = frozenset(int(a) for a in alive)
        return {sh: self.owners(sh, alive_f)
                for sh in range(self.num_shards)}
