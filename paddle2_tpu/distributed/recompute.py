"""Activation recomputation (fleet/recompute/recompute.py:455 parity).

The reference re-runs the forward inside a PyLayer backward with saved RNG
state. TPU-native: ``jax.checkpoint`` (remat) on the block's pure function —
XLA saves only the block inputs and re-materializes activations in the
backward, the standard HBM-for-FLOPs trade on TPU.
"""

from __future__ import annotations

from typing import Any, List

import jax

from ..framework.tensor import Tensor
from ..ops.dispatch import apply_op

__all__ = ["recompute", "recompute_sequential", "resolve_policy"]


def resolve_policy(policy):
    """Normalize a remat policy: None/"full" -> full recompute (plain
    ``jax.checkpoint``); a granularity name ("dots", "dots_plus",
    "dots_plus_ln", "offload", "nothing") -> the matching
    ``kernels.attention.remat_policy``; a callable passes through
    (already a jax checkpoint policy)."""
    if policy is None or policy == "full":
        return None
    if callable(policy):
        return policy
    from ..kernels.attention import remat_policy
    return remat_policy(str(policy))


def recompute(function, *args, use_reentrant: bool = True, policy=None,
              **kwargs):
    """Run ``function`` (Layer or callable) over ``args`` with activation
    checkpointing: only the inputs (and params) are saved for backward.

    ``policy`` selects WHAT is saved beyond the inputs: a granularity
    name or jax checkpoint policy (see :func:`resolve_policy`) — the
    seam the cost-model remat searcher wires its winner through on the
    non-scan path."""
    from ..nn.layer.layers import Layer

    params: List[Tensor] = []
    buffers: List[Tensor] = []
    if isinstance(function, Layer):
        params = [p for _, p in function.named_parameters()]
        buffers = [b for _, b in function.named_buffers() if b is not None]

    arg_tensors = [a for a in args if isinstance(a, Tensor)]
    n_p, n_b = len(params), len(buffers)
    state = params + buffers

    def pure(*arrays):
        originals = [t._data for t in state]
        for t, a in zip(state, arrays[:n_p + n_b]):
            t._data = a
        try:
            from ..framework import core
            it = iter(arrays[n_p + n_b:])
            call_args = [Tensor(next(it)) if isinstance(a, Tensor) else a
                         for a in args]
            with core.no_grad():
                out = function(*call_args, **kwargs)
        finally:
            for t, a in zip(state, originals):
                t._data = a
        if isinstance(out, (tuple, list)):
            return tuple(o._data for o in out)
        return out._data

    resolved = resolve_policy(policy)
    ckpt = jax.checkpoint(pure) if resolved is None \
        else jax.checkpoint(pure, policy=resolved)
    return apply_op("recompute", ckpt, tuple(state + arg_tensors), {})


def recompute_sequential(ctx, functions, *args, **kwargs):
    """fleet/recompute/recompute.py:622 parity: checkpoint a Sequential in
    segments."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    funcs = list(functions)
    seg_len = max(1, len(funcs) // max(1, segments))
    out = args
    for i in range(0, len(funcs), seg_len):
        seg = funcs[i:i + seg_len]

        def run_seg(*xs, _seg=seg):
            y = xs
            for f in _seg:
                y = f(*y) if isinstance(y, tuple) else f(y)
                y = y if isinstance(y, tuple) else (y,)
            return y[0] if len(y) == 1 else y

        out = (recompute(run_seg, *out),) if isinstance(out, tuple) else \
            (recompute(run_seg, out),)
    return out[0] if isinstance(out, tuple) and len(out) == 1 else out
