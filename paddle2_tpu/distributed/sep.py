"""Sequence / context parallelism: ring attention + Ulysses all-to-all.

Parity targets: the reference's sep parallelism (fleet/base/topology.py sep
axis, meta_parallel segment utilities) and its ring-p2p long-context path
(NCCL send/recv of KV blocks). TPU-native redesign:

- ``ring_attention``: shard_map over the 'sep' mesh axis. Each device owns
  a sequence chunk of Q/K/V; KV blocks rotate around the ICI ring via
  lax.ppermute while each step's partial attention is merged online with
  the numerically-stable log-sum-exp rule (the flash-attention merge).
  Peak memory is O(S/n) per chip and the N-1 rotations overlap compute.
- ``ulysses_attention``: the all-to-all formulation (DeepSpeed-Ulysses):
  resharding seq-sharded QKV to head-sharded via sharding constraints, so
  GSPMD emits the all-to-alls; full-sequence attention runs per head
  group, then the output reshards back to sequence-sharded.

Both consume paddle-layout (batch, seq, heads, dim) Tensors.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # jax < 0.5: lives under experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from ..framework.tensor import Tensor
from ..ops.dispatch import apply_op, ensure_tensor
from . import mesh as mesh_mod

__all__ = ["ring_attention", "ulysses_attention",
           "SequenceAxisError", "HeadShardingError"]

_NEG = float("-inf")


class SequenceAxisError(ValueError):
    """The requested (or inferred) sequence-parallel mesh axis does not
    exist on the current mesh. Subclasses ValueError so pre-existing
    callers that caught the untyped inference failure keep working —
    the fix (ISSUE 20) is that a *named* ``mesh_axis=`` absent from the
    mesh now raises this instead of a bare ``KeyError`` from the later
    ``mesh.shape[axis]`` lookup."""


class HeadShardingError(ValueError):
    """Ulysses head sharding is impossible: the head count does not
    divide by the sequence-parallel degree, so the seq->head all-to-all
    has no integral head group per rank. Subclasses ValueError for
    backward compatibility with callers catching the untyped raise."""


def _block_attn_lse(q, k, v, scale, mask):
    """Full (small-block) attention returning (out, lse).

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D]; mask: None | 'causal' | a
    traced/bool [Sq, Sk] matrix (True = attend)."""
    B, Sq, H, D = q.shape
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vh = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    s = jnp.einsum("bhsd,bhtd->bhst", qh, kh) * scale
    if mask is not None:
        if isinstance(mask, str):
            Sk = s.shape[-1]
            mask = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
        s = jnp.where(mask, s, _NEG)
    m = jnp.max(s, axis=-1)                                  # [B,H,Sq]
    m_safe = jnp.where(m == _NEG, 0.0, m)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(s == _NEG, 0.0, p)
    l = jnp.sum(p, axis=-1)                                  # [B,H,Sq]
    o = jnp.einsum("bhst,bhtd->bhsd", p, vh)
    o = o / jnp.maximum(l, 1e-30)[..., None]
    lse = jnp.where(l == 0.0, _NEG, m_safe + jnp.log(jnp.maximum(l, 1e-30)))
    return jnp.swapaxes(o, 1, 2).astype(q.dtype), lse


def _merge(o1, lse1, o2, lse2):
    """Log-sum-exp merge of two partial attention results."""
    m = jnp.maximum(lse1, lse2)
    m_safe = jnp.where(m == _NEG, 0.0, m)
    w1 = jnp.where(lse1 == _NEG, 0.0, jnp.exp(lse1 - m_safe))
    w2 = jnp.where(lse2 == _NEG, 0.0, jnp.exp(lse2 - m_safe))
    tot = jnp.maximum(w1 + w2, 1e-30)
    o = (o1.astype(jnp.float32) * jnp.swapaxes(w1, 1, 2)[..., None]
         + o2.astype(jnp.float32) * jnp.swapaxes(w2, 1, 2)[..., None]) \
        / jnp.swapaxes(tot, 1, 2)[..., None]
    lse = jnp.where((w1 + w2) == 0.0, _NEG, m_safe + jnp.log(tot))
    return o.astype(o1.dtype), lse


def _ring_body(q, k, v, *, axis, n, scale, causal):
    """Local computation inside shard_map: q/k/v are the device's sequence
    chunk [B, S/n, H, D]."""
    i = jax.lax.axis_index(axis)
    o = jnp.zeros_like(q)
    lse = jnp.full(
        (q.shape[0], q.shape[2], q.shape[1]), _NEG, jnp.float32)
    perm = [(r, (r + 1) % n) for r in range(n)]
    cur_k, cur_v = k, v
    chunk = q.shape[1]
    for t in range(n):
        # Block-offset convention (load-bearing for causal masking, and
        # mirrored float64-for-float64 by the longseq_fleet oracle): KV
        # blocks rotate FORWARD around the ring (rank r sends to r+1),
        # so after t hops rank i holds the KV chunk that ORIGINATED on
        # rank j = (i - t) mod n. Global token indices are block-major:
        # query rows of rank i are [i*chunk, (i+1)*chunk) and the held
        # KV columns are [j*chunk, (j+1)*chunk), which makes causality
        # a pure block predicate on (i, j) — no per-token global-index
        # arithmetic is ever needed.
        j = (i - t) % n  # origin chunk of the kv currently held
        if causal:
            # bottom-right-aligned global causality across chunks, as ONE
            # mask select (no duplicated attention): j < i full block
            # (every KV column is strictly in the past), j == i
            # intra-chunk lower-triangular, j > i fully masked (the
            # whole block is in the future; _block_attn_lse returns
            # lse = -inf rows and _merge drops them with weight 0)
            tril = jnp.tril(jnp.ones((chunk, chunk), bool))
            full = jnp.ones((chunk, chunk), bool)
            none = jnp.zeros((chunk, chunk), bool)
            mask = jnp.where(j == i, tril, jnp.where(j < i, full, none))
            o_b, lse_b = _block_attn_lse(q, cur_k, cur_v, scale, mask)
        else:
            o_b, lse_b = _block_attn_lse(q, cur_k, cur_v, scale, None)
        o, lse = _merge(o, lse, o_b, lse_b)
        if t < n - 1:
            cur_k = jax.lax.ppermute(cur_k, axis, perm)
            cur_v = jax.lax.ppermute(cur_v, axis, perm)
    return o


def _seq_axis(mesh_axis: Optional[str]) -> str:
    mesh = mesh_mod.get_mesh()
    if mesh_axis is not None:
        if mesh_axis not in mesh.axis_names:
            raise SequenceAxisError(
                f"mesh axis {mesh_axis!r} not on the current mesh "
                f"(axes: {tuple(mesh.axis_names)}); init a mesh with "
                f"that axis or drop mesh_axis= to auto-detect")
        return mesh_axis
    for name in ("sep", "cp", "sp"):
        if name in mesh.axis_names and mesh.shape[name] > 1:
            return name
    raise SequenceAxisError(
        "no sequence-parallel mesh axis found; init a mesh "
        "with a 'sep' axis or pass mesh_axis=")


def ring_attention(query, key, value, causal: bool = False,
                   scale: Optional[float] = None,
                   mesh_axis: Optional[str] = None):
    """Exact attention over a sequence sharded on a mesh ring.

    Inputs are GLOBAL [B, S, H, D] Tensors (sharded or replicated); the
    sequence dim is (re)sharded over the ring axis, KV blocks rotate via
    collective-permute, and the result equals full softmax attention to
    numerical precision — memory per chip stays O(S/n * S/n) per step.
    """
    q, k, v = (ensure_tensor(t) for t in (query, key, value))
    mesh = mesh_mod.get_mesh()
    axis = _seq_axis(mesh_axis)
    n = int(mesh.shape[axis])
    if q.shape[1] % n != 0:
        raise ValueError(f"seq len {q.shape[1]} not divisible by ring "
                         f"degree {n}")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])

    batch_axis = "dp" if "dp" in mesh.axis_names else None
    spec = P(batch_axis, axis, None, None)
    from .fleet.mp_layers import _constrain_tensor
    q = _constrain_tensor(q, spec)  # commit chunks onto the ring
    k = _constrain_tensor(k, spec)
    v = _constrain_tensor(v, spec)
    key = (id(mesh), axis, n, float(scale), bool(causal), batch_axis)
    fn = _ring_cache.get(key)
    if fn is None:
        fn = shard_map(
            partial(_ring_body, axis=axis, n=n, scale=float(scale),
                    causal=bool(causal)),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        fn = jax.jit(fn)  # executable cache keyed on avals by jax itself
        _ring_cache[key] = fn
    return apply_op("ring_attention", fn, (q, k, v), {})


_ring_cache: dict = {}


def ulysses_attention(query, key, value, causal: bool = False,
                      scale: Optional[float] = None,
                      mesh_axis: Optional[str] = None):
    """DeepSpeed-Ulysses sequence parallelism: all-to-all from seq-sharded
    to head-sharded, full attention per head group, all-to-all back. The
    resharding is expressed as GSPMD constraints; XLA emits all-to-alls."""
    from ..kernels.attention import scaled_dot_product_attention as sdpa
    q, k, v = (ensure_tensor(t) for t in (query, key, value))
    mesh = mesh_mod.get_mesh()
    axis = _seq_axis(mesh_axis)
    if q.shape[2] % mesh.shape[axis] != 0:
        raise HeadShardingError(
            f"num_heads {q.shape[2]} not divisible by sep "
            f"degree {mesh.shape[axis]}")
    batch_axis = "dp" if "dp" in mesh.axis_names else None
    from .fleet.mp_layers import _constrain_tensor
    head_spec = P(batch_axis, None, axis, None)
    seq_spec = P(batch_axis, axis, None, None)
    if scale is not None:
        # sdpa hard-codes 1/sqrt(D) (paddle API); fold a custom scale in
        q = q * (float(scale) * math.sqrt(q.shape[-1]))
    q = _constrain_tensor(q, head_spec)   # a2a: seq-shard -> head-shard
    k = _constrain_tensor(k, head_spec)
    v = _constrain_tensor(v, head_spec)
    out = sdpa(q, k, v, is_causal=causal)
    return _constrain_tensor(out, seq_spec)  # a2a back to seq-shard
