"""ZeRO group-sharded training (python/paddle/distributed/sharding/ +
fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:54,
meta_parallel/sharding/group_sharded_stage3.py:85 parity).

TPU-native ZeRO: instead of hand-managed param/grad buckets with explicit
reduce-scatter/all-gather, each stage is a PLACEMENT POLICY —
  - stage 1 ("os"):     optimizer states sharded over the axis, params/grads
                        replicated (re-replicate after step = all-gather).
  - stage 2 ("os_g"):   + gradients sharded before the step (reduce-scatter).
  - stage 3 ("p_g_os"): + parameters stored sharded; forward re-gathers on
                        demand (XLA latency-hiding scheduler overlaps it).
The optimizer's fused jit step consumes/produces arrays with those shardings,
so XLA emits exactly the ZeRO collective pattern.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..framework.tensor import Tensor
from . import mesh as mesh_mod

P = PartitionSpec

__all__ = ["group_sharded_parallel", "ShardedOptimizer", "shard_optimizer",
           "layer_param_groups", "prefetch_gather"]

_LEVELS = {"os": 1, "os_g": 2, "p_g_os": 3}


def _axis_name() -> str:
    mesh = mesh_mod.get_mesh()
    for name in ("sharding", "dp"):
        if name in mesh.axis_names:
            return name
    return mesh.axis_names[0]


def _shard_spec(arr, axis: str) -> P:
    """Shard dim0 if divisible by the axis degree, else replicate."""
    n = mesh_mod.get_mesh().shape[axis]
    if arr.ndim > 0 and arr.shape[0] % n == 0 and arr.shape[0] > 0:
        return P(axis, *([None] * (arr.ndim - 1)))
    return P()


def _place(arr, spec: P):
    return jax.device_put(arr, NamedSharding(mesh_mod.get_mesh(), spec))


class ShardedOptimizer:
    """Wraps an Optimizer with a ZeRO placement policy (stage 1/2/3).

    ``prefetch`` (stage 3 only) turns the on-demand forward re-gather
    into a LAYER-AHEAD schedule inside the compiled train step: each
    module group's parameter all-gather is issued as its own explicit
    collective, chained so group ``i`` cannot start before group
    ``i - prefetch_depth`` finished — the latency-hiding scheduler then
    overlaps the in-flight gather with the previous layer's compute
    while live replicated memory stays bounded to ~``prefetch_depth``
    layers instead of the whole model. Values are bitwise identical to
    the eager (non-prefetch) path; it is purely a schedule shape.
    """

    def __init__(self, optimizer, level: str = "os",
                 group=None, offload: bool = False,
                 prefetch: bool = False, prefetch_depth: int = 1):
        if level not in _LEVELS:
            raise ValueError(f"level must be one of {list(_LEVELS)}")
        self._inner = optimizer
        self._level = _LEVELS[level]
        self._axis = group.axes[0] if group is not None else _axis_name()
        self._prefetch = bool(prefetch) and self._level >= 3
        self._prefetch_depth = max(1, int(prefetch_depth))

    # -- placement policies ----------------------------------------------
    def _shard_states(self):
        axis = self._axis
        for key, state in list(self._inner._states.items()):
            self._inner._states[key] = jax.tree_util.tree_map(
                lambda a: _place(a, _shard_spec(a, axis))
                if isinstance(a, jnp.ndarray) else a, state)

    def _place_params_and_grads(self):
        axis = self._axis
        for p in self._inner._parameter_list():
            if self._level >= 3:
                p._replace_data(_place(p._data, _shard_spec(p._data, axis)))
            else:
                p._replace_data(_place(p._data, P()))
            if self._level >= 2 and p.grad is not None:
                g = p.grad
                g._replace_data(_place(g._data, _shard_spec(g._data, axis)))

    # -- optimizer API ----------------------------------------------------
    def step(self):
        if self._level >= 2:
            # reduce-scatter the (already-synced) grads before the update
            axis = self._axis
            for p in self._inner._parameter_list():
                if p.grad is not None:
                    p.grad._replace_data(
                        _place(p.grad._data, _shard_spec(p.grad._data, axis)))
        self._inner.step()
        self._shard_states()
        self._place_params_and_grads()

    def clear_grad(self, *a, **k):
        return self._inner.clear_grad(*a, **k)

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()

    def state_dict(self):
        # placement metadata rides along so a restore can verify it is
        # re-establishing the same ZeRO policy (the reshard-on-load path
        # reslices by the LIVE placement, so axis/level must round-trip)
        state = self._inner.state_dict()
        state["_zero_placement"] = {"level": self._level,
                                    "axis": self._axis}
        return state

    def _restore(self, state, loader):
        state = dict(state)
        meta = state.pop("_zero_placement", None)
        # validate BEFORE touching the inner optimizer: a caller that
        # catches the mismatch (elastic ladder trying the next
        # snapshot) must not be left with a half-applied checkpoint
        if meta is not None:
            if int(meta.get("level", self._level)) != self._level:
                raise ValueError(
                    f"ZeRO level mismatch on restore: checkpoint was "
                    f"saved at stage {meta['level']}, this optimizer "
                    f"is stage {self._level} — rebuild with the "
                    f"matching level")
            axis = meta.get("axis", self._axis)
            if axis != self._axis:
                raise ValueError(
                    f"ZeRO shard-axis mismatch on restore: checkpoint "
                    f"was sharded over {axis!r}, this optimizer over "
                    f"{self._axis!r} — reshard through the elastic "
                    f"checkpoint path instead")
        out = loader(state)
        # re-establish the shard placement: the inner restore copies
        # leaves onto the default device (replicated), and a donated
        # fused step whose out_shardings pin the ZeRO placement would
        # otherwise see differently-placed arguments on the next
        # dispatch — a fresh compile at best, a silent memory-footprint
        # regression (states materialized replicated) at worst. Pure
        # placement: values stay bitwise identical.
        self._shard_states()
        self._place_params_and_grads()
        return out

    def load_state_dict(self, state):
        loader = getattr(self._inner, "load_state_dict",
                         self._inner.set_state_dict)
        return self._restore(state, loader)

    def set_state_dict(self, state):
        return self._restore(state, self._inner.set_state_dict)

    def get_lr(self):
        return self._inner.get_lr()

    def set_lr(self, lr):
        return self._inner.set_lr(lr)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def group_sharded_parallel(model, optimizer, level: str, scaler=None,
                           group=None, offload: bool = False,
                           sync_buffers: bool = False, buffer_max_size=None,
                           segment_size=None, sync_comm: bool = False,
                           dp_group=None, exclude_layer=None,
                           prefetch: bool = False, prefetch_depth: int = 1):
    """python/paddle/distributed/sharding/group_sharded.py parity: returns
    (model, sharded_optimizer, scaler). ``prefetch`` enables the
    layer-ahead parameter all-gather schedule at stage 3 (see
    :class:`ShardedOptimizer`)."""
    if not mesh_mod.mesh_initialized():
        mesh_mod.init_mesh()
    opt = ShardedOptimizer(optimizer, level=level, group=group,
                           prefetch=prefetch,
                           prefetch_depth=prefetch_depth)
    if _LEVELS[level] >= 3:
        axis = opt._axis
        for p in model.parameters():
            p._replace_data(_place(p._data, _shard_spec(p._data, axis)))
    return model, opt, scaler


def shard_optimizer(optimizer, shard_fn=None, group=None):
    """auto_parallel/api.py:1591 parity: ZeRO-1 the optimizer states."""
    return ShardedOptimizer(optimizer, level="os", group=group)


# ------------------------------------------------------------- prefetch
def layer_param_groups(layers: Sequence, params: Sequence
                       ) -> List[List[int]]:
    """Indices of ``params`` grouped by owning sub-module, in forward
    (registration) order — the prefetch granularity.

    The owning module is the dotted-name prefix from
    ``named_parameters()``; consecutive parameters of the same module
    form one group (a Linear's weight+bias gather together). Parameters
    not reachable from ``layers`` land in one trailing group. Pure
    function of the layer tree — deterministic across ranks.
    """
    index = {id(p): i for i, p in enumerate(params)}
    groups: List[List[int]] = []
    last_key = None
    for lyr in layers:
        for name, p in lyr.named_parameters():
            i = index.pop(id(p), None)
            if i is None:
                continue
            owner = name.rsplit(".", 1)[0] if "." in name else ""
            key = (id(lyr), owner)
            if key != last_key:
                groups.append([])
                last_key = key
            groups[-1].append(i)
    leftover = sorted(index.values())
    if leftover:
        groups.append(leftover)
    return groups


def _identity_barrier_fwd(args):
    import jax
    return jax.lax.optimization_barrier(args), None


def _identity_barrier_bwd(_, cts):
    return (cts,)


_identity_barrier = None


def _get_identity_barrier():
    """``optimization_barrier`` with an identity VJP: the barrier is a
    SCHEDULING constraint only, so cotangents pass straight through
    (jax 0.4.x has no differentiation rule for the primitive). Built
    lazily so this module stays importable without tracing jax."""
    global _identity_barrier
    if _identity_barrier is None:
        import jax

        @jax.custom_vjp
        def barrier(args):
            return jax.lax.optimization_barrier(args)

        barrier.defvjp(_identity_barrier_fwd, _identity_barrier_bwd)
        _identity_barrier = barrier
    return _identity_barrier


def prefetch_gather(param_arrays: Sequence, groups: Sequence[Sequence[int]],
                    depth: int = 1) -> List:
    """Traced ZeRO-3 parameter gather, optionally layer-ahead-chained.

    For each module group (``layer_param_groups`` order) emit an
    EXPLICIT all-gather of its sharded parameters (a replicated
    sharding constraint — GSPMD lowers it to the gather). With
    ``depth >= 1`` the gathers are chained with an optimization barrier
    so group ``i``'s gather cannot issue before group ``i - depth``'s
    gathered values exist: live replicated memory stays bounded to
    ~``depth`` module groups while each gather is free to overlap the
    PREVIOUS groups' compute (a gather depends only on earlier gathers,
    never on compute). ``depth <= 0`` emits the same gathers UNCHAINED
    (the eager gather-all schedule — XLA may hoist every gather to the
    step start). Both shapes feed the model math the SAME gathered
    (replicated) values, so eager-vs-prefetch is bitwise by
    construction; the identity-VJP barrier keeps gradients bitwise
    too.
    """
    import jax
    from jax.sharding import PartitionSpec
    out = list(param_arrays)
    gathered_groups: List[List] = []
    barrier = _get_identity_barrier()
    chained_depth = int(depth)
    for gi, idxs in enumerate(groups):
        arrs = [out[i] for i in idxs]
        if not arrs:
            gathered_groups.append([])
            continue
        anchors = ()
        if chained_depth >= 1:
            anchor_gi = gi - chained_depth
            if anchor_gi >= 0:
                for back in range(anchor_gi, -1, -1):
                    if gathered_groups[back]:
                        # anchor on EVERY array of the group: an edge to
                        # only its first member would leave the
                        # scheduler free to hoist the siblings' gathers
                        # arbitrarily early, voiding the ~depth-groups
                        # live-memory bound
                        anchors = tuple(gathered_groups[back])
                        break
        if anchors:
            # anchors ride through stop_gradient: their only role is
            # ordering, and a second cotangent path through the barrier
            # would perturb the anchors' gradient accumulation order
            chained = barrier(tuple(arrs) + tuple(
                jax.lax.stop_gradient(a) for a in anchors))
            arrs = list(chained[:len(arrs)])
        gathered = [mesh_mod.constrain(a, PartitionSpec())
                    for a in arrs]
        for i, g in zip(idxs, gathered):
            out[i] = g
        gathered_groups.append(gathered)
    return out
