"""ZeRO group-sharded training (python/paddle/distributed/sharding/ +
fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:54,
meta_parallel/sharding/group_sharded_stage3.py:85 parity).

TPU-native ZeRO: instead of hand-managed param/grad buckets with explicit
reduce-scatter/all-gather, each stage is a PLACEMENT POLICY —
  - stage 1 ("os"):     optimizer states sharded over the axis, params/grads
                        replicated (re-replicate after step = all-gather).
  - stage 2 ("os_g"):   + gradients sharded before the step (reduce-scatter).
  - stage 3 ("p_g_os"): + parameters stored sharded; forward re-gathers on
                        demand (XLA latency-hiding scheduler overlaps it).
The optimizer's fused jit step consumes/produces arrays with those shardings,
so XLA emits exactly the ZeRO collective pattern.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..framework.tensor import Tensor
from . import mesh as mesh_mod

P = PartitionSpec

__all__ = ["group_sharded_parallel", "ShardedOptimizer", "shard_optimizer"]

_LEVELS = {"os": 1, "os_g": 2, "p_g_os": 3}


def _axis_name() -> str:
    mesh = mesh_mod.get_mesh()
    for name in ("sharding", "dp"):
        if name in mesh.axis_names:
            return name
    return mesh.axis_names[0]


def _shard_spec(arr, axis: str) -> P:
    """Shard dim0 if divisible by the axis degree, else replicate."""
    n = mesh_mod.get_mesh().shape[axis]
    if arr.ndim > 0 and arr.shape[0] % n == 0 and arr.shape[0] > 0:
        return P(axis, *([None] * (arr.ndim - 1)))
    return P()


def _place(arr, spec: P):
    return jax.device_put(arr, NamedSharding(mesh_mod.get_mesh(), spec))


class ShardedOptimizer:
    """Wraps an Optimizer with a ZeRO placement policy (stage 1/2/3)."""

    def __init__(self, optimizer, level: str = "os",
                 group=None, offload: bool = False):
        if level not in _LEVELS:
            raise ValueError(f"level must be one of {list(_LEVELS)}")
        self._inner = optimizer
        self._level = _LEVELS[level]
        self._axis = group.axes[0] if group is not None else _axis_name()

    # -- placement policies ----------------------------------------------
    def _shard_states(self):
        axis = self._axis
        for key, state in list(self._inner._states.items()):
            self._inner._states[key] = jax.tree_util.tree_map(
                lambda a: _place(a, _shard_spec(a, axis))
                if isinstance(a, jnp.ndarray) else a, state)

    def _place_params_and_grads(self):
        axis = self._axis
        for p in self._inner._parameter_list():
            if self._level >= 3:
                p._replace_data(_place(p._data, _shard_spec(p._data, axis)))
            else:
                p._replace_data(_place(p._data, P()))
            if self._level >= 2 and p.grad is not None:
                g = p.grad
                g._replace_data(_place(g._data, _shard_spec(g._data, axis)))

    # -- optimizer API ----------------------------------------------------
    def step(self):
        if self._level >= 2:
            # reduce-scatter the (already-synced) grads before the update
            axis = self._axis
            for p in self._inner._parameter_list():
                if p.grad is not None:
                    p.grad._replace_data(
                        _place(p.grad._data, _shard_spec(p.grad._data, axis)))
        self._inner.step()
        self._shard_states()
        self._place_params_and_grads()

    def clear_grad(self, *a, **k):
        return self._inner.clear_grad(*a, **k)

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()

    def state_dict(self):
        return self._inner.state_dict()

    def load_state_dict(self, state):
        return self._inner.load_state_dict(state)

    def set_state_dict(self, state):
        return self._inner.set_state_dict(state)

    def get_lr(self):
        return self._inner.get_lr()

    def set_lr(self, lr):
        return self._inner.set_lr(lr)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def group_sharded_parallel(model, optimizer, level: str, scaler=None,
                           group=None, offload: bool = False,
                           sync_buffers: bool = False, buffer_max_size=None,
                           segment_size=None, sync_comm: bool = False,
                           dp_group=None, exclude_layer=None):
    """python/paddle/distributed/sharding/group_sharded.py parity: returns
    (model, sharded_optimizer, scaler)."""
    if not mesh_mod.mesh_initialized():
        mesh_mod.init_mesh()
    opt = ShardedOptimizer(optimizer, level=level, group=group)
    if _LEVELS[level] >= 3:
        axis = opt._axis
        for p in model.parameters():
            p._replace_data(_place(p._data, _shard_spec(p._data, axis)))
    return model, opt, scaler


def shard_optimizer(optimizer, shard_fn=None, group=None):
    """auto_parallel/api.py:1591 parity: ZeRO-1 the optimizer states."""
    return ShardedOptimizer(optimizer, level="os", group=group)
