"""paddle.distributed.spawn (reference distributed/spawn.py:463) —
launch ``func`` in ``nprocs`` worker processes with the launcher's env
contract (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_MASTER), the
programmatic twin of ``python -m paddle2_tpu.distributed.launch``.
"""

from __future__ import annotations

import os
import pickle
import subprocess
import sys
import tempfile
from typing import Any, Iterable

__all__ = ["spawn"]

_WORKER_SNIPPET = """\
import pickle, sys
with open(sys.argv[1], "rb") as f:
    func, args = pickle.load(f)
func(*args)
"""


class MultiprocessContext:
    def __init__(self, procs):
        self.processes = procs

    def join(self, timeout=None):
        rcs = [p.wait(timeout=timeout) for p in self.processes]
        bad = [i for i, rc in enumerate(rcs) if rc != 0]
        if bad:
            raise RuntimeError(
                f"spawn worker(s) {bad} exited nonzero: "
                f"{[rcs[i] for i in bad]}")
        return True


def spawn(func, args: Iterable[Any] = (), nprocs: int = -1,
          join: bool = True, daemon: bool = False, **options):
    """Pickle (func, args) and exec one Python per rank with the
    collective env set. Workers call dist.init_parallel_env() themselves,
    exactly as under the CLI launcher."""
    if nprocs < 1:
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    with tempfile.NamedTemporaryFile("wb", suffix=".pkl",
                                     delete=False) as f:
        pickle.dump((func, tuple(args)), f)
        payload = f.name
    # the worker unpickles by importing func's module: make sure that
    # module's directory (and the caller's cwd) resolve there
    import inspect
    extra_paths = [os.getcwd()]
    try:
        extra_paths.append(os.path.dirname(inspect.getfile(func)))
    except TypeError:
        pass
    pypath = os.pathsep.join(
        extra_paths + [os.environ.get("PYTHONPATH", "")])
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    for rank in range(nprocs):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nprocs),
            "PADDLE_MASTER": f"127.0.0.1:{port}",
            "PADDLE_LOCAL_RANK": str(rank),
            "PYTHONPATH": pypath,
        })
        env.update({str(k): str(v) for k, v in options.get("env",
                                                           {}).items()})
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER_SNIPPET, payload], env=env))
    ctx = MultiprocessContext(procs)
    if join:
        ctx.join()
    return ctx
