"""ICI-vs-DCN-aware sharding defaults for hybrid parallelism.

A pod slice has two very different wire classes: ICI (the intra-slice
torus, ~90 GB/s per link) and DCN (the cross-slice data-center network,
~12.5 GB/s per host). A collective over a DCN-mapped mesh axis is an
order of magnitude slower per byte, so the axis PLACEMENT decides
whether hybrid parallelism scales:

* **tp** (tensor parallel) all-reduces activations on the critical path
  every layer — it must live on the innermost (ICI-adjacent) axis;
* **fsdp/sharding** (ZeRO) gathers parameters every step — ICI;
* **pp** (pipeline) moves only microbatch activations point-to-point —
  tolerant, between the two;
* **dp** (data parallel) all-reduces gradients ONCE per step and the
  reduction overlaps backward — the only traffic that survives DCN, so
  dp goes outermost (cross-slice).

:class:`SpecLayout` (the SNIPPETS [3] idiom) names the axes once and
hands out canonical PartitionSpecs for transformer parameters plus the
matching :class:`~paddle2_tpu.observability.cost_model.LinkModel`;
:func:`hybrid_mesh` builds the global mesh in that DCN-outermost /
ICI-innermost order and (on TPU) applies the latency-hiding-scheduler
XLA flags from :mod:`paddle2_tpu.flags`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

from jax.sharding import PartitionSpec as P

from . import mesh as mesh_mod

__all__ = ["SpecLayout", "hybrid_mesh", "installed_layout"]

# the layout hybrid_mesh last installed alongside the global mesh —
# mesh.dcn_axes() consults it so the axis placement and the link model
# pricing that traffic can never disagree
_installed: Optional["SpecLayout"] = None


def installed_layout() -> Optional["SpecLayout"]:
    """The :class:`SpecLayout` of the last :func:`hybrid_mesh` install
    (None when the mesh was built some other way)."""
    return _installed


@dataclass(frozen=True)
class SpecLayout:
    """Canonical PartitionSpecs for hybrid-parallel transformer state.

    Axis names follow this repo's ``HYBRID_AXES`` convention (``dp``,
    ``pp``, ``sharding``, ``mp``); ``dcn_axes`` names the axes that map
    onto the data-center network — by default only ``dp``, the one kind
    of traffic whose once-per-step overlappable gradient reduction
    tolerates the slow wire.
    """

    data_axis: str = "dp"
    pp_axis: str = "pp"
    fsdp_axis: str = "sharding"
    tp_axis: str = "mp"
    dcn_axes: Tuple[str, ...] = ("dp",)

    # -- activation / batch placement -----------------------------------
    def batch(self, ndim: int = 2) -> P:
        """Batch dim sharded over dp (and fsdp when present): the global
        batch splits across every data-ish axis."""
        return P((self.data_axis, self.fsdp_axis),
                 *([None] * max(0, ndim - 1)))

    # -- parameter placement (Megatron-style transformer) ----------------
    def embeddings(self) -> P:
        """Embedding tables: vocab dim over fsdp×tp, hidden replicated."""
        return P((self.fsdp_axis, self.tp_axis), None)

    def qkv_projection(self) -> P:
        """Column-parallel [hidden, 3*head_dim]: fsdp rows, tp cols."""
        return P(self.fsdp_axis, self.tp_axis)

    def attn_output(self) -> P:
        """Row-parallel output projection: tp rows, fsdp cols."""
        return P(self.tp_axis, self.fsdp_axis)

    def ffn_up(self) -> P:
        return P(self.fsdp_axis, self.tp_axis)

    def ffn_down(self) -> P:
        return P(self.tp_axis, self.fsdp_axis)

    def norm_scale(self) -> P:
        """Norm gains/biases: tiny — replicate everywhere."""
        return P()

    # -- mesh / link topology -------------------------------------------
    def mesh_axes(self, dp: int = 1, pp: int = 1, fsdp: int = 1,
                  tp: int = 1) -> Dict[str, int]:
        """Axis→degree in rank-major mesh order: dp OUTERMOST (adjacent
        ranks differ in the innermost axis, so the innermost axes land
        on ICI-adjacent chips), tp INNERMOST. Degree-1 axes are kept so
        PartitionSpecs naming them stay valid on every topology."""
        return {self.data_axis: int(dp), self.pp_axis: int(pp),
                self.fsdp_axis: int(fsdp), self.tp_axis: int(tp)}

    def is_dcn(self, axis: str) -> bool:
        """Delegates to the matching :class:`LinkModel` so there is ONE
        owner of the rule (this layout's axes + the ``PADDLE_DCN_AXES``
        env list + the ``"dcn"`` name convention)."""
        return self.link_model().is_dcn(axis)

    def split_link_classes(self, axes: Sequence[str]
                           ) -> Tuple[Tuple[str, ...], Tuple[str, ...]]:
        """Split a collective's mesh axes into ``(ici_axes,
        dcn_axes)`` — the axis split the hierarchical collectives
        (``collective.hierarchical_psum``) and the ladder's cost
        accounting consume. Order within each class is preserved."""
        lm = self.link_model()
        ici = tuple(a for a in axes if not lm.is_dcn(a))
        dcn = tuple(a for a in axes if lm.is_dcn(a))
        return ici, dcn

    def link_model(self, ici_gbps: Optional[float] = None,
                   dcn_gbps: Optional[float] = None,
                   ici_latency_us: Optional[float] = None,
                   dcn_latency_us: Optional[float] = None):
        """The matching cost-model link table: this layout's dcn axes
        charged at DCN bandwidth (and, when given, per-dispatch DCN
        latency), everything else ICI."""
        from ..observability.cost_model import LinkModel
        return LinkModel(ici_gbps=ici_gbps, dcn_gbps=dcn_gbps,
                         ici_latency_us=ici_latency_us,
                         dcn_latency_us=dcn_latency_us,
                         dcn_axes=self.dcn_axes)


def hybrid_mesh(dp: int = 1, pp: int = 1, fsdp: int = 1, tp: int = 1,
                layout: Optional[SpecLayout] = None,
                devices: Optional[Sequence] = None,
                apply_xla_flags: bool = True):
    """Build (and install) the hybrid mesh in DCN-outermost order and
    return ``(mesh, layout)``.

    On TPU platforms this also applies the latency-hiding-scheduler /
    async-collective XLA flags registered in :mod:`paddle2_tpu.flags`
    (a no-op on CPU, and a no-op once the backend is initialized —
    call before the first compile, launcher-style)."""
    layout = layout or SpecLayout()
    axes = layout.mesh_axes(dp=dp, pp=pp, fsdp=fsdp, tp=tp)
    n = 1
    for v in axes.values():
        n *= v
    if apply_xla_flags and n > 1:
        from ..flags import apply_multichip_xla_env
        apply_multichip_xla_env()
    mesh = mesh_mod.init_mesh(axes, devices=devices)
    global _installed
    _installed = layout
    return mesh, layout
