"""Distributed utilities: logging + env helpers (reference
python/paddle/distributed/utils/log_utils.py)."""

from __future__ import annotations

import logging
import os
import sys

_loggers = {}


def get_logger(level=logging.INFO, name: str = "paddle2_tpu.distributed"):
    lg = _loggers.get(name)
    if lg is not None:
        return lg
    lg = logging.getLogger(name)
    lg.setLevel(level)
    if not lg.handlers:
        h = logging.StreamHandler(sys.stderr)
        rank = os.environ.get("PADDLE_TRAINER_ID", "0")
        h.setFormatter(logging.Formatter(
            f"[rank {rank}] %(asctime)s %(levelname)s %(message)s"))
        lg.addHandler(h)
    lg.propagate = False
    _loggers[name] = lg
    return lg
