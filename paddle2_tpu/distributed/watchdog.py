"""Collective watchdog (reference paddle/phi/core/distributed/
comm_task_manager.h:37: background CommTaskLoop threads that detect
timed-out NCCL collectives, log diagnostics, and abort the communicator).

On TPU there is no communicator to abort — a hung collective means a hung
XLA execution (usually a desynced gang in multi-host). The watchdog
mirrors the reference's split:

  * a WAITER thread per watched operation blocks on the result buffers;
  * the MONITOR thread flags operations that outlive their deadline,
    logging a diagnostic with the op tag (and every other in-flight op,
    the usual clue for a rank mismatch) and, with
    FLAGS_collective_abort_on_timeout, killing the process so the
    launcher's gang supervision (launch/main.py) can restart the job —
    the moral twin of NCCLCommTask::AbortComm + store error propagation.

Enable with FLAGS_collective_timeout_s > 0 (off by default: the waiter
threads cost a sync per collective, like the reference's debug watchdog).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

from ..flags import define_flag, flag_value
from .utils import get_logger

define_flag("collective_timeout_s", 0.0,
            "Watchdog timeout for dispatched collectives (seconds); 0 "
            "disables the watchdog entirely.")
define_flag("collective_abort_on_timeout", False,
            "Kill the process when a collective times out so the "
            "launcher can restart the gang (CommTaskManager abort "
            "semantics).")
define_flag("straggler_k", 2.0,
            "A rank whose last step time exceeds k x the median of all "
            "ranks' step times is flagged as a suspected straggler in "
            "CollectiveTimeout diagnostics.")

logger = get_logger(name=__name__)

# env var: directory where ranks gossip their step times (one small file
# per rank, atomic tmp+replace like the elastic heartbeats). Unset =
# process-local gossip only (single-controller: that IS every rank).
GOSSIP_DIR_ENV = "PADDLE_STEP_GOSSIP_DIR"


class CollectiveTimeout(RuntimeError):
    """A deadline-aware collective outlived its timeout. Carries enough
    context to page the right person: the op tag, the group description,
    the deadline, the suspected straggler ranks from step-time gossip
    (empty when no gossip has been observed), and — when the flight
    recorder is on — the path of the dump written at the timeout, so
    the operator's first stack trace points at the evidence."""

    def __init__(self, tag: str, group_desc: str, timeout: float,
                 stragglers=(), dump_hint: str = ""):
        self.tag = tag
        self.group_desc = group_desc
        self.timeout = timeout
        self.stragglers = list(stragglers)
        who = (f"; suspected straggler rank(s): {self.stragglers} "
               f"(step time > k*median gossip)" if self.stragglers
               else "; no straggler gossip observed")
        super().__init__(
            f"collective '{tag}' on group {group_desc} exceeded its "
            f"{timeout:.1f}s deadline{who} — likely a desynced gang: "
            f"some rank never dispatched the matching collective"
            f"{dump_hint}")


class StragglerDetector:
    """Per-rank step-time gossip: each rank records how long its steps
    take; :meth:`suspects` flags ranks whose latest step time exceeds
    ``k * median`` of all observed ranks. Cross-process gossip rides
    one small file per rank under ``PADDLE_STEP_GOSSIP_DIR`` (atomic
    tmp+replace, read lazily); without it the registry is process-local
    — which in single-controller SPMD covers every logical rank."""

    _instance: Optional["StragglerDetector"] = None
    _lock = threading.Lock()

    def __init__(self):
        self._mu = threading.Lock()
        self._times: Dict[int, float] = {}

    @classmethod
    def get(cls) -> "StragglerDetector":
        with cls._lock:
            if cls._instance is None:
                cls._instance = StragglerDetector()
            return cls._instance

    def observe(self, rank: int, step_seconds: float) -> None:
        with self._mu:
            self._times[int(rank)] = float(step_seconds)
        d = os.environ.get(GOSSIP_DIR_ENV)
        if d:
            try:
                os.makedirs(d, exist_ok=True)
                tmp = os.path.join(d, f".rank.{int(rank)}.tmp")
                with open(tmp, "w") as f:
                    f.write(f"{float(step_seconds):.6f}")
                os.replace(tmp, os.path.join(d, f"rank.{int(rank)}"))
            except OSError:
                pass                      # gossip is best-effort

    def _gossip(self) -> Dict[int, float]:
        with self._mu:
            times = dict(self._times)
        d = os.environ.get(GOSSIP_DIR_ENV)
        if d and os.path.isdir(d):
            for name in os.listdir(d):
                if not name.startswith("rank."):
                    continue
                try:
                    r = int(name.split(".", 1)[1])
                    with open(os.path.join(d, name)) as f:
                        times[r] = float(f.read().strip())
                except (OSError, ValueError):
                    continue
        return times

    def suspects(self, k: Optional[float] = None) -> list:
        """Ranks whose last step time exceeds k x the median, slowest
        first. Needs >= 2 ranks observed (a median of one is itself)."""
        times = self._gossip()
        if len(times) < 2:
            return []
        k = float(flag_value("straggler_k")) if k is None else float(k)
        vals = sorted(times.values())
        mid = len(vals) // 2
        median = (vals[mid] if len(vals) % 2
                  else 0.5 * (vals[mid - 1] + vals[mid]))
        if median <= 0:
            return []
        out = [(t, r) for r, t in times.items() if t > k * median]
        return [r for _, r in sorted(out, reverse=True)]

    def reset(self) -> None:
        with self._mu:
            self._times.clear()


def prune_gossip(live_world: int,
                 directory: Optional[str] = None) -> list:
    """Drop step-time gossip from ranks that LEFT the gang (elastic
    scale-in): delete ``rank.N`` files with ``N >= live_world`` from the
    gossip dir and evict the same ranks from the in-process registry, so
    straggler attribution stops accusing dead ranks. Returns the pruned
    rank ids. The launcher calls this before respawning at a smaller
    world; harmless when no gossip dir is configured."""
    pruned = []
    d = directory or os.environ.get(GOSSIP_DIR_ENV)
    if d and os.path.isdir(d):
        for name in os.listdir(d):
            if not name.startswith("rank."):
                continue
            try:
                r = int(name.split(".", 1)[1])
            except ValueError:
                continue
            if r >= int(live_world):
                try:
                    os.remove(os.path.join(d, name))
                    pruned.append(r)
                except OSError:
                    pass
    det = StragglerDetector._instance
    if det is not None:
        with det._mu:
            for r in [r for r in det._times if r >= int(live_world)]:
                det._times.pop(r, None)
                if r not in pruned:
                    pruned.append(r)
    return sorted(pruned)


class CommWatchdog:
    """Tracks in-flight collectives; singleton via get()."""

    _instance: Optional["CommWatchdog"] = None
    _lock = threading.Lock()

    def __init__(self):
        self._inflight: Dict[int, Dict[str, Any]] = {}
        self._next_id = 0
        self._mu = threading.Lock()
        self._monitor: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # tags of ops the monitor flagged as overrun, drained by
        # consume_timeouts() — how ReliableStep learns a step's
        # collective hung (detect -> recover wiring)
        self._timeouts: list = []

    @classmethod
    def get(cls) -> "CommWatchdog":
        # the watchdog owns the low-frequency device self-test timer
        # (FLAGS_health_probe_interval_s): get() is on every guarded
        # step's path, so the prober lazily (re)starts here — one flag
        # read when the probe is off
        try:
            from .fault_tolerance.health import HealthProber
            HealthProber.ensure()
        except Exception:
            pass
        with cls._lock:
            if cls._instance is None:
                cls._instance = CommWatchdog()
            return cls._instance

    # -- public ----------------------------------------------------------
    def enabled(self) -> bool:
        return float(flag_value("collective_timeout_s")) > 0.0

    def watch(self, tag: str, arrays, timeout: Optional[float] = None
              ) -> None:
        """Register a dispatched collective; a waiter thread blocks on
        the buffers and clears the entry when they materialize. A
        per-op ``timeout`` (deadline-aware collectives) overrides the
        global flag and registers the op even when the flag is off."""
        if timeout is None:
            if not self.enabled():
                return
            timeout = float(flag_value("collective_timeout_s"))
        with self._mu:
            op_id = self._next_id
            self._next_id += 1
            self._inflight[op_id] = {
                "tag": tag, "start": time.monotonic(),
                "deadline": time.monotonic() + timeout, "fired": False,
            }
        waiter = threading.Thread(target=self._wait, args=(op_id, arrays),
                                  daemon=True,
                                  name=f"comm-waiter-{op_id}")
        waiter.start()
        self._ensure_monitor()

    # -- internals -------------------------------------------------------
    def _wait(self, op_id: int, arrays) -> None:
        try:
            from .fault_tolerance import chaos
            chaos.maybe_delay_collective(self._tag(op_id))
            chaos.maybe_stall_collective(self._tag(op_id))
            import jax
            jax.block_until_ready(arrays)
        except Exception as e:  # execution error counts as completion
            logger.warning("collective %s failed: %s",
                           self._tag(op_id), e)
        finally:
            with self._mu:
                self._inflight.pop(op_id, None)

    def _tag(self, op_id: int) -> str:
        with self._mu:
            entry = self._inflight.get(op_id)
            return entry["tag"] if entry else f"op{op_id}"

    def _ensure_monitor(self) -> None:
        # under _mu: pairs with the monitor's park-on-empty exit (which
        # clears _monitor under the same lock), closing the TOCTOU window
        # where a fresh op could see a dying-but-alive monitor and end up
        # unmonitored
        with self._mu:
            if self._monitor is None or not self._monitor.is_alive():
                self._stop.clear()
                self._monitor = threading.Thread(
                    target=self._monitor_loop, daemon=True,
                    name="comm-watchdog")
                self._monitor.start()

    def _monitor_loop(self) -> None:
        while not self._stop.wait(0.25):
            now = time.monotonic()
            overdue = []
            with self._mu:
                if not self._inflight:
                    self._monitor = None  # park; next watch() respawns
                    return
                for op_id, e in self._inflight.items():
                    if now > e["deadline"] and not e["fired"]:
                        e["fired"] = True
                        overdue.append((op_id, dict(e)))
                pending = [e["tag"] for e in self._inflight.values()]
            if overdue:
                with self._mu:
                    self._timeouts.extend(e["tag"] for _, e in overdue)
            for op_id, e in overdue:
                logger.error(
                    "collective TIMEOUT after %.1fs: %s (in-flight: %s) — "
                    "likely a desynced gang: some rank never dispatched "
                    "the matching collective (comm_task_manager.h "
                    "IsTimeout semantics)",
                    now - e["start"], e["tag"], pending)
                from .fault_tolerance import flight_recorder
                flight_recorder.record("watchdog_overrun", tag=e["tag"],
                                       waited_s=now - e["start"],
                                       inflight=list(pending))
                if bool(flag_value("collective_abort_on_timeout")):
                    # dump BEFORE the abort: the whole point of the
                    # flight recorder is that this exit leaves evidence
                    flight_recorder.dump(f"watchdog_abort:{e['tag']}")
                    logger.error("aborting process for gang restart "
                                 "(AbortComm semantics)")
                    os._exit(134)

    def consume_timeouts(self) -> list:
        """Drain and return the tags flagged as overrun since the last
        call. Polled by ReliableStep after each step so a hung-then-
        recovered collective triggers an in-job retry instead of
        silently training on a desynced gang."""
        with self._mu:
            out, self._timeouts = self._timeouts, []
            return out

    # test hook ----------------------------------------------------------
    def inflight_count(self) -> int:
        with self._mu:
            return len(self._inflight)


def watch(tag: str, arrays, timeout: Optional[float] = None) -> None:
    """Module-level convenience used by collective dispatch."""
    wd = CommWatchdog.get()
    if wd.enabled() or timeout is not None:
        wd.watch(tag, arrays, timeout=timeout)


def run_with_deadline(tag: str, fn, timeout: float,
                      group_desc: str = "world"):
    """Run ``fn()`` on a helper thread, bounded by ``timeout`` seconds:
    past the deadline, queue ``tag`` for ReliableStep's poll, log,
    honor FLAGS_collective_abort_on_timeout, and raise
    :class:`CollectiveTimeout` naming the group, the op tag, and the
    suspected straggler ranks — a hang surfaces a rank instead of
    stalling the pod. The single deadline-thread implementation behind
    ``wait_with_deadline``, the multi-controller collective paths, and
    ``barrier``. The helper thread is abandoned on timeout (daemon) —
    callers must make late completion side-effect-free (e.g. dispatch
    into a shadow buffer and commit only on an in-deadline return)."""
    done = threading.Event()
    box: Dict[str, Any] = {}

    def _block():
        try:
            from .fault_tolerance import chaos
            chaos.maybe_stall_collective(tag)
            box["out"] = fn()
        except BaseException as e:       # surfaced on the caller thread
            box["err"] = e
        finally:
            done.set()

    t = threading.Thread(target=_block, daemon=True,
                         name=f"comm-deadline-{tag}")
    t.start()
    if not done.wait(timeout):
        suspects = StragglerDetector.get().suspects()
        wd = CommWatchdog.get()
        with wd._mu:                     # ReliableStep's poll sees it too
            wd._timeouts.append(tag)
        from .fault_tolerance import flight_recorder
        flight_recorder.record("collective_timeout", tag=tag,
                               group=group_desc, timeout_s=timeout,
                               stragglers=list(suspects))
        flight_recorder.dump(f"collective_timeout:{tag}")
        exc = CollectiveTimeout(tag, group_desc, timeout, suspects,
                                dump_hint=flight_recorder.dump_hint())
        logger.error("%s", exc)
        if bool(flag_value("collective_abort_on_timeout")):
            logger.error("aborting process for gang restart "
                         "(AbortComm semantics)")
            os._exit(134)
        raise exc
    if "err" in box:
        raise box["err"]
    return box.get("out")


def wait_with_deadline(tag: str, arrays, timeout: float,
                       group_desc: str = "world") -> None:
    """Block on an ALREADY-DISPATCHED collective's result buffers for at
    most ``timeout`` seconds (late completion only reads the buffers —
    no side effects to suppress)."""
    def _block():
        import jax
        jax.block_until_ready(arrays)

    run_with_deadline(tag, _block, timeout, group_desc=group_desc)
