"""Collective watchdog (reference paddle/phi/core/distributed/
comm_task_manager.h:37: background CommTaskLoop threads that detect
timed-out NCCL collectives, log diagnostics, and abort the communicator).

On TPU there is no communicator to abort — a hung collective means a hung
XLA execution (usually a desynced gang in multi-host). The watchdog
mirrors the reference's split:

  * a WAITER thread per watched operation blocks on the result buffers;
  * the MONITOR thread flags operations that outlive their deadline,
    logging a diagnostic with the op tag (and every other in-flight op,
    the usual clue for a rank mismatch) and, with
    FLAGS_collective_abort_on_timeout, killing the process so the
    launcher's gang supervision (launch/main.py) can restart the job —
    the moral twin of NCCLCommTask::AbortComm + store error propagation.

Enable with FLAGS_collective_timeout_s > 0 (off by default: the waiter
threads cost a sync per collective, like the reference's debug watchdog).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

from ..flags import define_flag, flag_value
from .utils import get_logger

define_flag("collective_timeout_s", 0.0,
            "Watchdog timeout for dispatched collectives (seconds); 0 "
            "disables the watchdog entirely.")
define_flag("collective_abort_on_timeout", False,
            "Kill the process when a collective times out so the "
            "launcher can restart the gang (CommTaskManager abort "
            "semantics).")

logger = get_logger(name=__name__)


class CommWatchdog:
    """Tracks in-flight collectives; singleton via get()."""

    _instance: Optional["CommWatchdog"] = None
    _lock = threading.Lock()

    def __init__(self):
        self._inflight: Dict[int, Dict[str, Any]] = {}
        self._next_id = 0
        self._mu = threading.Lock()
        self._monitor: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # tags of ops the monitor flagged as overrun, drained by
        # consume_timeouts() — how ReliableStep learns a step's
        # collective hung (detect -> recover wiring)
        self._timeouts: list = []

    @classmethod
    def get(cls) -> "CommWatchdog":
        with cls._lock:
            if cls._instance is None:
                cls._instance = CommWatchdog()
            return cls._instance

    # -- public ----------------------------------------------------------
    def enabled(self) -> bool:
        return float(flag_value("collective_timeout_s")) > 0.0

    def watch(self, tag: str, arrays) -> None:
        """Register a dispatched collective; a waiter thread blocks on
        the buffers and clears the entry when they materialize."""
        if not self.enabled():
            return
        timeout = float(flag_value("collective_timeout_s"))
        with self._mu:
            op_id = self._next_id
            self._next_id += 1
            self._inflight[op_id] = {
                "tag": tag, "start": time.monotonic(),
                "deadline": time.monotonic() + timeout, "fired": False,
            }
        waiter = threading.Thread(target=self._wait, args=(op_id, arrays),
                                  daemon=True,
                                  name=f"comm-waiter-{op_id}")
        waiter.start()
        self._ensure_monitor()

    # -- internals -------------------------------------------------------
    def _wait(self, op_id: int, arrays) -> None:
        try:
            from .fault_tolerance import chaos
            chaos.maybe_delay_collective(self._tag(op_id))
            import jax
            jax.block_until_ready(arrays)
        except Exception as e:  # execution error counts as completion
            logger.warning("collective %s failed: %s",
                           self._tag(op_id), e)
        finally:
            with self._mu:
                self._inflight.pop(op_id, None)

    def _tag(self, op_id: int) -> str:
        with self._mu:
            entry = self._inflight.get(op_id)
            return entry["tag"] if entry else f"op{op_id}"

    def _ensure_monitor(self) -> None:
        # under _mu: pairs with the monitor's park-on-empty exit (which
        # clears _monitor under the same lock), closing the TOCTOU window
        # where a fresh op could see a dying-but-alive monitor and end up
        # unmonitored
        with self._mu:
            if self._monitor is None or not self._monitor.is_alive():
                self._stop.clear()
                self._monitor = threading.Thread(
                    target=self._monitor_loop, daemon=True,
                    name="comm-watchdog")
                self._monitor.start()

    def _monitor_loop(self) -> None:
        while not self._stop.wait(0.25):
            now = time.monotonic()
            overdue = []
            with self._mu:
                if not self._inflight:
                    self._monitor = None  # park; next watch() respawns
                    return
                for op_id, e in self._inflight.items():
                    if now > e["deadline"] and not e["fired"]:
                        e["fired"] = True
                        overdue.append((op_id, dict(e)))
                pending = [e["tag"] for e in self._inflight.values()]
            if overdue:
                with self._mu:
                    self._timeouts.extend(e["tag"] for _, e in overdue)
            for op_id, e in overdue:
                logger.error(
                    "collective TIMEOUT after %.1fs: %s (in-flight: %s) — "
                    "likely a desynced gang: some rank never dispatched "
                    "the matching collective (comm_task_manager.h "
                    "IsTimeout semantics)",
                    now - e["start"], e["tag"], pending)
                if bool(flag_value("collective_abort_on_timeout")):
                    logger.error("aborting process for gang restart "
                                 "(AbortComm semantics)")
                    os._exit(134)

    def consume_timeouts(self) -> list:
        """Drain and return the tags flagged as overrun since the last
        call. Polled by ReliableStep after each step so a hung-then-
        recovered collective triggers an in-job retry instead of
        silently training on a desynced gang."""
        with self._mu:
            out, self._timeouts = self._timeouts, []
            return out

    # test hook ----------------------------------------------------------
    def inflight_count(self) -> int:
        with self._mu:
            return len(self._inflight)


def watch(tag: str, arrays) -> None:
    """Module-level convenience used by collective dispatch."""
    wd = CommWatchdog.get()
    if wd.enabled():
        wd.watch(tag, arrays)
