"""paddle.distribution (reference python/paddle/distribution/*.py;
independent jnp implementation over the framework RNG).

Sampling draws keys from the global generator (framework/random.py), so
``paddle.seed`` reproduces draws; log_prob/entropy are differentiable
tensor ops recorded on the tape.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional, Sequence, Tuple, Type

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..framework import random as fr
from ..ops.dispatch import apply_op, ensure_tensor

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Beta", "Dirichlet", "Multinomial", "Laplace", "Gumbel",
           "LogNormal", "Geometric", "Poisson", "ExponentialFamily",
           "kl_divergence", "register_kl"]


def _arr(x):
    return ensure_tensor(x)._data.astype(jnp.float32) \
        if not isinstance(x, (int, float)) else jnp.float32(x)


def _t(a) -> Tensor:
    return Tensor(a, stop_gradient=True)


def _op(name, fn, *tensors):
    ts = tuple(ensure_tensor(t) for t in tensors)
    return apply_op(name, fn, ts, {})


class Distribution:
    """distribution/distribution.py:40 parity."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from ..ops.math import exp
        return exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    """distribution/normal.py:33."""

    def __init__(self, loc, scale, name=None):
        self.loc = ensure_tensor(loc)
        self.scale = ensure_tensor(scale)
        super().__init__(tuple(jnp.broadcast_shapes(
            tuple(self.loc.shape), tuple(self.scale.shape))))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return _op("square", jnp.square, self.scale)

    @property
    def stddev(self):
        return self.scale

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        eps = jax.random.normal(fr.next_key(), shape, jnp.float32)
        return _t(self.loc._data + eps * self.scale._data)

    def rsample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        eps = jax.random.normal(fr.next_key(), shape, jnp.float32)
        return _op("normal_rsample",
                   lambda l, s: l + eps * s, self.loc, self.scale)

    def log_prob(self, value):
        def f(v, l, s):
            var = jnp.square(s)
            return (-jnp.square(v - l) / (2 * var)
                    - jnp.log(s) - 0.5 * math.log(2 * math.pi))
        return _op("normal_log_prob", f, value, self.loc, self.scale)

    def entropy(self):
        return _op("normal_entropy",
                   lambda s: 0.5 + 0.5 * math.log(2 * math.pi)
                   + jnp.log(s) + jnp.zeros(self.batch_shape), self.scale)

    def cdf(self, value):
        return _op("normal_cdf",
                   lambda v, l, s: 0.5 * (1 + jax.scipy.special.erf(
                       (v - l) / (s * math.sqrt(2)))),
                   value, self.loc, self.scale)


class LogNormal(Normal):
    @property
    def mean(self):
        return _op("lognormal_mean",
                   lambda l, s: jnp.exp(l + jnp.square(s) / 2),
                   self.loc, self.scale)

    @property
    def variance(self):
        return _op("lognormal_var",
                   lambda l, s: (jnp.exp(jnp.square(s)) - 1)
                   * jnp.exp(2 * l + jnp.square(s)),
                   self.loc, self.scale)

    @property
    def stddev(self):
        return _op("sqrt", jnp.sqrt, self.variance)

    def sample(self, shape=(), seed=0):
        return _t(jnp.exp(super().sample(shape)._data))

    def log_prob(self, value):
        def f(v, l, s):
            lv = jnp.log(v)
            return (-jnp.square(lv - l) / (2 * jnp.square(s))
                    - jnp.log(s * v) - 0.5 * math.log(2 * math.pi))
        return _op("lognormal_log_prob", f, value, self.loc, self.scale)


class Uniform(Distribution):
    """distribution/uniform.py:32."""

    def __init__(self, low, high, name=None):
        self.low = ensure_tensor(low)
        self.high = ensure_tensor(high)
        super().__init__(tuple(jnp.broadcast_shapes(
            tuple(self.low.shape), tuple(self.high.shape))))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(fr.next_key(), shape, jnp.float32)
        return _t(self.low._data + u * (self.high._data - self.low._data))

    def log_prob(self, value):
        def f(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)
        return _op("uniform_log_prob", f, value, self.low, self.high)

    def entropy(self):
        return _op("uniform_entropy", lambda lo, hi: jnp.log(hi - lo),
                   self.low, self.high)


class Categorical(Distribution):
    """distribution/categorical.py:34 (logits parameterization)."""

    def __init__(self, logits=None, probs=None, name=None):
        if logits is None and probs is None:
            raise ValueError("need logits or probs")
        if logits is None:
            logits = _op("log", jnp.log, ensure_tensor(probs))
        self.logits = ensure_tensor(logits)
        super().__init__(tuple(self.logits.shape[:-1]))

    @property
    def probs(self):
        return _op("softmax", lambda l: jax.nn.softmax(l, -1), self.logits)

    def sample(self, shape=(), seed=0):
        idx = jax.random.categorical(fr.next_key(), self.logits._data,
                                     shape=tuple(shape) + self.batch_shape)
        return _t(idx)

    def log_prob(self, value):
        def f(lg, v):
            logp = jax.nn.log_softmax(lg, -1)
            return jnp.take_along_axis(
                logp, v[..., None].astype(jnp.int32), -1)[..., 0]
        return _op("categorical_log_prob", f, self.logits,
                   ensure_tensor(value))

    def entropy(self):
        def f(lg):
            logp = jax.nn.log_softmax(lg, -1)
            return -jnp.sum(jnp.exp(logp) * logp, -1)
        return _op("categorical_entropy", f, self.logits)


class Bernoulli(Distribution):
    """distribution/bernoulli.py."""

    def __init__(self, probs, name=None):
        self.probs_t = ensure_tensor(probs)
        super().__init__(tuple(self.probs_t.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(fr.next_key(), shape, jnp.float32)
        return _t((u < self.probs_t._data).astype(jnp.float32))

    def log_prob(self, value):
        def f(p, v):
            eps = 1e-7
            p = jnp.clip(p, eps, 1 - eps)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return _op("bernoulli_log_prob", f, self.probs_t,
                   ensure_tensor(value))

    def entropy(self):
        def f(p):
            eps = 1e-7
            p = jnp.clip(p, eps, 1 - eps)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))
        return _op("bernoulli_entropy", f, self.probs_t)


class Beta(Distribution):
    """distribution/beta.py."""

    def __init__(self, alpha, beta, name=None):
        self.alpha = ensure_tensor(alpha)
        self.beta = ensure_tensor(beta)
        super().__init__(tuple(jnp.broadcast_shapes(
            tuple(self.alpha.shape), tuple(self.beta.shape))))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        out = jax.random.beta(fr.next_key(), self.alpha._data,
                              self.beta._data, shape)
        return _t(out)

    def log_prob(self, value):
        def f(a, b, v):
            from jax.scipy.special import betaln
            return ((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                    - betaln(a, b))
        return _op("beta_log_prob", f, self.alpha, self.beta,
                   ensure_tensor(value))

    @property
    def mean(self):
        return _op("beta_mean", lambda a, b: a / (a + b), self.alpha,
                   self.beta)


class Dirichlet(Distribution):
    """distribution/dirichlet.py."""

    def __init__(self, concentration, name=None):
        self.concentration = ensure_tensor(concentration)
        super().__init__(tuple(self.concentration.shape[:-1]),
                         tuple(self.concentration.shape[-1:]))

    def sample(self, shape=(), seed=0):
        out = jax.random.dirichlet(fr.next_key(),
                                   self.concentration._data,
                                   tuple(shape) + self.batch_shape)
        return _t(out)

    def log_prob(self, value):
        def f(c, v):
            from jax.scipy.special import gammaln
            return (jnp.sum((c - 1) * jnp.log(v), -1)
                    + gammaln(jnp.sum(c, -1)) - jnp.sum(gammaln(c), -1))
        return _op("dirichlet_log_prob", f, self.concentration,
                   ensure_tensor(value))


class Multinomial(Distribution):
    """distribution/multinomial.py."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs_t = ensure_tensor(probs)
        super().__init__(tuple(self.probs_t.shape[:-1]),
                         tuple(self.probs_t.shape[-1:]))

    def sample(self, shape=(), seed=0):
        n = self.total_count
        idx = jax.random.categorical(
            fr.next_key(), jnp.log(self.probs_t._data),
            shape=(n,) + tuple(shape) + self.batch_shape)
        k = self.probs_t.shape[-1]
        counts = jnp.sum(jax.nn.one_hot(idx, k, dtype=jnp.float32), axis=0)
        return _t(counts)

    def log_prob(self, value):
        def f(p, v):
            from jax.scipy.special import gammaln
            return (gammaln(jnp.sum(v, -1) + 1)
                    - jnp.sum(gammaln(v + 1), -1)
                    + jnp.sum(v * jnp.log(p), -1))
        return _op("multinomial_log_prob", f, self.probs_t,
                   ensure_tensor(value))


class Laplace(Distribution):
    """distribution/laplace.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = ensure_tensor(loc)
        self.scale = ensure_tensor(scale)
        super().__init__(tuple(jnp.broadcast_shapes(
            tuple(self.loc.shape), tuple(self.scale.shape))))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        out = jax.random.laplace(fr.next_key(), shape, jnp.float32)
        return _t(self.loc._data + self.scale._data * out)

    def log_prob(self, value):
        return _op("laplace_log_prob",
                   lambda l, s, v: -jnp.abs(v - l) / s - jnp.log(2 * s),
                   self.loc, self.scale, ensure_tensor(value))

    def entropy(self):
        return _op("laplace_entropy", lambda s: 1 + jnp.log(2 * s),
                   self.scale)


class Gumbel(Distribution):
    """distribution/gumbel.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = ensure_tensor(loc)
        self.scale = ensure_tensor(scale)
        super().__init__(tuple(jnp.broadcast_shapes(
            tuple(self.loc.shape), tuple(self.scale.shape))))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        g = jax.random.gumbel(fr.next_key(), shape, jnp.float32)
        return _t(self.loc._data + self.scale._data * g)

    def log_prob(self, value):
        def f(l, s, v):
            z = (v - l) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)
        return _op("gumbel_log_prob", f, self.loc, self.scale,
                   ensure_tensor(value))


class Geometric(Distribution):
    def __init__(self, probs, name=None):
        self.probs_t = ensure_tensor(probs)
        super().__init__(tuple(self.probs_t.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(fr.next_key(), shape, jnp.float32)
        return _t(jnp.floor(jnp.log1p(-u)
                            / jnp.log1p(-self.probs_t._data)))

    def log_prob(self, value):
        return _op("geometric_log_prob",
                   lambda p, v: v * jnp.log1p(-p) + jnp.log(p),
                   self.probs_t, ensure_tensor(value))


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = ensure_tensor(rate)
        super().__init__(tuple(self.rate.shape))

    def sample(self, shape=(), seed=0):
        out = jax.random.poisson(fr.next_key(), self.rate._data,
                                 tuple(shape) + self.batch_shape)
        return _t(out.astype(jnp.float32))

    def log_prob(self, value):
        def f(r, v):
            from jax.scipy.special import gammaln
            return v * jnp.log(r) - r - gammaln(v + 1)
        return _op("poisson_log_prob", f, self.rate, ensure_tensor(value))


class ExponentialFamily(Distribution):
    pass


# ------------------------------------------------------------------- KL

_KL_REGISTRY: Dict[Tuple[type, type], Callable] = {}


def register_kl(type_p: type, type_q: type):
    """distribution/kl.py register_kl parity."""

    def decorator(fn):
        _KL_REGISTRY[(type_p, type_q)] = fn
        return fn

    return decorator


def _mro_dist(cls: type, base: type) -> int:
    return cls.__mro__.index(base)


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    # most-specific dispatch: exact match, then the registered pair with
    # minimal MRO distance (a subclass with a different sample space must
    # register its own entry rather than inherit the base formula)
    exact = _KL_REGISTRY.get((type(p), type(q)))
    if exact is not None:
        return exact(p, q)
    best = None
    best_d = None
    for (tp, tq), fn in _KL_REGISTRY.items():
        if isinstance(p, tp) and isinstance(q, tq):
            d = _mro_dist(type(p), tp) + _mro_dist(type(q), tq)
            if best_d is None or d < best_d:
                best, best_d = fn, d
    if best is not None:
        return best(p, q)
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__})")


@register_kl(Normal, Normal)
def _kl_normal_normal(p: Normal, q: Normal):
    def f(l1, s1, l2, s2):
        var1, var2 = jnp.square(s1), jnp.square(s2)
        return (jnp.log(s2 / s1) + (var1 + jnp.square(l1 - l2))
                / (2 * var2) - 0.5)
    return _op("kl_normal", f, p.loc, p.scale, q.loc, q.scale)


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p: Uniform, q: Uniform):
    def f(al, ah, bl, bh):
        ok = (bl <= al) & (ah <= bh)
        return jnp.where(ok, jnp.log((bh - bl) / (ah - al)), jnp.inf)
    return _op("kl_uniform", f, p.low, p.high, q.low, q.high)


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p: Categorical, q: Categorical):
    def f(lp, lq):
        a = jax.nn.log_softmax(lp, -1)
        b = jax.nn.log_softmax(lq, -1)
        return jnp.sum(jnp.exp(a) * (a - b), -1)
    return _op("kl_categorical", f, p.logits, q.logits)


@register_kl(Bernoulli, Bernoulli)
def _kl_bern_bern(p: Bernoulli, q: Bernoulli):
    def f(a, b):
        eps = 1e-7
        a = jnp.clip(a, eps, 1 - eps)
        b = jnp.clip(b, eps, 1 - eps)
        return a * jnp.log(a / b) + (1 - a) * jnp.log((1 - a) / (1 - b))
    return _op("kl_bernoulli", f, p.probs_t, q.probs_t)


@register_kl(LogNormal, LogNormal)
def _kl_lognormal_lognormal(p: LogNormal, q: LogNormal):
    # the log transform is a shared bijection: KL equals the underlying
    # normal KL
    return _kl_normal_normal(p, q)


def _no_kl(p, q):
    raise NotImplementedError(
        "KL between LogNormal and Normal has mismatched supports")


register_kl(LogNormal, Normal)(_no_kl)
register_kl(Normal, LogNormal)(_no_kl)


# ------------------------------------------------------------------ r5

class Exponential(ExponentialFamily):
    """distribution/exponential.py: rate-parameterized."""

    def __init__(self, rate, name=None):
        self.rate = ensure_tensor(rate)
        super().__init__(tuple(self.rate.shape))

    @property
    def mean(self):
        return _op("exp_mean", lambda r: 1.0 / r, self.rate)

    @property
    def variance(self):
        return _op("exp_var", lambda r: 1.0 / jnp.square(r), self.rate)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(fr.next_key(), shape, jnp.float32,
                               1e-7, 1.0)
        return _t(-jnp.log(u) / self.rate._data)

    rsample = sample

    def log_prob(self, value):
        return _op("exp_log_prob",
                   lambda v, r: jnp.log(r) - r * v, value, self.rate)

    def entropy(self):
        return _op("exp_entropy", lambda r: 1.0 - jnp.log(r), self.rate)

    def cdf(self, value):
        return _op("exp_cdf", lambda v, r: 1.0 - jnp.exp(-r * v),
                   value, self.rate)


class Gamma(ExponentialFamily):
    """distribution/gamma.py: concentration/rate."""

    def __init__(self, concentration, rate, name=None):
        self.concentration = ensure_tensor(concentration)
        self.rate = ensure_tensor(rate)
        super().__init__(tuple(jnp.broadcast_shapes(
            tuple(self.concentration.shape), tuple(self.rate.shape))))

    @property
    def mean(self):
        return _op("gamma_mean", lambda a, r: a / r,
                   self.concentration, self.rate)

    @property
    def variance(self):
        return _op("gamma_var", lambda a, r: a / jnp.square(r),
                   self.concentration, self.rate)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        g = jax.random.gamma(fr.next_key(),
                             jnp.broadcast_to(
                                 self.concentration._data, shape),
                             shape, jnp.float32)
        return _t(g / self.rate._data)

    rsample = sample

    def log_prob(self, value):
        def f(v, a, r):
            return (a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v
                    - jax.scipy.special.gammaln(a))
        return _op("gamma_log_prob", f, value, self.concentration,
                   self.rate)

    def entropy(self):
        def f(a, r):
            return (a - jnp.log(r) + jax.scipy.special.gammaln(a)
                    + (1.0 - a) * jax.scipy.special.digamma(a))
        return _op("gamma_entropy", f, self.concentration, self.rate)


class Chi2(Gamma):
    """distribution/chi2.py: Gamma(df/2, 1/2)."""

    def __init__(self, df, name=None):
        df_t = ensure_tensor(df)
        self.df = df_t
        # float math regardless of an integer df input
        super().__init__(
            _op("chi2_a", lambda d: d.astype(jnp.float32) / 2.0, df_t),
            _op("chi2_r",
                lambda d: jnp.full(jnp.shape(d), 0.5, jnp.float32),
                df_t))


class Cauchy(Distribution):
    """distribution/cauchy.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = ensure_tensor(loc)
        self.scale = ensure_tensor(scale)
        super().__init__(tuple(jnp.broadcast_shapes(
            tuple(self.loc.shape), tuple(self.scale.shape))))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(fr.next_key(), shape, jnp.float32,
                               1e-6, 1 - 1e-6)
        return _t(self.loc._data
                  + self.scale._data * jnp.tan(jnp.pi * (u - 0.5)))

    rsample = sample

    def log_prob(self, value):
        def f(v, l, s):
            return (-jnp.log(jnp.pi) - jnp.log(s)
                    - jnp.log1p(jnp.square((v - l) / s)))
        return _op("cauchy_log_prob", f, value, self.loc, self.scale)

    def entropy(self):
        return _op("cauchy_entropy",
                   lambda s: jnp.log(4 * jnp.pi * s), self.scale)

    def cdf(self, value):
        def f(v, l, s):
            return jnp.arctan((v - l) / s) / jnp.pi + 0.5
        return _op("cauchy_cdf", f, value, self.loc, self.scale)


class StudentT(Distribution):
    """distribution/student_t.py: df/loc/scale."""

    def __init__(self, df, loc, scale, name=None):
        self.df = ensure_tensor(df)
        self.loc = ensure_tensor(loc)
        self.scale = ensure_tensor(scale)
        super().__init__(tuple(jnp.broadcast_shapes(
            tuple(self.df.shape), tuple(self.loc.shape),
            tuple(self.scale.shape))))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        def f(df, s):
            return jnp.where(df > 2, jnp.square(s) * df / (df - 2),
                             jnp.inf)
        return _op("t_var", f, self.df, self.scale)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        t = jax.random.t(fr.next_key(),
                         jnp.broadcast_to(self.df._data, shape), shape,
                         jnp.float32)
        return _t(self.loc._data + self.scale._data * t)

    rsample = sample

    def log_prob(self, value):
        def f(v, df, l, s):
            z = (v - l) / s
            return (jax.scipy.special.gammaln((df + 1) / 2)
                    - jax.scipy.special.gammaln(df / 2)
                    - 0.5 * jnp.log(df * jnp.pi) - jnp.log(s)
                    - (df + 1) / 2 * jnp.log1p(jnp.square(z) / df))
        return _op("t_log_prob", f, value, self.df, self.loc, self.scale)

    def entropy(self):
        def f(df, s):
            hp = (df + 1) / 2
            return (jnp.log(s) + 0.5 * jnp.log(df)
                    + jax.scipy.special.betaln(df / 2, 0.5)
                    + hp * (jax.scipy.special.digamma(hp)
                            - jax.scipy.special.digamma(df / 2)))
        return _op("t_entropy", f, self.df, self.scale)


class Binomial(Distribution):
    """distribution/binomial.py: total_count/probs."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = ensure_tensor(total_count)
        self.probs = ensure_tensor(probs)
        super().__init__(tuple(jnp.broadcast_shapes(
            tuple(self.total_count.shape), tuple(self.probs.shape))))

    @property
    def mean(self):
        return _op("binom_mean", lambda n, p: n * p, self.total_count,
                   self.probs)

    @property
    def variance(self):
        return _op("binom_var", lambda n, p: n * p * (1 - p),
                   self.total_count, self.probs)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        n = jnp.broadcast_to(self.total_count._data, shape)
        p = jnp.broadcast_to(self.probs._data, shape)
        # sum of Bernoulli draws via binomial sampler
        out = jax.random.binomial(fr.next_key(), n.astype(jnp.float32),
                                  p, shape)
        return _t(out.astype(jnp.float32))

    def log_prob(self, value):
        def f(v, n, p):
            return (jax.scipy.special.gammaln(n + 1)
                    - jax.scipy.special.gammaln(v + 1)
                    - jax.scipy.special.gammaln(n - v + 1)
                    + v * jnp.log(p) + (n - v) * jnp.log1p(-p))
        return _op("binom_log_prob", f, value, self.total_count,
                   self.probs)

    def entropy(self):
        def f(n, p):
            # exact sum over the support (n is data-dependent but small
            # in practice; uses the max n in the batch)
            nmax = jnp.max(n).astype(jnp.int32)
            k = jnp.arange(nmax + 1, dtype=jnp.float32)
            logpmf = (jax.scipy.special.gammaln(n[..., None] + 1)
                      - jax.scipy.special.gammaln(k + 1)
                      - jax.scipy.special.gammaln(n[..., None] - k + 1)
                      + k * jnp.log(p[..., None])
                      + (n[..., None] - k) * jnp.log1p(-p[..., None]))
            valid = k <= n[..., None]
            pmf = jnp.where(valid, jnp.exp(logpmf), 0.0)
            return -jnp.sum(pmf * jnp.where(valid, logpmf, 0.0), -1)
        return _op("binom_entropy", f, self.total_count, self.probs)


class ContinuousBernoulli(Distribution):
    """distribution/continuous_bernoulli.py (Loaiza-Ganem & Cunningham)."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = ensure_tensor(probs)
        self._lims = lims
        super().__init__(tuple(self.probs.shape))

    def _log_C(self, p):
        # normalizing constant, Taylor-stabilized near p=0.5
        near = (p > self._lims[0]) & (p < self._lims[1])
        p_safe = jnp.where(near, 0.4, p)
        c = jnp.log(2 * jnp.arctanh(1 - 2 * p_safe)
                    / (1 - 2 * p_safe))
        x = p - 0.5
        taylor = jnp.log(2.0) + 4.0 / 3.0 * x ** 2 + 104.0 / 45.0 * x ** 4
        return jnp.where(near, taylor, c)

    @property
    def mean(self):
        def f(p):
            near = (p > self._lims[0]) & (p < self._lims[1])
            p_safe = jnp.where(near, 0.4, p)
            m = p_safe / (2 * p_safe - 1) \
                + 1.0 / (2 * jnp.arctanh(1 - 2 * p_safe))
            return jnp.where(near, 0.5, m)
        return _op("cb_mean", f, self.probs)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(fr.next_key(), shape, jnp.float32,
                               1e-6, 1 - 1e-6)
        p = jnp.broadcast_to(self.probs._data, shape)
        near = (p > self._lims[0]) & (p < self._lims[1])
        p_safe = jnp.where(near, 0.4, p)
        icdf = (jnp.log1p(u * (2 * p_safe - 1) / (1 - p_safe))
                / (jnp.log(p_safe) - jnp.log1p(-p_safe)))
        return _t(jnp.where(near, u, icdf))

    rsample = sample

    def log_prob(self, value):
        def f(v, p):
            return (v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
                    + self._log_C(p))
        return _op("cb_log_prob", f, value, self.probs)


class MultivariateNormal(Distribution):
    """distribution/multivariate_normal.py: loc + covariance_matrix."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = ensure_tensor(loc)
        if scale_tril is not None:
            self._tril = ensure_tensor(scale_tril)._data
        elif covariance_matrix is not None:
            self._tril = jnp.linalg.cholesky(
                ensure_tensor(covariance_matrix)._data)
        elif precision_matrix is not None:
            prec = ensure_tensor(precision_matrix)._data
            self._tril = jnp.linalg.cholesky(jnp.linalg.inv(prec))
        else:
            raise ValueError("need covariance_matrix / precision_matrix "
                             "/ scale_tril")
        super().__init__(tuple(self.loc.shape[:-1]),
                         (int(self.loc.shape[-1]),))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return _t(jnp.sum(jnp.square(self._tril), axis=-1))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape + self.event_shape
        eps = jax.random.normal(fr.next_key(), shape, jnp.float32)
        return _t(self.loc._data
                  + jnp.einsum("...ij,...j->...i", self._tril, eps))

    rsample = sample

    def log_prob(self, value):
        tril = self._tril
        def f(v, l):
            d = v - l
            z = jax.scipy.linalg.solve_triangular(tril, d[..., None],
                                                  lower=True)[..., 0]
            half_logdet = jnp.sum(jnp.log(jnp.diagonal(
                tril, axis1=-2, axis2=-1)), -1)
            k = v.shape[-1]
            return (-0.5 * jnp.sum(jnp.square(z), -1) - half_logdet
                    - 0.5 * k * jnp.log(2 * jnp.pi))
        return _op("mvn_log_prob", f, value, self.loc)

    def entropy(self):
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(
            self._tril, axis1=-2, axis2=-1)), -1)
        k = self.event_shape[0]
        return _t(0.5 * k * (1 + jnp.log(2 * jnp.pi)) + half_logdet)


class Independent(Distribution):
    """distribution/independent.py: reinterpret batch dims as event."""

    def __init__(self, base, reinterpreted_batch_rank, name=None):
        self.base = base
        self._rank = int(reinterpreted_batch_rank)
        bs = tuple(base.batch_shape)
        super().__init__(bs[:len(bs) - self._rank],
                         bs[len(bs) - self._rank:])

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        def f(a):
            return jnp.sum(a, axis=tuple(range(a.ndim - self._rank,
                                               a.ndim)))
        return _op("indep_log_prob", f, lp)

    def entropy(self):
        e = self.base.entropy()
        def f(a):
            return jnp.sum(a, axis=tuple(range(a.ndim - self._rank,
                                               a.ndim)))
        return _op("indep_entropy", f, e)


class TransformedDistribution(Distribution):
    """distribution/transformed_distribution.py: base pushed through a
    chain of transforms (paddle.distribution.transform objects or any
    object with forward / inverse / forward_log_det_jacobian)."""

    def __init__(self, base, transforms, name=None):
        self.base = base
        self.transforms = list(transforms)
        super().__init__(tuple(base.batch_shape))

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=()):
        x = self.base.rsample(shape) if hasattr(self.base, "rsample") \
            else self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        lp = None
        v = ensure_tensor(value)
        # walk backwards through the chain
        for t in reversed(self.transforms):
            x = t.inverse(v)
            ladj = t.forward_log_det_jacobian(x)
            lp = ladj if lp is None else _op(
                "td_acc", lambda a, b: a + b, lp, ladj)
            v = x
        base_lp = self.base.log_prob(v)
        if lp is None:
            return base_lp
        return _op("td_log_prob", lambda a, b: a - b, base_lp, lp)


class LKJCholesky(Distribution):
    """distribution/lkj_cholesky.py: LKJ prior over correlation-matrix
    Cholesky factors (onion-method sampling)."""

    def __init__(self, dim, concentration=1.0,
                 sample_method="onion", name=None):
        self.dim = int(dim)
        self.concentration = ensure_tensor(concentration)
        super().__init__(tuple(self.concentration.shape))

    def sample(self, shape=()):
        d = self.dim
        eta = float(jnp.reshape(self.concentration._data, (-1,))[0])
        shape = tuple(shape)
        # onion method (Lewandowski et al. 2009)
        L = jnp.zeros(shape + (d, d), jnp.float32)
        L = L.at[..., 0, 0].set(1.0)
        beta = eta + (d - 2) / 2.0
        for i in range(1, d):
            b = jax.random.beta(fr.next_key(), i / 2.0, beta,
                                shape, jnp.float32)
            beta = beta - 0.5
            u = jax.random.normal(fr.next_key(), shape + (i,),
                                  jnp.float32)
            u = u / jnp.linalg.norm(u, axis=-1, keepdims=True)
            w = jnp.sqrt(b)[..., None] * u
            L = L.at[..., i, :i].set(w)
            L = L.at[..., i, i].set(jnp.sqrt(1.0 - b))
        return _t(L)

    def log_prob(self, value):
        v = ensure_tensor(value)
        def f(L, eta):
            d = self.dim
            diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
            # L_ii (1-based row i = orders + 2) carries exponent
            # d - i + 2*eta - 2 (LKJ density, lkj_cholesky.py)
            orders = jnp.arange(d - 1, dtype=jnp.float32)
            exps = d - (orders + 2.0) + 2.0 * eta - 2.0
            unnorm = jnp.sum(exps * jnp.log(diag), -1)
            # normalization (lkj_cholesky.py log_normalizer)
            i = jnp.arange(1, d, dtype=jnp.float32)
            alpha = eta + (d - 1 - i) / 2.0
            lognorm = jnp.sum(
                0.5 * i * jnp.log(jnp.pi)
                + jax.scipy.special.gammaln(alpha)
                - jax.scipy.special.gammaln(alpha + i / 2.0))
            return unnorm - lognorm
        return _op("lkj_log_prob", f, v, self.concentration)


__all__ += ["Exponential", "Gamma", "Chi2", "Cauchy", "StudentT",
            "Binomial", "ContinuousBernoulli", "MultivariateNormal",
            "Independent", "TransformedDistribution", "LKJCholesky"]
