"""paddle.fft (reference python/paddle/fft.py) — jnp.fft bridged through
the op dispatcher so transforms are differentiable and jit-traceable.
Complex tensors ride the same Tensor wrapper (complex64/128 payloads)."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..ops.dispatch import apply_op, ensure_tensor

__all__ = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
           "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
           "hfft", "ihfft", "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _mk1(jnp_fn, op_name):
    def f(x, n=None, axis=-1, norm="backward", name=None):
        t = ensure_tensor(x)
        return apply_op(op_name, lambda a: jnp_fn(a, n=n, axis=axis,
                                                  norm=norm), (t,), {})
    f.__name__ = op_name
    f.__doc__ = f"python/paddle/fft.py {op_name} parity."
    return f


def _mk2(jnp_fn, op_name):
    def f(x, s=None, axes=(-2, -1), norm="backward", name=None):
        t = ensure_tensor(x)
        return apply_op(op_name, lambda a: jnp_fn(a, s=s, axes=axes,
                                                  norm=norm), (t,), {})
    f.__name__ = op_name
    return f


def _mkn(jnp_fn, op_name):
    def f(x, s=None, axes=None, norm="backward", name=None):
        t = ensure_tensor(x)
        return apply_op(op_name, lambda a: jnp_fn(a, s=s, axes=axes,
                                                  norm=norm), (t,), {})
    f.__name__ = op_name
    return f


fft = _mk1(jnp.fft.fft, "fft")
ifft = _mk1(jnp.fft.ifft, "ifft")
rfft = _mk1(jnp.fft.rfft, "rfft")
irfft = _mk1(jnp.fft.irfft, "irfft")
hfft = _mk1(jnp.fft.hfft, "hfft")
ihfft = _mk1(jnp.fft.ihfft, "ihfft")
fft2 = _mk2(jnp.fft.fft2, "fft2")
ifft2 = _mk2(jnp.fft.ifft2, "ifft2")
rfft2 = _mk2(jnp.fft.rfft2, "rfft2")
irfft2 = _mk2(jnp.fft.irfft2, "irfft2")
fftn = _mkn(jnp.fft.fftn, "fftn")
ifftn = _mkn(jnp.fft.ifftn, "ifftn")
rfftn = _mkn(jnp.fft.rfftn, "rfftn")
irfftn = _mkn(jnp.fft.irfftn, "irfftn")


def fftfreq(n, d=1.0, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or "float32"))


def rfftfreq(n, d=1.0, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or "float32"))


def fftshift(x, axes=None, name=None) -> Tensor:
    t = ensure_tensor(x)
    return apply_op("fftshift", lambda a: jnp.fft.fftshift(a, axes), (t,),
                    {})


def ifftshift(x, axes=None, name=None) -> Tensor:
    t = ensure_tensor(x)
    return apply_op("ifftshift", lambda a: jnp.fft.ifftshift(a, axes), (t,),
                    {})
