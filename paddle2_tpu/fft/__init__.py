"""paddle.fft (reference python/paddle/fft.py) — jnp.fft bridged through
the op dispatcher so transforms are differentiable and jit-traceable.
Complex tensors ride the same Tensor wrapper (complex64/128 payloads)."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..ops.dispatch import apply_op, ensure_tensor

__all__ = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
           "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
           "hfft", "ihfft", "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _mk1(jnp_fn, op_name):
    def f(x, n=None, axis=-1, norm="backward", name=None):
        t = ensure_tensor(x)
        return apply_op(op_name, lambda a: jnp_fn(a, n=n, axis=axis,
                                                  norm=norm), (t,), {})
    f.__name__ = op_name
    f.__doc__ = f"python/paddle/fft.py {op_name} parity."
    return f


def _mk2(jnp_fn, op_name):
    def f(x, s=None, axes=(-2, -1), norm="backward", name=None):
        t = ensure_tensor(x)
        return apply_op(op_name, lambda a: jnp_fn(a, s=s, axes=axes,
                                                  norm=norm), (t,), {})
    f.__name__ = op_name
    return f


def _mkn(jnp_fn, op_name):
    def f(x, s=None, axes=None, norm="backward", name=None):
        t = ensure_tensor(x)
        return apply_op(op_name, lambda a: jnp_fn(a, s=s, axes=axes,
                                                  norm=norm), (t,), {})
    f.__name__ = op_name
    return f


fft = _mk1(jnp.fft.fft, "fft")
ifft = _mk1(jnp.fft.ifft, "ifft")
rfft = _mk1(jnp.fft.rfft, "rfft")
irfft = _mk1(jnp.fft.irfft, "irfft")
hfft = _mk1(jnp.fft.hfft, "hfft")
ihfft = _mk1(jnp.fft.ihfft, "ihfft")
fft2 = _mk2(jnp.fft.fft2, "fft2")
ifft2 = _mk2(jnp.fft.ifft2, "ifft2")
rfft2 = _mk2(jnp.fft.rfft2, "rfft2")
irfft2 = _mk2(jnp.fft.irfft2, "irfft2")
fftn = _mkn(jnp.fft.fftn, "fftn")
ifftn = _mkn(jnp.fft.ifftn, "ifftn")
rfftn = _mkn(jnp.fft.rfftn, "rfftn")
irfftn = _mkn(jnp.fft.irfftn, "irfftn")


def fftfreq(n, d=1.0, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or "float32"))


def rfftfreq(n, d=1.0, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or "float32"))


def fftshift(x, axes=None, name=None) -> Tensor:
    t = ensure_tensor(x)
    return apply_op("fftshift", lambda a: jnp.fft.fftshift(a, axes), (t,),
                    {})


def ifftshift(x, axes=None, name=None) -> Tensor:
    t = ensure_tensor(x)
    return apply_op("ifftshift", lambda a: jnp.fft.ifftshift(a, axes), (t,),
                    {})


def _hfft_nd(a, s, axes, norm, inverse):
    """hfft over the last axis after plain (i)ffts over the others —
    the jnp.fft module has no hfft2/hfftn, but the reference defines
    them as Hermitian-in-last-axis n-d transforms (fft.py hfft2/hfftn)."""
    if axes is not None:
        axes = tuple(axes)
    elif s is not None:
        axes = tuple(range(-len(s), 0))
    else:
        axes = tuple(range(-a.ndim, 0))   # hfftn default: ALL axes
    pre, last = axes[:-1], axes[-1]
    sizes = list(s) if s is not None else [None] * len(axes)
    if inverse:
        # r2c along the LAST axis first (ihfft needs the real input),
        # then inverse ffts over the remaining axes
        out = jnp.fft.ihfft(a, n=sizes[-1], axis=last, norm=norm)
        for ax, n in zip(pre, sizes[:-1]):
            out = jnp.fft.ifft(out, n=n, axis=ax, norm=norm)
        return out
    out = a
    for ax, n in zip(pre, sizes[:-1]):
        out = jnp.fft.fft(out, n=n, axis=ax, norm=norm)
    return jnp.fft.hfft(out, n=sizes[-1], axis=last, norm=norm)


def _mk_h(op_name, inverse, default_axes):
    def f(x, s=None, axes=default_axes, norm="backward", name=None):
        t = ensure_tensor(x)
        return apply_op(op_name,
                        lambda a: _hfft_nd(a, s, axes, norm, inverse),
                        (t,), {})
    f.__name__ = op_name
    f.__doc__ = f"python/paddle/fft.py {op_name} parity."
    return f


hfft2 = _mk_h("hfft2", False, (-2, -1))
ihfft2 = _mk_h("ihfft2", True, (-2, -1))
hfftn = _mk_h("hfftn", False, None)     # None -> all axes at call time
ihfftn = _mk_h("ihfftn", True, None)

__all__ += ["hfft2", "ihfft2", "hfftn", "ihfftn"]
