"""Global flag registry.

TPU-native analog of the reference's gflags-style global flag system
(``paddle/common/flags.cc`` — 184 ``PHI_DEFINE_EXPORTED_*`` entries, readable and
writable from Python via ``paddle.set_flags``/``get_flags``,
``python/paddle/base/framework.py:132``). Flags are env-overridable with the
``FLAGS_`` prefix, typed, and registered at import time by the subsystems that
consume them.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Union


class _Flag:
    __slots__ = ("name", "value", "default", "type", "help", "on_change")

    def __init__(self, name: str, default: Any, help_str: str,
                 type_: type, on_change: Optional[Callable[[Any], None]] = None):
        self.name = name
        self.default = default
        self.type = type_
        self.help = help_str
        self.on_change = on_change
        self.value = default


_REGISTRY: Dict[str, _Flag] = {}
_LOCK = threading.RLock()


def _coerce(flag: _Flag, value: Any) -> Any:
    if flag.type is bool and isinstance(value, str):
        return value.lower() in ("1", "true", "yes", "on")
    return flag.type(value)


def define_flag(name: str, default: Any, help_str: str = "",
                on_change: Optional[Callable[[Any], None]] = None) -> None:
    """Register a flag. Environment ``FLAGS_<name>`` overrides the default."""
    with _LOCK:
        if name in _REGISTRY:
            return
        flag = _Flag(name, default, help_str, type(default), on_change)
        env = os.environ.get("FLAGS_" + name)
        if env is not None:
            flag.value = _coerce(flag, env)
        _REGISTRY[name] = flag


def set_flags(flags: Dict[str, Any]) -> None:
    """Set one or more registered flags (``paddle.set_flags`` parity)."""
    with _LOCK:
        for name, value in flags.items():
            key = name[6:] if name.startswith("FLAGS_") else name
            if key not in _REGISTRY:
                raise ValueError(f"unknown flag {name!r}")
            flag = _REGISTRY[key]
            flag.value = _coerce(flag, value)
            if flag.on_change is not None:
                flag.on_change(flag.value)


def get_flags(flags: Union[str, Iterable[str], None] = None) -> Dict[str, Any]:
    """Read registered flags (``paddle.get_flags`` parity)."""
    with _LOCK:
        if flags is None:
            names: List[str] = list(_REGISTRY)
        elif isinstance(flags, str):
            names = [flags]
        else:
            names = list(flags)
        out = {}
        for name in names:
            key = name[6:] if name.startswith("FLAGS_") else name
            if key not in _REGISTRY:
                raise ValueError(f"unknown flag {name!r}")
            out["FLAGS_" + key] = _REGISTRY[key].value
        return out


def flag_value(name: str) -> Any:
    """Fast internal read of a single flag value."""
    return _REGISTRY[name].value


# Core flags (subsystem-specific flags are defined where they are used).
define_flag("check_nan_inf", False,
            "Per-op nan/inf checking in eager mode (nan_inf_utils parity).")
define_flag("enable_api_kernel_fallback", True,
            "Fall back to CPU execution when an op has no device lowering.")
define_flag("eager_vjp_cache", True,
            "Cache per-op linearized VJP computations keyed on shapes/dtypes.")
define_flag("log_level", 0, "Framework verbosity (VLOG-style).")
def _apply_compilation_cache(path: str) -> None:
    import jax
    # empty REALLY disables (clears a previously-set directory)
    jax.config.update("jax_compilation_cache_dir", path or None)
    if path:
        # min compile time gates what is worth persisting; the elastic
        # restart path (and tests) override via env — a respawned
        # worker wants EVERY train-step executable cached, since each
        # one is pure MTTR on the next recovery
        min_s = float(os.environ.get("PADDLE2_TPU_CACHE_MIN_COMPILE_S",
                                     "1.0"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_s)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
        except Exception:
            pass
    # the in-process cache singleton latches its configuration on first
    # compile: without a reset, enabling the directory AFTER anything
    # has compiled (the elastic restart path re-enables it at resume
    # time) would silently leave the persistent cache off
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:
        pass


define_flag("compilation_cache_dir", os.environ.get(
    "PADDLE2_TPU_CACHE_DIR", ""),
    "Persistent XLA compilation cache directory: repeat runs skip the "
    "30s+ first-compile of large programs (the executor program-cache "
    "persistence analog). Empty disables.",
    on_change=_apply_compilation_cache)
if _REGISTRY["compilation_cache_dir"].value:
    _apply_compilation_cache(_REGISTRY["compilation_cache_dir"].value)


define_flag("conv_prefer_channels_last", False,
            "Run NCHW conv2d internally in NHWC. Measured on v5e: +26% "
            "on an isolated 3x3 conv but only +0.8% on ResNet-50 "
            "end-to-end (XLA's layout assignment already optimizes the "
            "NCHW graph) — off by default; a knob for conv-heavy models "
            "where it measures better.")
define_flag("pallas_layer_norm", False,
            "Route last-axis affine LayerNorm through the fused Pallas "
            "kernel (kernels/pallas_ln.py) on TPU. Measured 0.30 vs "
            "0.44 ms/LN ISOLATED at [8192,1024] bf16 fwd+bwd on v5e, "
            "but 241 vs 229 ms/step on the GPT bench — the custom-call "
            "boundary blocks XLA's fusion with the surrounding "
            "residual/matmul ops and the remat policy re-runs the "
            "opaque forward in backward. Off by default; a knob for "
            "LN-dominated models.")
define_flag("max_program_cache_size", 32,
            "Guard-miss budget per to_static function: beyond this many "
            "compiled variants the function falls back to eager "
            "execution (SOT graph-break analog) instead of retracing "
            "per distinct value.")
define_flag("donate_optimizer_buffers", True,
            "Donate parameter/optimizer-state buffers to the fused update "
            "executable (XLA in-place aliasing; saves ~3x model size of HBM "
            "traffic per step). Disable if you hold aliases of parameter "
            "arrays across optimizer steps.")
define_flag("fused_optimizer_step", False,
            "Route AdamW/Momentum updates through the one-pass Pallas "
            "step kernels (kernels/pallas_fused.py fused_*_step): one "
            "HBM pass over (param, grad, moments) with in-place output "
            "aliases instead of XLA's multi-op chain and its staging "
            "copies. Bitwise-identical to the generic update on f32 "
            "state (bench --single-chip-speed gates it); per-optimizer "
            "fused= ctor kwarg overrides the flag either way.")


# -- XLA comm/compute-overlap knobs (multichip) -----------------------------
# The latency-hiding scheduler and async collectives are what turn the
# bucketed grad reduces and ZeRO-3 prefetch gathers from SERIAL wire
# time into overlapped wire time. They are compiler-process-wide
# XLA_FLAGS, so they are NEVER applied implicitly: only
# apply_multichip_xla_env() (called by launchers / hybrid_mesh for
# multichip TPU runs) mutates the environment, and only before backend
# init — a single-chip CPU test compile never sees them.
define_flag("xla_latency_hiding_scheduler", True,
            "Schedule XLA collectives with the latency-hiding scheduler "
            "so in-flight collectives overlap independent compute "
            "(bucketed grad reduces under backward, ZeRO-3 prefetch "
            "gathers under the previous layer). Takes effect only via "
            "apply_multichip_xla_env() before backend init; no-op on "
            "CPU.")
define_flag("xla_async_collectives", True,
            "Lower all-gather / all-reduce / collective-permute as "
            "async start/done pairs so the scheduler can move compute "
            "between them. Takes effect only via "
            "apply_multichip_xla_env() before backend init; no-op on "
            "CPU.")

# flag name -> XLA_FLAGS tokens it expands to (tokens carry explicit
# ={true|false} so disabling a knob can OVERRIDE an operator default)
_XLA_PERF_FLAG_TOKENS = {
    "xla_latency_hiding_scheduler": (
        "--xla_tpu_enable_latency_hiding_scheduler={v}",
        "--xla_tpu_overlap_compute_collective_tc={v}",
    ),
    "xla_async_collectives": (
        "--xla_enable_async_all_gather={v}",
        "--xla_enable_async_collective_permute={v}",
        "--xla_tpu_enable_async_collective_fusion={v}",
        "--xla_tpu_enable_async_collective_fusion_fuse_all_gather={v}",
    ),
}


def multichip_xla_flag_tokens() -> List[str]:
    """The XLA_FLAGS tokens the current knob values expand to."""
    out: List[str] = []
    for name, tokens in _XLA_PERF_FLAG_TOKENS.items():
        v = "true" if flag_value(name) else "false"
        out.extend(t.format(v=v) for t in tokens)
    return out


def _probe_tpu_devices() -> bool:
    """Host exposes TPU device nodes: ``/dev/accel*`` (the TPU driver's
    char devices), or a VFIO group backed by a Google (PCI vendor
    0x1ae0) accelerator (v5e+ attach via vfio). Bare ``/dev/vfio/*`` is
    NOT sufficient — GPU-passthrough VMs expose those too, and the
    TPU-only XLA flags abort XLA startup on non-TPU backends."""
    import glob
    if glob.glob("/dev/accel*"):
        return True
    if glob.glob("/dev/vfio/*"):
        for vf in glob.glob("/sys/bus/pci/devices/*/vendor"):
            try:
                with open(vf) as f:
                    if f.read().strip().lower() == "0x1ae0":
                        return True
            except OSError:
                continue
    return False


def _env_platform(env) -> str:
    """Best-effort target platform from the environment WITHOUT
    importing (or initializing) jax."""
    for key in ("JAX_PLATFORMS", "JAX_PLATFORM_NAME", "PJRT_DEVICE"):
        val = str(env.get(key, "")).strip().lower()
        if val:
            return val.split(",")[0]
    # no explicit platform: a Cloud TPU VM typically sets NONE of the
    # above (jax autodetects the chips) — probe the accelerator device
    # files directly so the overlap flags are not silently skipped on
    # the very hosts they exist for. Only consulted when env is the
    # real process environment (a caller-supplied env dict describes a
    # DIFFERENT process whose host we cannot see).
    if env is os.environ and _probe_tpu_devices():
        return "tpu"
    return ""


def apply_multichip_xla_env(env=None, platform: Optional[str] = None
                            ) -> str:
    """Append the overlap-scheduling XLA flags to ``env['XLA_FLAGS']``
    and return the resulting string.

    Guard rails, because XLA_FLAGS is process-wide: (a) NO-OP unless
    the target platform is TPU — ``platform`` explicit, else detected
    from env vars without touching jax, so a CPU test process is never
    mutated; (b) idempotent — a token already present (from the
    operator or a previous call) is never duplicated, and the
    operator's existing value WINS over the knob default."""
    env = os.environ if env is None else env
    plat = (platform or _env_platform(env) or "").lower()
    if not plat.startswith("tpu"):
        return env.get("XLA_FLAGS", "")
    existing = env.get("XLA_FLAGS", "")
    have = {t.split("=", 1)[0] for t in existing.split() if t}
    added = [t for t in multichip_xla_flag_tokens()
             if t.split("=", 1)[0] not in have]
    if added:
        env["XLA_FLAGS"] = " ".join(([existing] if existing else [])
                                    + added)
    return env.get("XLA_FLAGS", "")
