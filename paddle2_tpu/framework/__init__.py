from .core import (  # noqa: F401
    CPUPlace, CUDAPlace, CustomPlace, Place, TPUPlace,
    bool_, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
    float64, complex64, complex128,
    convert_dtype, current_place, device_count, enable_grad, get_default_dtype,
    get_device, is_compiled_with_cuda, is_compiled_with_tpu, is_grad_enabled,
    no_grad, set_default_dtype, set_device, set_grad_enabled, synchronize,
)
from .random import seed, get_rng_state, set_rng_state  # noqa: F401
from .tensor import Parameter, Tensor, to_tensor  # noqa: F401
