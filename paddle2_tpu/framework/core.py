"""Framework core: dtypes, places, device selection, global modes.

TPU-native equivalent of the reference's place/dtype machinery
(``paddle/phi/common/place.h``, ``python/paddle/device/__init__.py:281``
``set_device``). Devices are JAX/PJRT devices; ``TPUPlace`` maps to a PJRT TPU
device, ``CPUPlace`` to host. There are no streams/events to manage — PJRT's
async dispatch plays that role (SURVEY.md §5.8).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

# float32 matmuls must be true fp32 (reference parity). bf16 training — the
# TPU-fast path — passes real bf16 operands, which hit the MXU natively and
# are unaffected by this setting.
jax.config.update("jax_default_matmul_precision", "highest")

# ---------------------------------------------------------------------------
# dtypes — exposed paddle-style (paddle.float32 is a usable dtype object)
# ---------------------------------------------------------------------------

# TPU has no native 64-bit arithmetic (XLA emulates int64 as int32 pairs and
# has no f64 path worth using); the framework runs x32 like JAX's default and
# treats 64-bit dtype requests as their 32-bit equivalents. This is a
# deliberate, documented policy — `paddle.int64` IS int32 here — so dtype
# equality checks in ported code keep working instead of silently diverging.
bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int32
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float32
complex64 = jnp.complex64
complex128 = jnp.complex64

_DTYPE_ALIASES = {
    "bool": bool_, "uint8": uint8, "int8": int8, "int16": int16,
    "int32": int32, "int64": int64, "uint32": jnp.uint32, "uint64": jnp.uint32,
    "float16": float16, "bfloat16": bfloat16,
    "float32": float32, "float64": float64, "complex64": complex64,
    "complex128": complex128,
}


def convert_dtype(dtype: Any) -> Any:
    """Normalize a user-supplied dtype (str / np / jnp) to a jnp dtype,
    applying the x32 policy (64-bit names map to 32-bit types)."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype in _DTYPE_ALIASES:
            return _DTYPE_ALIASES[dtype]
        dtype = jnp.dtype(dtype).type
    else:
        dtype = jnp.dtype(dtype).type
    name = jnp.dtype(dtype).name
    if name in _DTYPE_ALIASES:
        return _DTYPE_ALIASES[name]
    return dtype


_state = threading.local()


def _tls() -> threading.local:
    if not hasattr(_state, "default_dtype"):
        _state.default_dtype = float32
        _state.grad_enabled = True
        _state.amp_state = None  # set by paddle2_tpu.amp
    return _state


def set_default_dtype(dtype: Any) -> None:
    _tls().default_dtype = convert_dtype(dtype)


def get_default_dtype() -> Any:
    return _tls().default_dtype


def is_grad_enabled() -> bool:
    return _tls().grad_enabled


def set_grad_enabled(mode: bool):
    """Context/shorthand matching paddle.set_grad_enabled."""
    return _GradModeGuard(bool(mode))


class _GradModeGuard(contextlib.ContextDecorator):
    def __init__(self, mode: bool):
        self._mode = mode
        tls = _tls()
        self._prev = tls.grad_enabled
        tls.grad_enabled = mode  # effective immediately, like paddle

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        _tls().grad_enabled = self._prev
        return False


def no_grad(func=None):
    """Disable autograd tape recording (decorator or context manager)."""
    if func is not None:
        def wrapper(*args, **kwargs):
            with _GradModeGuard(False):
                return func(*args, **kwargs)
        return wrapper
    return _GradModeGuard(False)


def enable_grad():
    return _GradModeGuard(True)


# ---------------------------------------------------------------------------
# Places / devices
# ---------------------------------------------------------------------------

class Place:
    """Base place. Wraps a JAX device (or denotes a device class)."""

    device_type = "unknown"

    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (isinstance(other, Place)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def jax_device(self):
        # LOCAL devices only: in a multi-process job jax.devices() is the
        # global list, and device_put onto another process's chip would
        # make the array unreadable here (reference semantics: a Place is
        # always a local device, device_context.h:37)
        devs = [d for d in jax.local_devices()
                if _platform_matches(d, self.device_type)]
        if not devs:
            try:
                devs = jax.local_devices(backend="cpu")
            except Exception:
                devs = [d for d in jax.devices("cpu")
                        if d.process_index == jax.process_index()] \
                    or jax.devices("cpu")
        return devs[min(self.device_id, len(devs) - 1)]


def _platform_matches(dev, device_type: str) -> bool:
    plat = dev.platform.lower()
    if device_type in ("tpu", "gpu"):
        # Under the axon tunnel TPU devices may report an experimental platform
        # name; treat any non-cpu accelerator as the accelerator place.
        return plat != "cpu"
    return plat == device_type


class CPUPlace(Place):
    device_type = "cpu"


class TPUPlace(Place):
    device_type = "tpu"


class CUDAPlace(Place):  # accepted for API parity; maps to the accelerator
    device_type = "gpu"


class CustomPlace(Place):
    def __init__(self, device_type: str, device_id: int = 0):
        super().__init__(device_id)
        self.device_type = device_type


_device_lock = threading.Lock()
_current_place: Optional[Place] = None


def _default_place() -> Place:
    devs = jax.devices()
    if devs and devs[0].platform.lower() != "cpu":
        return TPUPlace(0)
    return CPUPlace(0)


def set_device(device: str) -> Place:
    """paddle.device.set_device parity: 'tpu', 'tpu:0', 'cpu', 'gpu:0'."""
    global _current_place
    name, _, idx = device.partition(":")
    device_id = int(idx) if idx else 0
    if name in ("cpu",):
        place: Place = CPUPlace(device_id)
    elif name in ("tpu", "gpu", "cuda", "xpu"):
        place = TPUPlace(device_id)
    else:
        place = CustomPlace(name, device_id)
    with _device_lock:
        _current_place = place
    return place


def get_device() -> str:
    p = current_place()
    return f"{p.device_type}:{p.device_id}"


def current_place() -> Place:
    global _current_place
    with _device_lock:
        if _current_place is None:
            _current_place = _default_place()
        return _current_place


def device_count(device_type: str = "tpu") -> int:
    return len([d for d in jax.devices() if _platform_matches(d, device_type)]) \
        or len(jax.devices())


def is_compiled_with_cuda() -> bool:  # API parity
    return False


def is_compiled_with_tpu() -> bool:
    return any(d.platform.lower() != "cpu" for d in jax.devices())


def synchronize(device=None) -> None:
    """Block until all dispatched work completes (stream-sync parity)."""
    (jnp.zeros(()) + 0).block_until_ready()


# ---------------------------------------------------------------------------
# Helpers used across the framework
# ---------------------------------------------------------------------------

def to_jax_array(data: Any, dtype: Any = None, place: Optional[Place] = None):
    """Convert host data to a jax.Array on the current (or given) place."""
    dtype = convert_dtype(dtype)
    if isinstance(data, (bool, int, float, complex)):
        if dtype is None:
            if isinstance(data, bool):
                dtype = bool_
            elif isinstance(data, int):
                dtype = int64
            elif isinstance(data, float):
                dtype = get_default_dtype()
            else:
                dtype = complex64
        arr = np.asarray(data, dtype=dtype)
    else:
        arr = np.asarray(data)
        if dtype is not None:
            arr = arr.astype(dtype)
        elif arr.dtype == np.float64:
            arr = arr.astype(get_default_dtype())
    dev = (place or current_place()).jax_device()
    return jax.device_put(arr, dev)
