"""paddle.iinfo / paddle.finfo parity."""

import jax.numpy as jnp
import numpy as np


class iinfo:
    def __init__(self, dtype):
        info = np.iinfo(np.dtype(str(dtype).replace("paddle.", "")))
        self.min = int(info.min)
        self.max = int(info.max)
        self.bits = int(info.bits)
        self.dtype = str(info.dtype)


class finfo:
    def __init__(self, dtype):
        d = str(dtype).replace("paddle.", "")
        info = jnp.finfo(jnp.dtype(d))
        self.min = float(info.min)
        self.max = float(info.max)
        self.eps = float(info.eps)
        self.tiny = float(info.tiny)
        self.smallest_normal = float(info.tiny)
        self.resolution = float(info.resolution)
        self.bits = int(info.bits)
        self.dtype = d
