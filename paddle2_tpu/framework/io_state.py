"""paddle.save / paddle.load — single-file object checkpointing.

TPU-native re-design of the reference checkpoint API
(``python/paddle/framework/io.py:773`` save, ``:1020`` load). The reference
walks nested containers converting ``Tensor``/``LoDTensor`` to numpy and
pickles the result; we do the same over ``jax.Array`` payloads. bfloat16
arrays round-trip via ``ml_dtypes`` (numpy extension dtypes pickle natively).

Differences from the reference, by design:
- no static-graph ``Program`` branch (no static graphs here);
- a saved file is self-describing: any nested python structure whose leaves
  are Tensor/Parameter/ndarray/scalars round-trips.
"""

from __future__ import annotations

import io as _io
import os
import pickle
import struct
import zlib
from typing import Any

import numpy as np

_PROTOCOL_DEFAULT = 4

# integrity format: an 8-byte magic + (crc32, size) header, then the
# payload pickle STREAMED through a CRC-tracking writer (no in-memory
# copy of the serialized state); the header is backfilled once the
# stream ends. load() re-computes the CRC while pickle consumes the
# stream — one pass, verified at EOF. Old files (bare payload pickle)
# still load; non-seekable streams fall back to the envelope-dict form.
_MAGIC = b"P2TCKPT\x01"
_HEADER = struct.Struct("<IQ")
_INTEGRITY_MARKER = "__p2t_integrity__"
_INTEGRITY_VERSION = 1


class CheckpointCorruptionError(ValueError):
    """A checkpoint file or shard failed integrity verification (CRC32 or
    byte-size mismatch, truncation, or an unreadable pickle). Raised by
    :func:`load`, ``distributed.checkpoint.load_state_dict``, and
    ``distributed.checkpoint.verify_checkpoint`` so callers (e.g. the
    fault-tolerance ``CheckpointManager``) can roll back to an older
    verified checkpoint instead of crashing on garbage weights."""


class Crc32Writer:
    """File-object wrapper feeding a running CRC32 + byte counter while
    a pickle streams through it — integrity metadata without holding the
    serialized bytes in memory. Shared with distributed.checkpoint."""

    __slots__ = ("_f", "crc", "size")

    def __init__(self, f):
        self._f = f
        self.crc = 0
        self.size = 0

    def write(self, b):
        self._f.write(b)
        self.crc = zlib.crc32(b, self.crc)
        self.size += len(b)


class Crc32Reader:
    """Read-side mirror of :class:`Crc32Writer`: CRCs bytes as
    ``pickle.load`` consumes them, so integrity verification costs no
    second pass over the file."""

    __slots__ = ("_f", "crc", "size")

    def __init__(self, f):
        self._f = f
        self.crc = 0
        self.size = 0

    def read(self, n=-1):
        b = self._f.read(n)
        self.crc = zlib.crc32(b, self.crc)
        self.size += len(b)
        return b

    def readline(self):
        b = self._f.readline()
        self.crc = zlib.crc32(b, self.crc)
        self.size += len(b)
        return b


def _integrity_wrap(blob: bytes) -> dict:
    return {_INTEGRITY_MARKER: _INTEGRITY_VERSION,
            "crc32": zlib.crc32(blob) & 0xFFFFFFFF,
            "size": len(blob),
            "payload": blob}


def _integrity_unwrap(obj: Any, origin: str) -> Any:
    """Return the verified inner payload bytes→object, or ``obj`` itself
    for pre-envelope files."""
    if not (isinstance(obj, dict) and _INTEGRITY_MARKER in obj):
        return obj
    version = obj[_INTEGRITY_MARKER]
    if version != _INTEGRITY_VERSION or \
            not isinstance(obj.get("payload"), bytes):
        raise CheckpointCorruptionError(
            f"paddle.load: {origin} has integrity-envelope version "
            f"{version!r}; this build supports {_INTEGRITY_VERSION} — "
            "load it with the build that wrote it")
    blob = obj["payload"]
    if len(blob) != obj.get("size"):
        raise CheckpointCorruptionError(
            f"paddle.load: {origin} truncated: payload {len(blob)} bytes, "
            f"expected {obj.get('size')}")
    crc = zlib.crc32(blob) & 0xFFFFFFFF
    if crc != obj.get("crc32"):
        raise CheckpointCorruptionError(
            f"paddle.load: {origin} corrupt: crc32 {crc:#010x} != recorded "
            f"{obj.get('crc32'):#010x}")
    return pickle.loads(blob)


def _dump_with_integrity(payload: Any, f, protocol: int) -> None:
    """Stream the payload pickle behind a magic + (crc32, size) header;
    non-seekable sinks get the envelope-dict fallback (payload buffered
    once — unavoidable without a second pass over the sink)."""
    try:
        seekable = f.seekable()
    except (AttributeError, OSError):
        seekable = False
    if not seekable:
        pickle.dump(_integrity_wrap(pickle.dumps(payload, protocol)), f,
                    protocol=protocol)
        return
    start = f.tell()
    f.write(_MAGIC)
    f.write(_HEADER.pack(0, 0))          # backfilled after the stream
    w = Crc32Writer(f)
    pickle.dump(payload, w, protocol=protocol)
    end = f.tell()
    f.seek(start + len(_MAGIC))
    f.write(_HEADER.pack(w.crc & 0xFFFFFFFF, w.size))
    f.seek(end)


def verified_unpickle(f, crc32: int, size: int, label: str) -> Any:
    """``pickle.load`` through a :class:`Crc32Reader` with the
    size/CRC32 verdict delivered at EOF — one pass over the stream, and
    the integrity error (not the confused unpickle error) is what
    surfaces when the bytes are bad. Shared by :func:`load` and
    ``distributed.checkpoint``'s shard reader."""
    r = Crc32Reader(f)
    err = None
    out = None
    try:
        out = pickle.load(r)
    except Exception as e:
        err = e
        r.read()                         # drain: complete the CRC verdict
    if r.size != size:
        raise CheckpointCorruptionError(
            f"{label} truncated: {r.size} bytes read, recorded {size}")
    if r.crc & 0xFFFFFFFF != crc32:
        raise CheckpointCorruptionError(
            f"{label} corrupt: crc32 {r.crc & 0xFFFFFFFF:#010x} != "
            f"recorded {crc32:#010x}")
    if err is not None:
        raise CheckpointCorruptionError(
            f"{label} unreadable: {err}") from err
    return out


class _PrependReader:
    """Serve already-consumed sniff bytes ahead of the underlying
    stream — lets load() probe for the magic header on NON-SEEKABLE
    streams (pipes, sockets) without losing those bytes."""

    __slots__ = ("_head", "_f")

    def __init__(self, head: bytes, f):
        self._head = head
        self._f = f

    def read(self, n=-1):
        if not self._head:
            return self._f.read(n)
        if n is None or n < 0:
            b, self._head = self._head, b""
            return b + self._f.read(n)
        b, self._head = self._head[:n], self._head[n:]
        if len(b) < n:
            b += self._f.read(n - len(b))
        return b

    def readline(self):
        if not self._head:
            return self._f.readline()
        i = self._head.find(b"\n")
        if i >= 0:
            b, self._head = self._head[:i + 1], self._head[i + 1:]
            return b
        b, self._head = self._head, b""
        return b + self._f.readline()


def _load_with_integrity(f, origin: str) -> Any:
    """Counterpart of :func:`_dump_with_integrity`; also accepts the
    envelope-dict form and pre-integrity bare pickles, on seekable AND
    non-seekable streams."""
    head = f.read(len(_MAGIC))
    if head == _MAGIC:
        raw = f.read(_HEADER.size)
        if len(raw) != _HEADER.size:
            raise CheckpointCorruptionError(
                f"paddle.load: {origin} truncated inside the integrity "
                "header")
        crc, size = _HEADER.unpack(raw)
        return verified_unpickle(f, crc, size, f"paddle.load: {origin}")
    # legacy bare pickle / envelope fallback: re-serve the sniffed bytes
    return _integrity_unwrap(pickle.load(_PrependReader(head, f)), origin)


class _TensorPayload:
    """Pickle surrogate for a Tensor leaf (keeps name/trainable so
    Parameter round-trips through Layer.set_state_dict unchanged)."""

    __slots__ = ("array", "name", "stop_gradient", "is_param")

    def __init__(self, array: np.ndarray, name: str, stop_gradient: bool,
                 is_param: bool):
        self.array = array
        self.name = name
        self.stop_gradient = stop_gradient
        self.is_param = is_param

    def __reduce__(self):
        return (_TensorPayload,
                (self.array, self.name, self.stop_gradient, self.is_param))


def _to_saveable(obj: Any) -> Any:
    from .tensor import Tensor, Parameter
    from ..optimizer.lr import LRScheduler

    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._data), obj.name,
                              obj.stop_gradient, isinstance(obj, Parameter))
    if isinstance(obj, LRScheduler):
        return {"__lr_scheduler__": _to_saveable(obj.state_dict())}
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj) if type(obj) in (list, tuple) else list
        return t(_to_saveable(v) for v in obj)
    if isinstance(obj, (np.ndarray, np.generic, int, float, bool, str,
                        bytes, complex, type(None))):
        return obj
    # Layers / optimizers: save their state_dict, mirroring the reference's
    # guidance that save(layer.state_dict(), path) is the canonical form.
    if hasattr(obj, "state_dict") and callable(obj.state_dict):
        return _to_saveable(obj.state_dict())
    raise TypeError(
        f"paddle.save: unsupported object type {type(obj)!r}; save a "
        "state_dict / nested container of Tensors instead")


def _from_saved(obj: Any, return_numpy: bool) -> Any:
    from .tensor import Tensor, Parameter

    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        cls = Parameter if obj.is_param else Tensor
        if obj.is_param:
            t = cls(obj.array, name=obj.name,
                    trainable=not obj.stop_gradient)
        else:
            t = cls(obj.array, stop_gradient=obj.stop_gradient,
                    name=obj.name)
        return t
    if isinstance(obj, dict):
        if set(obj.keys()) == {"__lr_scheduler__"}:
            return _from_saved(obj["__lr_scheduler__"], return_numpy)
        return {k: _from_saved(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saved(v, return_numpy) for v in obj)
    return obj


def save(obj: Any, path, protocol: int = _PROTOCOL_DEFAULT, **configs) -> None:
    """Serialize ``obj`` (state_dict / Tensor / nested container) to ``path``.

    Parity: ``python/paddle/framework/io.py:773``. ``path`` may be a string
    path or a writable file-like object (reference saves to memory buffers
    for unit tests the same way).
    """
    if protocol < 2 or protocol > 5:
        raise ValueError(f"pickle protocol must be in [2, 5], got {protocol}")
    payload = _to_saveable(obj)
    if hasattr(path, "write"):
        _dump_with_integrity(payload, path, protocol)
        return
    path = os.fspath(path)
    if path.endswith(os.sep) or (os.path.isdir(path)):
        raise ValueError(f"paddle.save path is a directory: {path!r}")
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        _dump_with_integrity(payload, f, protocol)
    os.replace(tmp, path)  # atomic: a crashed save never corrupts the file


def load(path, return_numpy: bool = False, **configs) -> Any:
    """Deserialize a ``paddle.save`` file. Parity: io.py:1020.

    ``return_numpy=True`` yields raw ndarrays instead of Tensors.
    """
    if hasattr(path, "read"):
        payload = _load_with_integrity(path, "<stream>")
    else:
        path = os.fspath(path)
        if not os.path.exists(path):
            raise ValueError(f"paddle.load: no such file {path!r}")
        try:
            with open(path, "rb") as f:
                payload = _load_with_integrity(f, path)
        except (pickle.UnpicklingError, EOFError) as e:
            raise CheckpointCorruptionError(
                f"paddle.load: {path!r} unreadable (truncated or "
                f"corrupt): {e}") from e
    return _from_saved(payload, return_numpy)


def save_to_bytes(obj: Any, protocol: int = _PROTOCOL_DEFAULT) -> bytes:
    buf = _io.BytesIO()
    save(obj, buf, protocol=protocol)
    return buf.getvalue()


def load_from_bytes(data: bytes, return_numpy: bool = False) -> Any:
    return load(_io.BytesIO(data), return_numpy=return_numpy)


# default age guard for reap_stale_tmps: old enough that a LIVE
# concurrent writer (streaming writes keep mtime fresh) is never hit
STALE_TMP_MIN_AGE_S = 60.0


def reap_stale_tmps(directory, match,
                    min_age_s: float = STALE_TMP_MIN_AGE_S) -> list:
    """Remove ``*.tmp`` leftovers of a writer killed between its write
    and its ``os.replace`` — shared by the distributed-checkpoint
    directory and the buddy-replica store, which differ only in the
    ``match(fname)`` predicate. Only files past ``min_age_s`` are
    touched (a live peer's in-flight write must survive); returns the
    reaped names."""
    import time
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    now = time.time()
    reaped = []
    for fname in names:
        if not fname.endswith(".tmp") or not match(fname):
            continue
        full = os.path.join(directory, fname)
        try:
            if now - os.path.getmtime(full) < min_age_s:
                continue
            os.remove(full)
            reaped.append(fname)
        except OSError:
            continue
    return reaped
