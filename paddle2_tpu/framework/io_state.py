"""paddle.save / paddle.load — single-file object checkpointing.

TPU-native re-design of the reference checkpoint API
(``python/paddle/framework/io.py:773`` save, ``:1020`` load). The reference
walks nested containers converting ``Tensor``/``LoDTensor`` to numpy and
pickles the result; we do the same over ``jax.Array`` payloads. bfloat16
arrays round-trip via ``ml_dtypes`` (numpy extension dtypes pickle natively).

Differences from the reference, by design:
- no static-graph ``Program`` branch (no static graphs here);
- a saved file is self-describing: any nested python structure whose leaves
  are Tensor/Parameter/ndarray/scalars round-trips.
"""

from __future__ import annotations

import io as _io
import os
import pickle
from typing import Any

import numpy as np

_PROTOCOL_DEFAULT = 4


class _TensorPayload:
    """Pickle surrogate for a Tensor leaf (keeps name/trainable so
    Parameter round-trips through Layer.set_state_dict unchanged)."""

    __slots__ = ("array", "name", "stop_gradient", "is_param")

    def __init__(self, array: np.ndarray, name: str, stop_gradient: bool,
                 is_param: bool):
        self.array = array
        self.name = name
        self.stop_gradient = stop_gradient
        self.is_param = is_param

    def __reduce__(self):
        return (_TensorPayload,
                (self.array, self.name, self.stop_gradient, self.is_param))


def _to_saveable(obj: Any) -> Any:
    from .tensor import Tensor, Parameter
    from ..optimizer.lr import LRScheduler

    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._data), obj.name,
                              obj.stop_gradient, isinstance(obj, Parameter))
    if isinstance(obj, LRScheduler):
        return {"__lr_scheduler__": _to_saveable(obj.state_dict())}
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj) if type(obj) in (list, tuple) else list
        return t(_to_saveable(v) for v in obj)
    if isinstance(obj, (np.ndarray, np.generic, int, float, bool, str,
                        bytes, complex, type(None))):
        return obj
    # Layers / optimizers: save their state_dict, mirroring the reference's
    # guidance that save(layer.state_dict(), path) is the canonical form.
    if hasattr(obj, "state_dict") and callable(obj.state_dict):
        return _to_saveable(obj.state_dict())
    raise TypeError(
        f"paddle.save: unsupported object type {type(obj)!r}; save a "
        "state_dict / nested container of Tensors instead")


def _from_saved(obj: Any, return_numpy: bool) -> Any:
    from .tensor import Tensor, Parameter

    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        cls = Parameter if obj.is_param else Tensor
        if obj.is_param:
            t = cls(obj.array, name=obj.name,
                    trainable=not obj.stop_gradient)
        else:
            t = cls(obj.array, stop_gradient=obj.stop_gradient,
                    name=obj.name)
        return t
    if isinstance(obj, dict):
        if set(obj.keys()) == {"__lr_scheduler__"}:
            return _from_saved(obj["__lr_scheduler__"], return_numpy)
        return {k: _from_saved(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saved(v, return_numpy) for v in obj)
    return obj


def save(obj: Any, path, protocol: int = _PROTOCOL_DEFAULT, **configs) -> None:
    """Serialize ``obj`` (state_dict / Tensor / nested container) to ``path``.

    Parity: ``python/paddle/framework/io.py:773``. ``path`` may be a string
    path or a writable file-like object (reference saves to memory buffers
    for unit tests the same way).
    """
    if protocol < 2 or protocol > 5:
        raise ValueError(f"pickle protocol must be in [2, 5], got {protocol}")
    payload = _to_saveable(obj)
    if hasattr(path, "write"):
        pickle.dump(payload, path, protocol=protocol)
        return
    path = os.fspath(path)
    if path.endswith(os.sep) or (os.path.isdir(path)):
        raise ValueError(f"paddle.save path is a directory: {path!r}")
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(payload, f, protocol=protocol)
    os.replace(tmp, path)  # atomic: a crashed save never corrupts the file


def load(path, return_numpy: bool = False, **configs) -> Any:
    """Deserialize a ``paddle.save`` file. Parity: io.py:1020.

    ``return_numpy=True`` yields raw ndarrays instead of Tensors.
    """
    if hasattr(path, "read"):
        payload = pickle.load(path)
    else:
        path = os.fspath(path)
        if not os.path.exists(path):
            raise ValueError(f"paddle.load: no such file {path!r}")
        with open(path, "rb") as f:
            payload = pickle.load(f)
    return _from_saved(payload, return_numpy)


def save_to_bytes(obj: Any, protocol: int = _PROTOCOL_DEFAULT) -> bytes:
    buf = _io.BytesIO()
    save(obj, buf, protocol=protocol)
    return buf.getvalue()


def load_from_bytes(data: bytes, return_numpy: bool = False) -> Any:
    return load(_io.BytesIO(data), return_numpy=return_numpy)
