"""Global RNG state.

The reference seeds per-device generators (``paddle.seed``). On TPU the idiomatic
form is a functional PRNG key; this module bridges the two: an imperative global
key that is split on every consumption, plus a scoped override so traced code
(``jit.to_static``) consumes keys threaded through the compiled function instead
of baking a constant into the executable.
"""

from __future__ import annotations

import contextlib
import threading

import jax

_lock = threading.Lock()
_global_key = None
_traced = threading.local()


def seed(value: int):
    """paddle.seed parity: reset the global generator."""
    global _global_key
    with _lock:
        _global_key = jax.random.PRNGKey(value)
    return value


def _ensure_key():
    global _global_key
    if _global_key is None:
        _global_key = jax.random.PRNGKey(0)
    return _global_key


def next_key():
    """Split one subkey off the active generator.

    Inside a `scoped_rng` region (the jit.to_static functional bridge) the key
    comes from the traced state so randomness is a function input, not a
    compile-time constant.
    """
    holder = getattr(_traced, "holder", None)
    if holder is not None:
        holder[0], sub = jax.random.split(holder[0])
        return sub
    global _global_key
    with _lock:
        key = _ensure_key()
        _global_key, sub = jax.random.split(key)
        return sub


@contextlib.contextmanager
def scoped_rng(key):
    """Route next_key() through `key` (a traced PRNGKey) for the duration."""
    prev = getattr(_traced, "holder", None)
    _traced.holder = [key]
    try:
        yield _traced.holder
    finally:
        _traced.holder = prev


def get_rng_state():
    with _lock:
        return _ensure_key()


def set_rng_state(state):
    global _global_key
    with _lock:
        _global_key = state
