"""Eager Tensor: a jax.Array plus autograd metadata.

TPU-native redesign of the reference's eager Tensor
(``paddle/fluid/pybind/eager_method.cc`` methods/properties over a phi
DenseTensor). The payload is a ``jax.Array`` living in HBM via PJRT; autograd
metadata (stop_gradient / grad / producer GradNode) mirrors AutogradMeta.
Tensor methods are mostly monkey-patched in by ``paddle2_tpu.ops`` the same way
``eager_math_op_patch.cc`` patches operators onto the pybind class.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import core
from ..autograd import tape


def _match_devices(cur, g):
    """Reshard g onto cur's placement when their committed device sets
    differ (one cotangent path crossed a mesh collective, the other
    stayed single-device) — XLA refuses mixed-device-set adds."""
    sc = getattr(cur, "sharding", None)
    sg = getattr(g, "sharding", None)
    if (sc is not None and sg is not None and not _is_tracer(g)
            and not _is_tracer(cur) and sc.device_set != sg.device_set):
        return jax.device_put(g, sc)
    return g


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


class Tensor:
    __slots__ = ("_data", "stop_gradient", "grad", "_grad_node", "_output_index",
                 "name", "persistable", "_hooks", "trainable", "__weakref__")

    def __init__(self, data, dtype=None, place=None, stop_gradient: bool = True,
                 name: Optional[str] = None):
        if isinstance(data, Tensor):
            self._data = data._data
        elif isinstance(data, jnp.ndarray) or _is_tracer(data):
            self._data = data if dtype is None else data.astype(
                core.convert_dtype(dtype))
        else:
            self._data = core.to_jax_array(data, dtype, place)
        self.stop_gradient = stop_gradient
        self.grad: Optional[Tensor] = None
        self._grad_node: Optional[tape.GradNode] = None
        self._output_index = 0
        self.name = name or ""
        self.persistable = False
        self.trainable = not stop_gradient
        self._hooks: Optional[List] = None

    # -- properties -----------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self):
        return core.current_place()

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    @property
    def T(self):
        from .. import ops
        return ops.manipulation.t(self)

    # -- distributed attributes (DistTensor surface, dist_tensor.h:39) --
    # derived from the payload's jax sharding: placement IS the sharding
    @property
    def process_mesh(self):
        """ProcessMesh this tensor is placed on, or None (api.py parity:
        dist_tensor.process_mesh)."""
        sh = getattr(self._data, "sharding", None)
        from jax.sharding import NamedSharding
        if not isinstance(sh, NamedSharding) or not sh.mesh.axis_names:
            return None
        from ..distributed.auto_parallel.process_mesh import ProcessMesh
        return ProcessMesh.from_jax_mesh(sh.mesh)

    @property
    def placements(self):
        """Per-mesh-dim placements (Shard/Replicate list), or None."""
        sh = getattr(self._data, "sharding", None)
        from jax.sharding import NamedSharding
        if not isinstance(sh, NamedSharding) or not sh.mesh.axis_names:
            return None
        from ..distributed.auto_parallel.placement import spec_to_placements
        return spec_to_placements(sh.spec, self._data.ndim,
                                  sh.mesh.axis_names)

    def is_dist(self) -> bool:
        """True when placed on a multi-device mesh (DistTensor check)."""
        sh = getattr(self._data, "sharding", None)
        return sh is not None and len(getattr(sh, "device_set", ())) > 1

    # -- conversion -----------------------------------------------------
    def _guard_value_read(self, what: str):
        """Under jit.to_static tracing a Tensor has no concrete value: a
        Python branch on it would silently BAKE the trace-time path into the
        cached program. When TracedProgram installed a graph-break
        controller, the read becomes a GRAPH BREAK: the controller either
        answers with a concrete value resolved by a compiled prefix
        program (returned here, non-None) or aborts the trace to capture
        one — the reference's SOT break-graph semantics (jit/sot/).
        Without a controller the read raises loudly rather than
        specialize silently."""
        if not _is_tracer(self._data):
            return None
        from ..jit.graph_break import active_break_controller
        ctl = active_break_controller()
        if ctl is not None:
            return ctl.on_value_read(self._data, what)
        raise RuntimeError(
            f"jit.to_static: {what} reads the VALUE of a traced Tensor — "
            "Python control flow would be frozen at trace time. Rewrite "
            "with paddle.where/paddle.clip or tensor ops, or run this "
            "function eagerly (reference SOT falls back here).")

    def numpy(self) -> np.ndarray:
        ans = self._guard_value_read("Tensor.numpy()")
        return np.asarray(self._data if ans is None else ans)

    def item(self, *args):
        ans = self._guard_value_read("Tensor.item()")
        return np.asarray(self._data if ans is None else ans).item(*args)

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        ans = self._guard_value_read("float(Tensor)")
        if ans is not None:
            return float(np.asarray(ans).item())
        return float(self.item())

    def __int__(self):
        ans = self._guard_value_read("int(Tensor)")
        if ans is not None:
            return int(np.asarray(ans).item())
        return int(self.item())

    def __bool__(self):
        ans = self._guard_value_read("bool(Tensor) / `if tensor:`")
        if ans is not None:
            return bool(np.asarray(ans).item())
        return bool(self.item())

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._data.shape[0]

    def __hash__(self):
        return id(self)

    # -- autograd -------------------------------------------------------
    def backward(self, grad_tensor: Optional["Tensor"] = None,
                 retain_graph: bool = False) -> None:
        tape.run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def _accumulate_grad(self, g) -> None:
        if self.grad is None:
            self.grad = Tensor(g, stop_gradient=True)
        else:
            cur = self.grad._data
            g = _match_devices(cur, g)
            self.grad = Tensor(cur + g, stop_gradient=True)

    def _apply_grad_hooks(self, g):
        if self._hooks:
            for h in self._hooks:
                out = h(Tensor(g, stop_gradient=True))
                if out is not None:
                    g = out._data if isinstance(out, Tensor) else out
        return g

    def register_hook(self, hook):
        if self._hooks is None:
            self._hooks = []
        self._hooks.append(hook)
        hooks = self._hooks
        class _Removable:
            def remove(self_inner):
                if hook in hooks:
                    hooks.remove(hook)
        return _Removable()

    def clear_grad(self) -> None:
        self.grad = None

    def clear_gradient(self, set_to_zero: bool = False) -> None:
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad._data), stop_gradient=True)
        else:
            self.grad = None

    def detach(self) -> "Tensor":
        return Tensor(self._data, stop_gradient=True, name=self.name)

    def detach_(self) -> "Tensor":
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from ..ops.dispatch import apply_op
        return apply_op("clone", lambda x: x + 0, (self,), {})

    # -- in-place value mutation (optimizer updates, set_value) ----------
    def _replace_data(self, new_data) -> None:
        self._data = new_data

    def set_value(self, value) -> None:
        if isinstance(value, Tensor):
            new = value._data.astype(self._data.dtype)
        else:
            new = core.to_jax_array(np.asarray(value), self._data.dtype)
        if tuple(new.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch: {tuple(new.shape)} vs "
                f"{tuple(self._data.shape)}")
        self._data = new

    def copy_(self, other: "Tensor") -> "Tensor":
        self.set_value(other)
        return self

    # -- misc -----------------------------------------------------------
    def pin_memory(self):
        return self

    def cpu(self):
        arr = jax.device_put(self._data, jax.devices("cpu")[0])
        t = Tensor(arr, stop_gradient=self.stop_gradient)
        return t

    def cuda(self, device_id=None, blocking=True):
        """Move to the accelerator (reference Tensor.cuda; the
        accelerator here is the TPU/default backend device)."""
        devs = [d for d in jax.devices() if d.platform != "cpu"] \
            or jax.devices()
        dev = devs[device_id or 0]
        return Tensor(jax.device_put(self._data, dev),
                      stop_gradient=self.stop_gradient)

    def to(self, *args, **kwargs):
        from ..ops.dispatch import apply_op
        device = kwargs.pop("device", None)
        dtype = kwargs.pop("dtype", None)
        for a in args:
            if isinstance(a, str) and (":" in a or a in ("cpu", "tpu", "gpu")):
                device = a
            else:
                dtype = a
        out = self
        if dtype is not None:
            out = out.astype(dtype)
        if device is not None:
            place = core.set_device(device) if False else None  # no global switch
            name, _, idx = device.partition(":")
            p = core.CPUPlace(int(idx or 0)) if name == "cpu" else core.TPUPlace(int(idx or 0))
            out = Tensor(jax.device_put(out._data, p.jax_device()),
                         stop_gradient=out.stop_gradient)
        return out

    def astype(self, dtype) -> "Tensor":
        from ..ops.dispatch import apply_op
        dt = core.convert_dtype(dtype)
        return apply_op("cast", lambda x: x.astype(dt), (self,), {})

    def cast(self, dtype) -> "Tensor":
        return self.astype(dtype)

    def __repr__(self):
        grad_str = "" if self.stop_gradient else ", stop_gradient=False"
        if _is_tracer(self._data):
            return f"Tensor(shape={self.shape}, dtype={self.dtype}{grad_str}, <traced>)"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype}{grad_str},\n"
                f"       {np.array2string(self.numpy(), prefix='       ')})")

    # Indexing / math dunders are patched in by paddle2_tpu.ops (monkey-patch
    # mirror of eager_math_op_patch.cc). Placeholders raise until ops import.


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """paddle.to_tensor parity."""
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


class Parameter(Tensor):
    """Trainable tensor (python/paddle/base/framework.py Parameter parity)."""

    def __init__(self, data, dtype=None, name: Optional[str] = None,
                 trainable: bool = True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable
