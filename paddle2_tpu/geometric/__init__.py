"""paddle.geometric (reference python/paddle/geometric/: graph message
passing + segment reductions).

TPU-native: the reference's fused CUDA send/recv kernels become
jax.ops.segment_* reductions (XLA scatter-reduce) — static-shape friendly
and differentiable.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..ops.dispatch import apply_op, ensure_tensor

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv", "send_uv"]


def _seg(name, reducer, data, ids, num=None):
    d, i = ensure_tensor(data), ensure_tensor(ids)
    n = num if num is not None else int(jnp.max(i._data)) + 1

    def f(a, idx):
        return reducer(a, idx.astype(jnp.int32), num_segments=n)
    return apply_op(name, f, (d, i), {})


def segment_sum(data, segment_ids, name=None):
    return _seg("segment_sum", jax.ops.segment_sum, data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    d, i = ensure_tensor(data), ensure_tensor(segment_ids)
    n = int(jnp.max(i._data)) + 1

    def f(a, idx):
        return _mean_reduce(a, idx.astype(jnp.int32), n)
    return apply_op("segment_mean", f, (d, i), {})


def segment_max(data, segment_ids, name=None):
    return _seg("segment_max", jax.ops.segment_max, data, segment_ids)


def segment_min(data, segment_ids, name=None):
    return _seg("segment_min", jax.ops.segment_min, data, segment_ids)


_REDUCERS = {"sum": jax.ops.segment_sum, "add": jax.ops.segment_sum,
             "max": jax.ops.segment_max, "min": jax.ops.segment_min}


def _mean_reduce(msgs, di, n):
    tot = jax.ops.segment_sum(msgs, di, num_segments=n)
    cnt = jax.ops.segment_sum(jnp.ones_like(msgs), di, num_segments=n)
    return tot / jnp.maximum(cnt, 1.0)


def _reduce(msgs, di, n, reduce_op):
    if reduce_op == "mean":
        return _mean_reduce(msgs, di, n)
    return _REDUCERS[reduce_op](msgs, di, num_segments=n)


def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size=None, name=None):
    """geometric/message_passing/send_recv.py send_u_recv: gather source
    features along edges, reduce at destinations."""
    xt = ensure_tensor(x)
    s, d = ensure_tensor(src_index), ensure_tensor(dst_index)
    n = int(out_size) if out_size is not None else int(xt.shape[0])

    def f(a, si, di):
        msgs = a[si.astype(jnp.int32)]
        return _reduce(msgs, di.astype(jnp.int32), n, reduce_op)
    return apply_op("send_u_recv", f, (xt, s, d), {})


def send_ue_recv(x, y, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum", out_size=None, name=None):
    """Edge-featured variant: combine node features with edge features."""
    xt, yt = ensure_tensor(x), ensure_tensor(y)
    s, d = ensure_tensor(src_index), ensure_tensor(dst_index)
    n = int(out_size) if out_size is not None else int(xt.shape[0])

    if message_op not in ("add", "sub", "mul", "div"):
        raise ValueError(f"unknown message_op {message_op!r}")

    def f(a, e, si, di):
        u = a[si.astype(jnp.int32)]
        msgs = {"add": u + e, "sub": u - e, "mul": u * e,
                "div": u / e}[message_op]
        return _reduce(msgs, di.astype(jnp.int32), n, reduce_op)
    return apply_op("send_ue_recv", f, (xt, yt, s, d), {})


def send_uv(x, y, src_index, dst_index, message_op: str = "add", name=None):
    """Per-edge messages from both endpoints (no reduction)."""
    xt, yt = ensure_tensor(x), ensure_tensor(y)
    s, d = ensure_tensor(src_index), ensure_tensor(dst_index)

    def f(a, b, si, di):
        u = a[si.astype(jnp.int32)]
        v = b[di.astype(jnp.int32)]
        return {"add": u + v, "sub": u - v, "mul": u * v,
                "div": u / v}[message_op]
    return apply_op("send_uv", f, (xt, yt, s, d), {})


def reindex_graph(x, neighbors, count, value_buffer=None,
                  index_buffer=None, name=None):
    """geometric.reindex_graph == incubate graph_reindex (stable name)."""
    from ..incubate.graph_ops import graph_reindex
    return graph_reindex(x, neighbors, count, value_buffer, index_buffer)


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Multi-edge-type reindex: neighbors/count given PER TYPE; ids are
    renumbered over the union (x first, then first appearance)."""
    import jax.numpy as jnp
    import numpy as np
    from ..incubate.graph_ops import graph_reindex
    from ..framework.tensor import Tensor
    from ..ops.dispatch import ensure_tensor
    nb = jnp.concatenate([ensure_tensor(n)._data for n in neighbors])
    ct = jnp.concatenate([ensure_tensor(c)._data for c in count])
    return graph_reindex(x, Tensor(nb), Tensor(ct))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """geometric.sample_neighbors == incubate graph_sample_neighbors."""
    from ..incubate.graph_ops import graph_sample_neighbors
    return graph_sample_neighbors(row, colptr, input_nodes, eids=eids,
                                  sample_size=sample_size,
                                  return_eids=return_eids)


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None,
                              return_eids=False, name=None):
    """Weight-proportional neighbor sampling (geometric
    weighted_sample_neighbors): per node, sample without replacement
    with probability proportional to edge weight."""
    import numpy as np
    import jax.numpy as jnp
    from ..framework.tensor import Tensor
    from ..ops.dispatch import ensure_tensor
    r = np.asarray(ensure_tensor(row).numpy()).reshape(-1)
    cp = np.asarray(ensure_tensor(colptr).numpy()).reshape(-1)
    w = np.asarray(ensure_tensor(edge_weight).numpy()).reshape(-1)
    nodes = np.asarray(ensure_tensor(input_nodes).numpy()).reshape(-1)
    eid = (np.asarray(ensure_tensor(eids).numpy()).reshape(-1)
           if eids is not None else None)
    rng = np.random.default_rng()
    out_nb, out_ct, out_eid = [], [], []
    for n in nodes.tolist():
        lo, hi = int(cp[n]), int(cp[n + 1])
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:
            sel = np.arange(lo, hi)
        else:
            p = w[lo:hi].astype(np.float64)
            p = p / p.sum() if p.sum() > 0 else None
            sel = lo + rng.choice(deg, size=sample_size, replace=False,
                                  p=p)
        out_nb.append(r[sel])
        out_ct.append(len(sel))
        if eid is not None:
            out_eid.append(eid[sel])
    nb = np.concatenate(out_nb) if out_nb else np.zeros((0,), r.dtype)
    res = (Tensor(jnp.asarray(nb)),
           Tensor(jnp.asarray(np.asarray(out_ct, np.int32))))
    if return_eids:
        res = res + (Tensor(jnp.asarray(
            np.concatenate(out_eid) if out_eid
            else np.zeros((0,), r.dtype))),)
    return res


__all__ += ["reindex_graph", "reindex_heter_graph", "sample_neighbors",
            "weighted_sample_neighbors"]
