"""paddle.geometric (reference python/paddle/geometric/: graph message
passing + segment reductions).

TPU-native: the reference's fused CUDA send/recv kernels become
jax.ops.segment_* reductions (XLA scatter-reduce) — static-shape friendly
and differentiable.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..ops.dispatch import apply_op, ensure_tensor

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv", "send_uv"]


def _seg(name, reducer, data, ids, num=None):
    d, i = ensure_tensor(data), ensure_tensor(ids)
    n = num if num is not None else int(jnp.max(i._data)) + 1

    def f(a, idx):
        return reducer(a, idx.astype(jnp.int32), num_segments=n)
    return apply_op(name, f, (d, i), {})


def segment_sum(data, segment_ids, name=None):
    return _seg("segment_sum", jax.ops.segment_sum, data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    d, i = ensure_tensor(data), ensure_tensor(segment_ids)
    n = int(jnp.max(i._data)) + 1

    def f(a, idx):
        return _mean_reduce(a, idx.astype(jnp.int32), n)
    return apply_op("segment_mean", f, (d, i), {})


def segment_max(data, segment_ids, name=None):
    return _seg("segment_max", jax.ops.segment_max, data, segment_ids)


def segment_min(data, segment_ids, name=None):
    return _seg("segment_min", jax.ops.segment_min, data, segment_ids)


_REDUCERS = {"sum": jax.ops.segment_sum, "add": jax.ops.segment_sum,
             "max": jax.ops.segment_max, "min": jax.ops.segment_min}


def _mean_reduce(msgs, di, n):
    tot = jax.ops.segment_sum(msgs, di, num_segments=n)
    cnt = jax.ops.segment_sum(jnp.ones_like(msgs), di, num_segments=n)
    return tot / jnp.maximum(cnt, 1.0)


def _reduce(msgs, di, n, reduce_op):
    if reduce_op == "mean":
        return _mean_reduce(msgs, di, n)
    return _REDUCERS[reduce_op](msgs, di, num_segments=n)


def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size=None, name=None):
    """geometric/message_passing/send_recv.py send_u_recv: gather source
    features along edges, reduce at destinations."""
    xt = ensure_tensor(x)
    s, d = ensure_tensor(src_index), ensure_tensor(dst_index)
    n = int(out_size) if out_size is not None else int(xt.shape[0])

    def f(a, si, di):
        msgs = a[si.astype(jnp.int32)]
        return _reduce(msgs, di.astype(jnp.int32), n, reduce_op)
    return apply_op("send_u_recv", f, (xt, s, d), {})


def send_ue_recv(x, y, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum", out_size=None, name=None):
    """Edge-featured variant: combine node features with edge features."""
    xt, yt = ensure_tensor(x), ensure_tensor(y)
    s, d = ensure_tensor(src_index), ensure_tensor(dst_index)
    n = int(out_size) if out_size is not None else int(xt.shape[0])

    if message_op not in ("add", "sub", "mul", "div"):
        raise ValueError(f"unknown message_op {message_op!r}")

    def f(a, e, si, di):
        u = a[si.astype(jnp.int32)]
        msgs = {"add": u + e, "sub": u - e, "mul": u * e,
                "div": u / e}[message_op]
        return _reduce(msgs, di.astype(jnp.int32), n, reduce_op)
    return apply_op("send_ue_recv", f, (xt, yt, s, d), {})


def send_uv(x, y, src_index, dst_index, message_op: str = "add", name=None):
    """Per-edge messages from both endpoints (no reduction)."""
    xt, yt = ensure_tensor(x), ensure_tensor(y)
    s, d = ensure_tensor(src_index), ensure_tensor(dst_index)

    def f(a, b, si, di):
        u = a[si.astype(jnp.int32)]
        v = b[di.astype(jnp.int32)]
        return {"add": u + v, "sub": u - v, "mul": u * v,
                "div": u / v}[message_op]
    return apply_op("send_uv", f, (xt, yt, s, d), {})
