"""paddle.hapi (reference python/paddle/hapi/)."""

from .model import Model
from . import callbacks  # noqa: F401
from .callbacks import (Callback, EarlyStopping, LRScheduler,
                        ModelCheckpoint, ProgBarLogger)

__all__ = ["Model", "Callback", "ProgBarLogger", "ModelCheckpoint",
           "LRScheduler", "EarlyStopping", "callbacks"]
