"""hapi callbacks (reference python/paddle/hapi/callbacks.py:105 Callback,
:339 ProgBarLogger, :599 ModelCheckpoint, :727 LRScheduler,
:805 EarlyStopping; independent implementation)."""

from __future__ import annotations

import os
import sys
import time
from typing import Dict, List, Optional

import numpy as np

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "LRScheduler", "EarlyStopping"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """callbacks.py:339 (plain-text variant: step logs every log_freq)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}",
                  file=sys.stderr)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and (step + 1) % self.log_freq == 0:
            logs = logs or {}
            msg = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float) else
                             f"{k}: {v}" for k, v in logs.items())
            ips = (step + 1) / max(time.time() - self._t0, 1e-9)
            print(f"step {step + 1}/{self.steps} - {msg} "
                  f"- {ips:.2f} step/s", file=sys.stderr)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            logs = logs or {}
            msg = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float) else
                             f"{k}: {v}" for k, v in logs.items())
            print(f"Epoch {epoch + 1} done ({time.time() - self._t0:.1f}s)"
                  f" - {msg}", file=sys.stderr)


class ModelCheckpoint(Callback):
    """callbacks.py:599."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """callbacks.py:727: steps the optimizer's LRScheduler."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class EarlyStopping(Callback):
    """callbacks.py:805."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.verbose = verbose
        self.save_best_model = save_best_model
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        if mode == "max" or (mode == "auto" and ("acc" in monitor
                                                 or monitor.endswith("score"))):
            self.better = lambda a, b: a > b + self.min_delta
            self.best = -np.inf
        else:
            self.better = lambda a, b: a < b - self.min_delta
            self.best = np.inf
        if baseline is not None:
            self.best = baseline

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if self.better(cur, self.best):
            self.best = cur
            self.wait = 0
            save_dir = self.params.get("save_dir")
            if self.save_best_model and save_dir:
                self.model.save(os.path.join(save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Epoch {epoch + 1}: early stopping "
                          f"(best {self.monitor}={self.best:.5f})",
                          file=sys.stderr)
