"""High-level Model API (reference python/paddle/hapi/model.py:918 Model,
:1472 fit, :1685 evaluate, :1797 predict; independent implementation on the
eager engine — the reference's static-graph branch is subsumed by
jit.to_static, which callers can apply to the wrapped network)."""

from __future__ import annotations

import os
import pickle
from typing import Any, List, Optional, Sequence

import numpy as np

from ..framework.tensor import Tensor
from ..nn.layer.layers import Layer
from .callbacks import Callback, CallbackList, ProgBarLogger, ModelCheckpoint


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _tensorize(batch):
    from .. import to_tensor
    out = []
    for item in _to_list(batch):
        if isinstance(item, Tensor):
            out.append(item)
        else:
            out.append(to_tensor(np.asarray(item)))
    return out


def _metered_iter(loader):
    """Iterate ``loader`` attributing blocking time to the metrics
    plane's "input" phase — the input-wait component of the step-time
    breakdown. Zero-overhead passthrough when the plane is off."""
    from ..observability import metrics as _metrics
    it = iter(loader)
    while True:
        pl = _metrics._ACTIVE
        if pl is None:
            try:
                yield next(it)
            except StopIteration:
                return
            continue
        pl.phase_enter("input")
        try:
            batch = next(it)
        except StopIteration:
            return
        finally:
            pl.phase_exit()
        yield batch


class Model:
    """hapi/model.py:918 parity: wraps a Layer with train/eval/predict
    loops, metric bookkeeping, and checkpoint save/load."""

    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List = []
        self.stop_training = False

    # ----------------------------------------------------------- prepare
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        """model.py:1392 parity."""
        self._optimizer = optimizer
        if loss is not None and not (isinstance(loss, Layer)
                                     or callable(loss)):
            raise TypeError("loss must be a Layer or callable")
        self._loss = loss
        self._metrics = _to_list(metrics)
        return self

    # ------------------------------------------------------ batch methods
    def train_batch(self, inputs, labels=None, update=True):
        """model.py:1049 parity. Returns [loss values] (+ metric results)."""
        if self._optimizer is None or self._loss is None:
            raise RuntimeError("call prepare(optimizer, loss) before "
                               "training")
        self.network.train()
        ins = _tensorize(inputs)
        lbs = _tensorize(labels)
        from ..distributed.fault_tolerance import numerics
        from ..observability import metrics as _obs
        pl = _obs._ACTIVE
        if pl is not None:
            pl.phase_enter("compute")
        try:
            if numerics.debug_anomaly_enabled():
                # opt-in bisection: raises AnomalyDetected naming the
                # first sublayer whose output goes non-finite
                with numerics.debug_anomaly(self.network):
                    outs = self.network(*ins)
            else:
                outs = self.network(*ins)
            losses = self._compute_loss(outs, lbs)
            total = losses[0]
            for l in losses[1:]:
                total = total + l
            total.backward()
            if update:
                self._optimizer.step()
                self._optimizer.clear_grad()
        finally:
            if pl is not None:
                pl.phase_exit()
        metrics = self._update_metrics(outs, lbs)
        loss_vals = [float(np.asarray(l.numpy())) for l in losses]
        if pl is not None:
            samples = int(ins[0].shape[0]) if ins and ins[0].shape \
                else None
            pl.step_end(samples=samples,
                        loss=loss_vals[0] if loss_vals else None)
        return (loss_vals, metrics) if metrics else loss_vals

    def eval_batch(self, inputs, labels=None):
        from ..framework import core
        self.network.eval()
        ins = _tensorize(inputs)
        lbs = _tensorize(labels)
        with core.no_grad():
            outs = self.network(*ins)
            losses = self._compute_loss(outs, lbs) if self._loss else []
        metrics = self._update_metrics(outs, lbs)
        loss_vals = [float(np.asarray(l.numpy())) for l in losses]
        return (loss_vals, metrics) if metrics else loss_vals

    def predict_batch(self, inputs):
        from ..framework import core
        self.network.eval()
        ins = _tensorize(inputs)
        with core.no_grad():
            outs = self.network(*ins)
        return [np.asarray(o.numpy()) for o in _to_list(outs)]

    def _compute_loss(self, outs, lbs):
        outs_l = _to_list(outs)
        losses = self._loss(*(outs_l + lbs))
        return _to_list(losses)

    @staticmethod
    def _metric_items(m):
        names, vals = m.name(), m.accumulate()
        if isinstance(names, (list, tuple)):
            return list(zip(names, vals))
        return [(names, vals)]

    def _update_metrics(self, outs, lbs):
        outs_l = _to_list(outs)
        res = {}
        for m in self._metrics:
            computed = m.compute(*(outs_l + lbs))
            m.update(*_to_list(computed))
            res.update(self._metric_items(m))
        return res

    # -------------------------------------------------------------- loops
    def _make_loader(self, data, batch_size, shuffle, num_workers,
                     drop_last=False):
        from ..io.dataloader import DataLoader, Dataset
        if data is None:
            return None
        if isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers, drop_last=drop_last)

    def _split_batch(self, batch):
        batch = _to_list(batch)
        if self._inputs is not None or self._labels is not None:
            n_in = len(_to_list(self._inputs)) if self._inputs is not None \
                else max(len(batch) - len(_to_list(self._labels)), 1)
            return batch[:n_in], batch[n_in:]
        if len(batch) == 1:
            return batch, []
        return batch[:-1], batch[-1:]

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, accumulate_grad_batches=1, num_iters=None):
        """model.py:1472 parity."""
        loader = self._make_loader(train_data, batch_size, shuffle,
                                   num_workers, drop_last=drop_last)
        eval_loader = self._make_loader(eval_data, batch_size, False,
                                        num_workers)
        cbks = _to_list(callbacks)
        if verbose and not any(isinstance(c, ProgBarLogger) for c in cbks):
            cbks.append(ProgBarLogger(log_freq, verbose=verbose))
        from .callbacks import LRScheduler as _LRS
        if not any(isinstance(c, _LRS) for c in cbks) and \
                hasattr(getattr(self._optimizer, "_learning_rate", None),
                        "step"):
            cbks.append(_LRS(by_step=True))  # reference config_callbacks
        if save_dir:
            cbks.append(ModelCheckpoint(save_freq, save_dir))
        cbk = CallbackList(cbks)
        cbk.set_model(self)
        metric_names = ["loss"]
        for m in self._metrics:
            n = m.name()
            metric_names += list(n) if isinstance(n, (list, tuple)) else [n]
        cbk.set_params({"epochs": epochs, "steps": len(loader),
                        "verbose": verbose, "save_dir": save_dir,
                        "metrics": metric_names})
        self.stop_training = False
        cbk.on_train_begin()
        it = 0
        # preemption safety: SIGTERM (TPU preemption notice) is latched
        # by the guard and honored at the NEXT STEP BOUNDARY — save a
        # final checkpoint (when save_dir is set) and exit the loop
        # cleanly instead of dying mid-step with progress lost
        from ..distributed.fault_tolerance import PreemptionGuard, numerics
        from ..flags import flag_value
        # FLAGS_check_loss_finite (or the heavier FLAGS_check_nan_inf):
        # consume the numerics sentinel on the loss each step — the value
        # is already on the host for logging, so the guard adds no sync;
        # it turns silent NaN training into a raise that ReliableStep /
        # debug_anomaly can act on
        nan_guard = bool(flag_value("check_loss_finite")) or \
            bool(flag_value("check_nan_inf"))
        with PreemptionGuard() as guard:
            for epoch in range(epochs):
                cbk.on_epoch_begin(epoch)
                for m in self._metrics:
                    m.reset()
                logs = {}  # an empty loader must still yield epoch logs
                from ..observability import metrics as _obs
                pl = _obs._ACTIVE
                if pl is not None:
                    # epoch boundary: eval/callback/checkpoint time since
                    # the previous epoch's last step must not be billed
                    # to this epoch's first step record
                    pl.step_window_reset()
                for step, batch in enumerate(_metered_iter(loader)):
                    cbk.on_train_batch_begin(step)
                    ins, lbs = self._split_batch(batch)
                    # end-of-epoch flush so a trailing partial
                    # accumulation cannot leak into the next epoch
                    # (reference model.py:2808)
                    update = ((step + 1) % accumulate_grad_batches == 0
                              or step + 1 == len(loader))
                    res = self.train_batch(ins, lbs, update=update)
                    logs = self._pack_logs(res)
                    if nan_guard:
                        numerics.assert_finite(
                            logs.get("loss", 0.0),
                            context=f"loss (epoch {epoch} step {step})")
                    cbk.on_train_batch_end(step, logs)
                    it += 1
                    if guard.preempted:
                        self.stop_training = True
                        if save_dir:
                            with guard.saving():
                                self.save(os.path.join(save_dir,
                                                       "preempted"))
                    if (num_iters is not None and it >= num_iters) or \
                            self.stop_training:
                        break
                epoch_logs = dict(logs)
                if not guard.preempted and eval_loader is not None \
                        and (epoch + 1) % eval_freq == 0:
                    eval_logs = self.evaluate(eval_loader, verbose=0,
                                              num_workers=num_workers)
                    epoch_logs.update({f"eval_{k}": v
                                       for k, v in eval_logs.items()})
                cbk.on_epoch_end(epoch, epoch_logs)
                if (num_iters is not None and it >= num_iters) or \
                        self.stop_training:
                    break
        if guard.preempted:
            # this fit CONSUMED the preemption (checkpointed + stopped);
            # clear the process-wide latch so a later fit() in the same
            # surviving process trains normally instead of stopping at
            # its first step boundary
            from ..distributed.fault_tolerance import preemption
            preemption.reset()
        cbk.on_train_end()
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        """model.py:1685 parity: returns {metric_name: value}.
        ``num_samples`` caps how many samples are evaluated."""
        loader = self._make_loader(eval_data, batch_size, False, num_workers)
        cbk = CallbackList(_to_list(callbacks))
        cbk.set_model(self)
        cbk.on_eval_begin()
        for m in self._metrics:
            m.reset()
        losses = []
        seen = 0
        for batch in loader:
            ins, lbs = self._split_batch(batch)
            res = self.eval_batch(ins, lbs)
            loss_vals = res[0] if isinstance(res, tuple) else res
            if loss_vals:
                losses.append(loss_vals[0])
            seen += int(_to_list(batch)[0].shape[0]
                        if hasattr(_to_list(batch)[0], "shape")
                        else batch_size)
            if num_samples is not None and seen >= num_samples:
                break
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            logs.update(self._metric_items(m))
        cbk.on_eval_end(logs)
        if verbose:
            import sys
            print("Eval - " + " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                for k, v in logs.items()), file=sys.stderr)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        """model.py:1797 parity: list (per output) of per-batch arrays."""
        loader = self._make_loader(test_data, batch_size, False, num_workers)
        outputs: Optional[List[List[np.ndarray]]] = None
        # field count fed to the network: the inputs spec when declared,
        # else the forward() signature's required-arg count (so a labeled
        # dataset reused for predict doesn't push its labels into forward)
        if self._inputs is not None:
            n_in = len(_to_list(self._inputs))
        else:
            import inspect
            params = [p for p in inspect.signature(
                self.network.forward).parameters.values()
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
            required = [p for p in params if p.default is p.empty]
            n_in = max(len(required), 1)
        for batch in loader:
            ins = _to_list(batch)
            outs = self.predict_batch(ins[:n_in])
            if outputs is None:
                outputs = [[] for _ in outs]
            for slot, o in zip(outputs, outs):
                slot.append(o)
        if outputs is None:
            return []
        if stack_outputs:
            return [np.concatenate(slot) for slot in outputs]
        return outputs

    def _pack_logs(self, res):
        if isinstance(res, tuple):
            loss_vals, metrics = res
        else:
            loss_vals, metrics = res, {}
        logs = {"loss": loss_vals[0] if loss_vals else 0.0}
        logs.update(metrics)
        return logs

    # -------------------------------------------------------- persistence
    def save(self, path, training=True):
        """model.py:1149: path + '.pdparams' (+ '.pdopt' with optimizer)."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        from .. import save as paddle_save
        paddle_save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None and \
                hasattr(self._optimizer, "state_dict"):
            paddle_save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        """model.py:1216 parity."""
        from .. import load as paddle_load
        state = paddle_load(path + ".pdparams"
                            if not path.endswith(".pdparams") else path)
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path) and \
                hasattr(self._optimizer, "set_state_dict"):
            self._optimizer.set_state_dict(paddle_load(opt_path))

    # -------------------------------------------------------------- misc
    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self.network.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self.network.set_state_dict(state_dict, *args, **kwargs)

    def train(self):
        self.network.train()

    def eval(self):
        self.network.eval()

    def summary(self, input_size=None, dtype=None):
        """model.py:2200 parity: per-layer table + parameter tallies
        (delegates to hapi.model_summary)."""
        if input_size is not None or self._inputs is not None:
            from .model_summary import summary as _summary
            return _summary(self.network,
                            input_size if input_size is not None
                            else self._inputs, dtypes=dtype)
        total = 0
        trainable = 0
        for p in self.network.parameters():
            n = int(np.prod(p.shape))
            total += n
            if not p.stop_gradient:
                trainable += n
        return {"total_params": total, "trainable_params": trainable}
