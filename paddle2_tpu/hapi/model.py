class Model:
    pass
