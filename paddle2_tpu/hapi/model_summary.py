"""paddle.summary (reference python/paddle/hapi/model_summary.py:28):
layer-by-layer table of output shapes and parameter counts, produced by a
forward pass with hooks."""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..nn.layer.layers import Layer

__all__ = ["summary"]


def _to_shape_list(input_size):
    """Normalize input_size (tuple | list | InputSpec | list thereof) to a
    list of concrete shape lists."""
    from ..jit.api import InputSpec

    def one(s):
        if isinstance(s, InputSpec):
            return [d if isinstance(d, int) and d > 0 else 1
                    for d in s.shape]
        return [d if d is not None and d > 0 else 1 for d in s]

    if isinstance(input_size, InputSpec):
        return [one(input_size)]
    if isinstance(input_size, list) and input_size and \
            isinstance(input_size[0], (list, tuple, InputSpec)):
        return [one(s) for s in input_size]
    return [one(input_size)]


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Prints the per-layer table; returns {'total_params', 'trainable_params'}."""
    from .. import zeros

    rows = []
    hooks = []

    def make_hook(full):
        def hook(l, inputs, output=None):
            shape = list(getattr(output, "shape", [])) \
                if not isinstance(output, (tuple, list)) \
                else [list(getattr(o, "shape", [])) for o in output]
            n = sum(int(np.prod(p.shape)) for p in
                    l.parameters(include_sublayers=False))
            rows.append((f"{type(l).__name__} ({full})", shape, n))
        return hook

    def register(layer: Layer, prefix=""):
        children = list(layer.named_children())
        if not children and prefix == "":
            # leaf model: the root itself is the one table row
            hooks.append(layer.register_forward_post_hook(
                make_hook(type(layer).__name__.lower())))
            return
        for name, child in children:
            full = f"{prefix}{name}"
            if list(child.named_children()):
                register(child, full + ".")
            else:
                hooks.append(child.register_forward_post_hook(
                    make_hook(full)))

    register(net)
    try:
        if input is not None:
            x = input if isinstance(input, (tuple, list)) else [input]
            net(*x)
        elif input_size is not None:
            sizes = _to_shape_list(input_size)
            if dtypes is None:
                dts = ["float32"] * len(sizes)
            elif isinstance(dtypes, str):
                dts = [dtypes] * len(sizes)  # one dtype broadcasts
            else:
                dts = list(dtypes)
                if len(dts) != len(sizes):
                    raise ValueError(
                        f"dtypes has {len(dts)} entries for "
                        f"{len(sizes)} inputs")
            args = [zeros(s, dtype=dt) for s, dt in zip(sizes, dts)]
            net(*args)
        else:
            raise ValueError("summary needs input_size or input")
    finally:
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)
    width = max([len(r[0]) for r in rows] + [20])
    print(f"{'Layer (type)':<{width}}  {'Output Shape':<24} {'Params':>12}")
    print("-" * (width + 40))
    for name, shape, n in rows:
        print(f"{name:<{width}}  {str(shape):<24} {n:>12,}")
    print("-" * (width + 40))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    return {"total_params": total, "trainable_params": trainable}
