"""paddle.summary (reference python/paddle/hapi/model_summary.py:28):
layer-by-layer table of output shapes and parameter counts, produced by a
forward pass with hooks."""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..nn.layer.layers import Layer

__all__ = ["summary"]


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Prints the per-layer table; returns {'total_params', 'trainable_params'}."""
    from .. import zeros, to_tensor

    rows = []
    hooks = []

    def register(layer: Layer, prefix=""):
        for name, child in layer.named_children():
            full = f"{prefix}{name}"
            if list(child.named_children()):
                register(child, full + ".")
            else:
                def hook(l, inputs, output=None, _full=full):
                    out = output
                    shape = list(getattr(out, "shape", [])) \
                        if not isinstance(out, (tuple, list)) \
                        else [list(getattr(o, "shape", [])) for o in out]
                    n = sum(int(np.prod(p.shape)) for p in
                            l.parameters(include_sublayers=False))
                    rows.append((f"{type(l).__name__} ({_full})",
                                 shape, n))
                hooks.append(child.register_forward_post_hook(
                    lambda l, i, o, _f=full: hook(l, i, o, _f)))

    register(net)
    try:
        if input is not None:
            x = input if isinstance(input, (tuple, list)) else [input]
            net(*x)
        elif input_size is not None:
            sizes = input_size if isinstance(input_size, list) and \
                isinstance(input_size[0], (list, tuple)) else [input_size]
            dts = dtypes or ["float32"] * len(sizes)
            args = [zeros([d if d is not None and d > 0 else 1
                           for d in s], dtype=dt)
                    for s, dt in zip(sizes, dts)]
            net(*args)
        else:
            raise ValueError("summary needs input_size or input")
    finally:
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)
    width = max([len(r[0]) for r in rows] + [20])
    print(f"{'Layer (type)':<{width}}  {'Output Shape':<24} {'Params':>12}")
    print("-" * (width + 40))
    for name, shape, n in rows:
        print(f"{name:<{width}}  {str(shape):<24} {n:>12,}")
    print("-" * (width + 40))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    return {"total_params": total, "trainable_params": trainable}
