"""paddle.hub parity (local-source only — this build has no network)."""

from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_ENTRY = "hubconf.py"

# loaded hubconf modules keyed by absolute repo dir — repeated
# list()/help()/load() calls against the same repo must not re-execute
# hubconf.py (it may build registries / touch the filesystem);
# force_reload=True bypasses and refreshes the cached entry
_HUBCONF_CACHE: dict = {}


def _load_hubconf(repo_dir: str, force_reload: bool = False):
    path = os.path.join(repo_dir, _ENTRY)
    if not os.path.exists(path):
        raise ValueError(f"no {_ENTRY} in {repo_dir!r}; paddle.hub in this "
                         "offline build supports source='local' only")
    key = os.path.abspath(repo_dir)
    if not force_reload and key in _HUBCONF_CACHE:
        return _HUBCONF_CACHE[key]
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    _HUBCONF_CACHE[key] = mod
    return mod


def list(repo_dir: str, source: str = "local", force_reload: bool = False):
    if source != "local":
        raise ValueError("offline build: only source='local'")
    mod = _load_hubconf(repo_dir, force_reload)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False):
    return getattr(_load_hubconf(repo_dir, force_reload), model).__doc__


def load(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False, **kwargs):
    if source != "local":
        raise ValueError("offline build: only source='local'")
    return getattr(_load_hubconf(repo_dir, force_reload), model)(**kwargs)
