"""paddle.incubate (reference python/paddle/incubate/)."""

from . import moe  # noqa: F401
from .moe import MoELayer, SwitchGate, TopKGate
from . import distributed  # noqa: F401
from . import nn  # noqa: F401
from . import autotune  # noqa: F401

__all__ = ["MoELayer", "SwitchGate", "TopKGate", "moe", "distributed",
           "nn", "LookAhead", "ModelAverage"]
from . import asp  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
