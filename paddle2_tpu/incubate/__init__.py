"""paddle.incubate (reference python/paddle/incubate/)."""

from . import moe  # noqa: F401
from .moe import MoELayer, SwitchGate, TopKGate
from . import distributed  # noqa: F401
from . import nn  # noqa: F401
from . import autotune  # noqa: F401

__all__ = ["MoELayer", "SwitchGate", "TopKGate", "moe", "distributed",
           "nn", "LookAhead", "ModelAverage"]
from . import asp  # noqa: F401
from . import optimizer  # noqa: F401
from . import autograd  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from .graph_ops import (graph_khop_sampler, graph_reindex,  # noqa: F401
                        graph_sample_neighbors, graph_send_recv)
from ..geometric import (segment_max, segment_mean,  # noqa: F401
                         segment_min, segment_sum)


def identity_loss(x, reduction="none"):
    """incubate identity_loss (reference marks a loss for the IPU
    backend; here the reduction semantics are kept: 0/'sum', 1/'mean',
    2/'none')."""
    import jax.numpy as jnp
    from ..ops.dispatch import apply_op, ensure_tensor
    red = {0: "sum", 1: "mean", 2: "none"}.get(reduction, reduction)
    if red == "sum":
        return apply_op("identity_loss", jnp.sum, (ensure_tensor(x),), {})
    if red == "mean":
        return apply_op("identity_loss", jnp.mean, (ensure_tensor(x),), {})
    return ensure_tensor(x)


def softmax_mask_fuse(x, mask, name=None):
    """incubate softmax_mask_fuse: softmax(x + mask) in one kernel
    (fused_softmax_mask op) — XLA fuses the jnp expression."""
    import jax
    from ..ops.dispatch import apply_op, ensure_tensor
    return apply_op("softmax_mask_fuse",
                    lambda a, m: jax.nn.softmax(a + m, axis=-1),
                    (ensure_tensor(x), ensure_tensor(mask)), {})


def softmax_mask_fuse_upper_triangle(x):
    """incubate softmax_mask_fuse_upper_triangle: causal-masked softmax
    for [B, H, S, S] scores in one fused expression."""
    import jax
    import jax.numpy as jnp
    from ..ops.dispatch import apply_op, ensure_tensor

    def fn(a):
        s = a.shape[-1]
        causal = jnp.tril(jnp.ones((s, s), bool))
        return jax.nn.softmax(jnp.where(causal, a, -1e9), axis=-1)

    return apply_op("softmax_mask_fuse_upper_triangle", fn,
                    (ensure_tensor(x),), {})


class inference:
    """paddle.incubate.inference namespace shim: the reference's
    inference decorators map onto jit.to_static + paddle.inference."""

    @staticmethod
    def enable(model=None, **kwargs):
        from .. import jit
        return jit.to_static(model) if model is not None else jit.to_static


__all__ += ["graph_send_recv", "graph_reindex", "graph_sample_neighbors",
            "graph_khop_sampler", "identity_loss", "softmax_mask_fuse",
            "softmax_mask_fuse_upper_triangle", "segment_sum",
            "segment_mean", "segment_max", "segment_min", "inference"]
