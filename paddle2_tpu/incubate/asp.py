"""paddle.incubate.asp — 2:4 structured sparsity (reference
python/paddle/incubate/asp/: prune_model, decorate, supported_layers).

TPU note: the MXU has no sparse-tensor-core acceleration, so ASP here is
the TRAINING-side workflow — magnitude-based n:m mask computation,
masked weights, and an optimizer decorator that re-applies masks after
every step (the reference's OptimizerWithSparsityGuarantee) — producing
models whose weights satisfy the 2:4 invariant for deployment on
hardware that does accelerate it (or for quality studies). Masks are
plain jnp multiplications; XLA fuses them into the adjacent matmuls.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from .. import nn

__all__ = ["prune_model", "decorate", "calculate_density",
           "set_excluded_layers", "reset_excluded_layers",
           "create_mask", "check_sparsity", "reset_masks"]

import weakref

_excluded: Dict[int, List[str]] = {}
# id-keyed with weakref.finalize cleanup (Tensor's elementwise __eq__
# rules out WeakKeyDictionary): the entry dies WITH the parameter, so a
# recycled id can never alias a stale mask and the store cannot grow
# unboundedly across prune_model calls
_masks: Dict[int, "jnp.ndarray"] = {}


def _store_mask(param, mask) -> None:
    pid = id(param)
    _masks[pid] = mask
    weakref.finalize(param, _masks.pop, pid, None)


def _mask_for(param):
    return _masks.get(id(param))


def reset_masks():
    """Drop every stored mask (fresh pruning run)."""
    _masks.clear()


def set_excluded_layers(param_names, main_program=None):
    """Exclude parameters (by EXACT name) from pruning."""
    _excluded[0] = list(param_names)


def reset_excluded_layers(main_program=None):
    _excluded.pop(0, None)


def create_mask(weight, n: int = 2, m: int = 4, mask_algo: str = "mask_1d"):
    """n:m magnitude mask along the LAST dim (mask_1d; the reference's
    default): in every group of m consecutive weights, keep the n
    largest magnitudes."""
    if mask_algo != "mask_1d":
        raise NotImplementedError(
            f"mask_algo={mask_algo!r}: only 'mask_1d' is implemented "
            "(the reference's default); 2d permutation search is not")
    w = weight._data if isinstance(weight, Tensor) else jnp.asarray(weight)
    shape = w.shape
    if shape[-1] % m != 0:
        return jnp.ones_like(w)          # unprunable tail — dense
    g = w.reshape(-1, m)
    order = jnp.argsort(jnp.abs(g), axis=-1)        # ascending
    keep = order[:, m - n:]                          # top-n indices
    mask = jnp.zeros_like(g)
    mask = mask.at[jnp.arange(g.shape[0])[:, None], keep].set(1.0)
    return mask.reshape(shape)


def calculate_density(t) -> float:
    a = np.asarray(t._data if isinstance(t, Tensor) else t)
    return float((a != 0).sum() / a.size)


def check_sparsity(t, n: int = 2, m: int = 4) -> bool:
    """Every m-group has at most n nonzeros (reference check_sparsity)."""
    a = np.asarray(t._data if isinstance(t, Tensor) else t)
    if a.shape[-1] % m != 0:
        return False
    g = (a.reshape(-1, m) != 0).sum(axis=-1)
    return bool((g <= n).all())


_SUPPORTED = (nn.Linear, nn.Conv2D)


def _prunable_params(model: nn.Layer):
    excl = _excluded.get(0, [])
    for lname, layer in model.named_sublayers(include_self=True):
        if not isinstance(layer, _SUPPORTED):
            continue
        w = getattr(layer, "weight", None)
        if w is None:
            continue
        pname = f"{lname}.weight" if lname else "weight"
        if pname in excl or (w.name or "") in excl:
            continue
        yield pname, w


def prune_model(model: nn.Layer, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d", with_mask: bool = True):
    """Apply n:m masks to every supported layer's weight (asp.py
    prune_model contract). Returns {param_name: mask}."""
    out = {}
    for pname, w in _prunable_params(model):
        mask = create_mask(w, n, m, mask_algo)
        w._replace_data(w._data * mask)
        if with_mask:
            _store_mask(w, mask)
        out[pname] = Tensor(mask, stop_gradient=True)
    return out


class _ASPOptimizer:
    """decorate() wrapper (OptimizerWithSparsityGuarantee): after every
    step, re-apply the stored masks so updated weights keep the n:m
    pattern."""

    def __init__(self, inner):
        self._inner = inner

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()          # OUR step: masks re-applied
        return None, None

    def step(self):
        self._inner.step()
        for p in self._inner._parameter_list():
            mask = _mask_for(p)
            if mask is not None:
                p._replace_data(p._data * mask)
                # multi-precision master weights must stay masked too,
                # or the pattern erodes through the f32 copy
                st = self._inner._states.get(id(p))
                if isinstance(st, dict) and "master" in st:
                    st["master"] = st["master"] * mask

    def __getattr__(self, name):
        return getattr(self._inner, name)


def decorate(optimizer):
    """asp.decorate parity: wrap the optimizer so masks survive updates."""
    return _ASPOptimizer(optimizer)
