"""paddle.incubate.autograd (reference incubate/autograd/): primitive-
based functional autodiff. On this stack the "primitive system" IS jax's
jaxpr tracing — forward-mode (jvp), reverse-mode (vjp), and the
Jacobian/Hessian objects ride the same machinery as paddle.autograd;
enable/disable_prim are accepted no-ops (XLA always composes from
primitives)."""

from ..autograd.functional import hessian as Hessian  # noqa: F401
from ..autograd.functional import jacobian as Jacobian  # noqa: F401
from ..autograd.functional import jvp, vjp  # noqa: F401
from ..autograd import grad  # noqa: F401

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "enable_prim",
           "disable_prim", "forward_grad", "grad"]

_PRIM = {"enabled": True}


def enable_prim():
    """No-op: every op already lowers through jaxpr primitives."""
    _PRIM["enabled"] = True


def disable_prim():
    _PRIM["enabled"] = False


def prim_enabled():
    return _PRIM["enabled"]


def forward_grad(outputs, inputs, grad_inputs=None):
    """incubate/autograd forward_grad is a PIR program-transform API;
    the dygraph equivalent is jvp(func, xs, v)."""
    raise NotImplementedError(
        "forward_grad over already-built static programs is a PIR-pass "
        "API; in dygraph use paddle.incubate.autograd.jvp(func, xs, v)")
