"""paddle.incubate.autotune (reference python/paddle/incubate/autotune.py
set_config: kernel / layout / dataloader tuning).

TPU-native content: "kernel" tuning picks Pallas flash-attention block
sizes per attention shape and caches the winner (the analog of the
reference's cuDNN algo exhaustive search); "layout" is a no-op (XLA owns
layouts on TPU); "dataloader" tuning probes worker counts.

Two additions beyond the reference surface:

* **Deterministic kernel scoring** — candidate block sizes can be
  scored by an analytic VMEM-traffic/compute model instead of a wall
  clock. This is the DEFAULT on CPU (CI, dryrun parity: wall clocks in
  shared sandboxes pick a different winner every run, which changes
  the compiled program under test) and opt-in everywhere via
  ``PADDLE_AUTOTUNE_MODE=model``. Exact score ties break through a
  seeded RNG (``PADDLE_AUTOTUNE_SEED``), so the tuned blocks are
  reproducible run to run AND the tie-break policy is explicit.
* **Remat policy search** (:func:`search_remat_policy`) — enumerates
  ``jax.checkpoint`` policies for a GPT block (save-everything /
  save-dots(+qkv/mlp/ln variants) / save-nothing / host-offload),
  scores each candidate by the deterministic cost model (recompute
  FLOPs added + HBM bytes re-touched vs activation bytes saved
  against an explicit memory budget), and picks the minimal-recompute
  policy that fits. The winner wires into ``models/gpt.py``
  (``recompute_granularity="search"``), ``jit/train_step.py`` (the
  resolved policy keys the program cache), and
  ``distributed/recompute.py`` (``policy=`` pass-through) — see the
  README "Raw speed" section.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

_config = {"kernel": {"enable": False, "tuning_range": [1, 10]},
           "layout": {"enable": False},
           "dataloader": {"enable": False}}

_block_cache: Dict[Tuple, Tuple[int, int]] = {}
_CANDIDATES = ((256, 256), (256, 512), (512, 512), (512, 1024),
               (1024, 1024))

AUTOTUNE_MODE_ENV = "PADDLE_AUTOTUNE_MODE"      # "model" | "measure"
AUTOTUNE_SEED_ENV = "PADDLE_AUTOTUNE_SEED"


def set_config(config=None):
    """incubate/autotune.py:23 parity: dict or json file path."""
    if config is None:
        for v in _config.values():
            v["enable"] = True
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    for k, v in config.items():
        if k in _config and isinstance(v, dict):
            _config[k].update(v)


def kernel_tuning_enabled() -> bool:
    return bool(_config["kernel"]["enable"])


def autotune_mode() -> str:
    """``"model"`` (deterministic cost-model scoring) or ``"measure"``
    (wall-clock A/B). Default: ``model`` off-accelerator — CI and the
    virtual-device parity suites must compile the SAME program every
    run — ``measure`` on real TPUs, env-overridable either way."""
    env = os.environ.get(AUTOTUNE_MODE_ENV, "").strip().lower()
    if env in ("model", "measure"):
        return env
    try:
        import jax
        platform = jax.devices()[0].platform.lower()
    except Exception:
        platform = "cpu"
    return "measure" if platform not in ("", "cpu") else "model"


def _tie_rng():
    import numpy as np
    return np.random.RandomState(
        int(os.environ.get(AUTOTUNE_SEED_ENV, "0")))


def _model_flash_block_score(q_shape, k_shape, causal: bool,
                             bq: int, bk: int) -> float:
    """Analytic per-candidate cost of one flash-attention pass:
    HBM traffic (K/V re-streamed once per q-block) + a fixed per-tile
    dispatch overhead, in nominal seconds under the observability rate
    model. Pure function of (shapes, blocks) — no wall clock."""
    from ..observability.cost_model import chip_peak
    peak, hbm, _ = chip_peak()
    b, sq = q_shape[0], q_shape[1]
    sk = k_shape[1]
    hd = 1
    for d in q_shape[2:]:
        hd *= d
    n_q = -(-sq // bq)
    n_k = -(-sk // bk)
    tiles = n_q * n_k
    if causal and sq == sk:
        tiles = (n_q * (n_k + 1)) // 2      # lower-triangular tile set
    bytes_io = 2.0 * b * hd * (sq + n_q * sk * 2)   # q once, k/v per row
    flops = 4.0 * b * sq * sk * hd * (0.5 if causal and sq == sk else 1.0)
    per_tile_overhead = 2e-7                # grid dispatch + pipeline fill
    return flops / peak + bytes_io / hbm + tiles * per_tile_overhead


def best_flash_blocks(q_shape, k_shape, causal: bool,
                      default: Tuple[int, int]) -> Tuple[int, int]:
    """Block-size search, cached per (shapes, causal, mode).

    ``model`` mode scores candidates with the deterministic analytic
    model above; ``measure`` mode times them (TPU only — wall clock).
    Both modes break exact ties with the seeded RNG so the tuned
    blocks are reproducible."""
    mode = autotune_mode()
    key = (tuple(q_shape), tuple(k_shape), bool(causal), mode)
    hit = _block_cache.get(key)
    if hit is not None:
        return hit
    from ..kernels import pallas_flash as pf
    viable = [(bq, bk) for bq, bk in _CANDIDATES
              if pf.supported(q_shape, k_shape, bq, bk)]
    if not viable:
        _block_cache[key] = default
        return default
    if mode == "model":
        scores = [(_model_flash_block_score(q_shape, k_shape, causal,
                                            bq, bk), (bq, bk))
                  for bq, bk in viable]
        best_score = min(s for s, _ in scores)
        tied = [c for s, c in scores if s == best_score]
        best = tied[0] if len(tied) == 1 else \
            tied[_tie_rng().randint(len(tied))]
        _block_cache[key] = best
        return best
    import jax
    import jax.numpy as jnp
    import numpy as np
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(*q_shape), jnp.bfloat16)
    k = jnp.asarray(rs.randn(*k_shape), jnp.bfloat16)
    best, best_t = default, float("inf")
    measured = []
    for bq, bk in viable:
        try:
            f = jax.jit(lambda a, b, c, _bq=bq, _bk=bk:
                        pf.flash_attention_bshd(a, b, c, causal=causal,
                                                block_q=_bq, block_k=_bk))
            o = f(q, k, k)
            _ = float(jnp.sum(o.astype(jnp.float32)))  # true sync
            t0 = time.perf_counter()
            for _i in range(3):
                o = f(o, k, k)
            _ = float(jnp.sum(o.astype(jnp.float32)))
            dt = time.perf_counter() - t0
            measured.append((dt, (bq, bk)))
            if dt < best_t:
                best, best_t = (bq, bk), dt
        except Exception:
            continue
    tied = [c for dt, c in measured if dt == best_t]
    if len(tied) > 1:
        best = tied[_tie_rng().randint(len(tied))]
    _block_cache[key] = best
    return best


# ===================================================================
# Remat policy search (cost-model-guided jax.checkpoint selection)
# ===================================================================

# elementwise recompute cost, FLOPs per element (nominal VPU op counts;
# only RELATIVE weight matters — every candidate is scored by the same
# table)
_LN_FLOPS_PER_ELEM = 8.0        # two reduction passes + normalize+affine
_GELU_FLOPS_PER_ELEM = 12.0     # tanh-approx gelu
_ADD_FLOPS_PER_ELEM = 1.0

# host-offload link rate is owned by cost_model (shared with the
# serving KV spill tier — same channel, one owner, no drift); the old
# local names stay as aliases for compatibility.
from ..observability.cost_model import (
    HOST_ENV as OFFLOAD_ENV,
    DEFAULT_HOST_GBPS as _DEFAULT_OFFLOAD_GBPS,
    host_link_bps as _host_link_bps,
)


@dataclass
class RematCandidate:
    """One remat policy's per-layer accounting at a given (batch, seq).

    ``granularity`` is the ``GPTConfig.recompute_granularity`` value
    the candidate wires to (``None`` = no ``jax.checkpoint`` at all).
    ``saved_bytes`` is the activation HBM held per layer for backward;
    ``recompute_flops``/``recompute_bytes`` the extra work backward
    pays; ``offload_bytes`` what leaves HBM for pinned host memory
    (charged at the offload link, twice: out in forward, back in
    backward)."""
    name: str
    granularity: Optional[str]
    saved_bytes: float
    recompute_flops: float
    recompute_bytes: float
    offload_bytes: float = 0.0
    wired: bool = True      # False: jax on this host can't express it

    def overhead_s(self, peak_flops: float, hbm_bps: float,
                   offload_bps: float) -> float:
        """Modeled backward-overhead seconds per layer — the score."""
        return (self.recompute_flops / peak_flops
                + self.recompute_bytes / hbm_bps
                + 2.0 * self.offload_bytes / offload_bps)


@dataclass
class RematPlan:
    """The searcher's verdict: the chosen policy plus the full scored
    table (the bench prints it; the budget gate re-checks it)."""
    policy: str
    granularity: Optional[str]
    use_recompute: bool
    fits: bool
    budget_bytes: float
    fixed_bytes: float
    activation_bytes: float     # L x saved_bytes of the chosen policy
    total_bytes: float
    recompute_flops: float      # L x per-layer, chosen policy
    overhead_s: float           # L x per-layer modeled seconds
    table: List[Dict] = field(default_factory=list)

    def cache_token(self) -> Tuple:
        """Hashable token for the jit.train_step program cache: two
        models differing only in searched policy must not share a
        compiled entry."""
        return ("remat", self.policy, self.granularity,
                self.use_recompute)


def _offload_supported() -> bool:
    try:
        import jax
        return hasattr(jax.checkpoint_policies,
                       "save_and_offload_only_these_names")
    except Exception:
        return False


def gpt_remat_candidates(hidden: int, ffn: int, num_heads: int,
                         tokens: int, act_bytes: int = 2
                         ) -> List[RematCandidate]:
    """The per-layer accounting table for one GPT pre-LN block at
    ``tokens = batch x seq`` activations of ``act_bytes`` each.

    Saved-tensor census per policy (t = tokens, H = hidden, F = ffn):

    ===================  ==========================================
    save_all             every intermediate: ln1/ln2 (2H), qkv (3H),
                         flash o (H) + f32 lse, out_proj (H), both
                         residuals (2H), up (F), gelu (F), down (H)
    save_dots_plus_ln    dots + gelu + both LN outputs
    save_dots_plus       dots + gelu output   (the "save-qkv-and-mlp-
                         activations" point: every matmul input in
                         backward is materialized)
    save_dots            matmul outputs + pinned flash (o, lse) only
    save_nothing         block input only; backward re-runs the whole
                         forward (matmul FLOPs included)
    save_all_offload     save_all's tensors, parked in pinned host
                         memory — HBM cost of save_nothing, transfer
                         cost of the full activation set
    ===================  ==========================================
    """
    t, H, F, N = float(tokens), float(hidden), float(ffn), float(num_heads)
    a = float(act_bytes)
    lse = t * N * 4.0                       # f32, per layer
    dots = t * (7.0 * H + F) * a + lse      # in + qkv + o + proj + up+down
    all_saved = t * (10.0 * H + 2.0 * F) * a + lse
    ln_flops = 2.0 * _LN_FLOPS_PER_ELEM * t * H          # ln1 + ln2
    gelu_flops = _GELU_FLOPS_PER_ELEM * t * F
    add_flops = 2.0 * _ADD_FLOPS_PER_ELEM * t * H
    cands = [
        RematCandidate("save_all", None, all_saved, 0.0, 0.0),
        RematCandidate(
            "save_dots_plus_ln", "dots_plus_ln",
            dots + t * (2.0 * H + F) * a,
            add_flops, 2.0 * t * H * a),
        RematCandidate(
            "save_dots_plus", "dots_plus",
            dots + t * F * a,
            ln_flops + add_flops, t * (6.0 * H) * a),
        RematCandidate(
            "save_dots", "dots", dots,
            ln_flops + gelu_flops + add_flops,
            t * (6.0 * H + 2.0 * F) * a),
    ]
    return cands


def search_remat_policy(*, hidden: int, num_layers: int, num_heads: int,
                        seq: int, batch: int,
                        ffn: Optional[int] = None,
                        budget_bytes: float,
                        fixed_bytes: float = 0.0,
                        act_bytes: int = 2,
                        peak_flops: Optional[float] = None,
                        hbm_bps: Optional[float] = None,
                        offload_gbps: Optional[float] = None,
                        allow_offload: bool = True) -> RematPlan:
    """Deterministic remat policy search for a GPT block stack.

    Enumerates the candidate table, keeps the candidates whose total
    footprint (``fixed_bytes`` — params/grads/optimizer state — plus
    ``num_layers x saved_bytes``) fits ``budget_bytes``, and returns
    the one with the LOWEST modeled backward overhead (recompute FLOPs
    at the chip peak + re-touched HBM bytes at the HBM rate + offload
    traffic at the host link). Exact-score ties break through the
    seeded autotune RNG. When nothing fits, ``save_nothing`` is
    returned with ``fits=False`` — minimal memory is the only honest
    fallback, and the caller (bench gate / README) surfaces it.

    Pure function of its arguments + the rate model: the same config
    resolves to the same policy on every host, so the compiled train
    step is reproducible (the plan's :meth:`~RematPlan.cache_token`
    keys the program cache)."""
    from ..observability.cost_model import chip_peak
    if peak_flops is None or hbm_bps is None:
        p, h, _ = chip_peak()
        peak_flops = peak_flops if peak_flops is not None else p
        hbm_bps = hbm_bps if hbm_bps is not None else h
    offload_bps = _host_link_bps(offload_gbps)
    F = int(ffn if ffn is not None else 4 * hidden)
    tokens = int(batch) * int(seq)
    t, H = float(tokens), float(hidden)
    a = float(act_bytes)
    cands = gpt_remat_candidates(hidden, F, num_heads, tokens, act_bytes)
    # save_nothing: full forward re-run in backward (matmul FLOPs are
    # geometry-dependent — built here where seq is known)
    mm_flops = 2.0 * t * H * (4.0 * H + 2.0 * F) + 4.0 * t * seq * H
    ew_flops = (2.0 * _LN_FLOPS_PER_ELEM * t * H
                + _GELU_FLOPS_PER_ELEM * t * F
                + 2.0 * _ADD_FLOPS_PER_ELEM * t * H)
    cands.append(RematCandidate(
        "save_nothing", "full", t * H * a,
        mm_flops + ew_flops, t * (10.0 * H + 2.0 * F) * a))
    if allow_offload:
        # offload variant: HBM footprint of save_nothing, backward
        # work of save_dots — the dot outputs are parked in pinned
        # host memory (and charged twice on the host link) instead of
        # recomputed or held in HBM
        dots_c = next(c for c in cands if c.name == "save_dots")
        cands.append(RematCandidate(
            "offload_dots", "offload", t * H * a,
            dots_c.recompute_flops, dots_c.recompute_bytes,
            offload_bytes=dots_c.saved_bytes,
            wired=_offload_supported()))
    # residual stream between layers rides on top of every policy
    residual = t * H * a
    L = int(num_layers)
    rows: List[Dict] = []
    fitting: List[Tuple[float, RematCandidate, float]] = []
    for c in cands:
        total = float(fixed_bytes) + L * c.saved_bytes + residual
        fits = total <= float(budget_bytes)
        score = L * c.overhead_s(peak_flops, hbm_bps, offload_bps)
        rows.append({
            "policy": c.name, "granularity": c.granularity,
            "saved_bytes_per_layer": c.saved_bytes,
            "recompute_flops": L * c.recompute_flops,
            "recompute_bytes": L * c.recompute_bytes,
            "offload_bytes": L * c.offload_bytes,
            "total_bytes": total, "fits": fits, "wired": c.wired,
            "overhead_s": score})
        if fits and c.wired:
            fitting.append((score, c, total))
    if fitting:
        best_score = min(s for s, _, _ in fitting)
        tied = [(c, tot) for s, c, tot in fitting if s == best_score]
        chosen, total = tied[0] if len(tied) == 1 else \
            tied[_tie_rng().randint(len(tied))]
        fits = True
        score = best_score
    else:
        chosen = next(c for c in cands if c.name == "save_nothing")
        total = float(fixed_bytes) + L * chosen.saved_bytes + residual
        fits = total <= float(budget_bytes)
        score = L * chosen.overhead_s(peak_flops, hbm_bps, offload_bps)
    return RematPlan(
        policy=chosen.name, granularity=chosen.granularity,
        use_recompute=chosen.granularity is not None,
        fits=fits, budget_bytes=float(budget_bytes),
        fixed_bytes=float(fixed_bytes),
        activation_bytes=L * chosen.saved_bytes,
        total_bytes=total,
        recompute_flops=L * chosen.recompute_flops,
        overhead_s=score, table=rows)
