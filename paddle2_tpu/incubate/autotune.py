"""paddle.incubate.autotune (reference python/paddle/incubate/autotune.py
set_config: kernel / layout / dataloader tuning).

TPU-native content: "kernel" tuning measures Pallas flash-attention block
sizes per attention shape and caches the winner (the analog of the
reference's cuDNN algo exhaustive search); "layout" is a no-op (XLA owns
layouts on TPU); "dataloader" tuning probes worker counts.
"""

from __future__ import annotations

import json
import time
from typing import Dict, Optional, Tuple

_config = {"kernel": {"enable": False, "tuning_range": [1, 10]},
           "layout": {"enable": False},
           "dataloader": {"enable": False}}

_block_cache: Dict[Tuple, Tuple[int, int]] = {}
_CANDIDATES = ((256, 256), (256, 512), (512, 512), (512, 1024),
               (1024, 1024))


def set_config(config=None):
    """incubate/autotune.py:23 parity: dict or json file path."""
    if config is None:
        for v in _config.values():
            v["enable"] = True
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    for k, v in config.items():
        if k in _config and isinstance(v, dict):
            _config[k].update(v)


def kernel_tuning_enabled() -> bool:
    return bool(_config["kernel"]["enable"])


def best_flash_blocks(q_shape, k_shape, causal: bool,
                      default: Tuple[int, int]) -> Tuple[int, int]:
    """Measured block-size search, cached per (shapes, causal)."""
    key = (tuple(q_shape), tuple(k_shape), bool(causal))
    hit = _block_cache.get(key)
    if hit is not None:
        return hit
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..kernels import pallas_flash as pf
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(*q_shape), jnp.bfloat16)
    k = jnp.asarray(rs.randn(*k_shape), jnp.bfloat16)
    best, best_t = default, float("inf")
    for bq, bk in _CANDIDATES:
        if not pf.supported(q_shape, k_shape, bq, bk):
            continue
        try:
            f = jax.jit(lambda a, b, c, _bq=bq, _bk=bk:
                        pf.flash_attention_bshd(a, b, c, causal=causal,
                                                block_q=_bq, block_k=_bk))
            o = f(q, k, k)
            _ = float(jnp.sum(o.astype(jnp.float32)))  # true sync
            t0 = time.perf_counter()
            for _i in range(3):
                o = f(o, k, k)
            _ = float(jnp.sum(o.astype(jnp.float32)))
            dt = time.perf_counter() - t0
            if dt < best_t:
                best, best_t = (bq, bk), dt
        except Exception:
            continue
    _block_cache[key] = best
    return best
