from ... import moe  # noqa: F401  (paddle.incubate.distributed.models.moe)
