"""Graph-learning operators (reference python/paddle/incubate/operators/
graph_send_recv.py:46, graph_reindex.py:35, graph_sample_neighbors.py:77,
graph_khop_sampler.py:63).

Sampling produces data-dependent shapes, so — like the reference's CPU
kernels — the samplers run host-side on numpy; the dense message-passing
(`graph_send_recv`) runs as XLA segment reductions.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..ops.dispatch import ensure_tensor

__all__ = ["graph_send_recv", "graph_reindex", "graph_sample_neighbors",
           "graph_khop_sampler"]


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Gather x at src, segment-reduce onto dst (the message-passing
    primitive; geometric.send_u_recv is the stable twin)."""
    from ..geometric import send_u_recv
    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """Relabel nodes to a dense 0..K-1 id space: x first, then unseen
    neighbors in first-appearance order. Returns (reindexed_src,
    reindexed_dst, out_nodes)."""
    xs = np.asarray(ensure_tensor(x).numpy()).reshape(-1)
    nb = np.asarray(ensure_tensor(neighbors).numpy()).reshape(-1)
    ct = np.asarray(ensure_tensor(count).numpy()).reshape(-1)
    mapping = {}
    out_nodes: List[int] = []
    for v in xs.tolist():
        if v not in mapping:
            mapping[v] = len(out_nodes)
            out_nodes.append(v)
    for v in nb.tolist():
        if v not in mapping:
            mapping[v] = len(out_nodes)
            out_nodes.append(v)
    reindex_src = np.array([mapping[v] for v in nb.tolist()], xs.dtype)
    # dst: node i of x repeated count[i] times; with multi-edge-type
    # input (graph_reindex docs) count has k*len(x) entries — the x ids
    # cycle per type
    if len(ct) % len(xs) != 0:
        raise ValueError(
            f"count length {len(ct)} must be a multiple of len(x) "
            f"{len(xs)}")
    k = len(ct) // len(xs)
    dst = np.repeat(np.tile(np.arange(len(xs), dtype=xs.dtype), k), ct)
    return (Tensor(jnp.asarray(reindex_src)), Tensor(jnp.asarray(dst)),
            Tensor(jnp.asarray(np.asarray(out_nodes, xs.dtype))))


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    """Uniformly sample up to ``sample_size`` in-neighbors per input node
    from the CSC graph. Returns (neighbors, count[, eids])."""
    r = np.asarray(ensure_tensor(row).numpy()).reshape(-1)
    cp = np.asarray(ensure_tensor(colptr).numpy()).reshape(-1)
    nodes = np.asarray(ensure_tensor(input_nodes).numpy()).reshape(-1)
    eid = (np.asarray(ensure_tensor(eids).numpy()).reshape(-1)
           if eids is not None else None)
    rng = np.random.default_rng()
    out_nb, out_ct, out_eid = [], [], []
    for n in nodes.tolist():
        lo, hi = int(cp[n]), int(cp[n + 1])
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:
            sel = np.arange(lo, hi)
        else:
            sel = lo + rng.choice(deg, size=sample_size, replace=False)
        out_nb.append(r[sel])
        out_ct.append(len(sel))
        if eid is not None:
            out_eid.append(eid[sel])
    nb = np.concatenate(out_nb) if out_nb else np.zeros((0,), r.dtype)
    ct = np.asarray(out_ct, np.int32)
    res = (Tensor(jnp.asarray(nb)), Tensor(jnp.asarray(ct)))
    if return_eids:
        if eid is None:
            raise ValueError("return_eids=True requires eids")
        res = res + (Tensor(jnp.asarray(
            np.concatenate(out_eid) if out_eid
            else np.zeros((0,), r.dtype))),)
    return res


def graph_khop_sampler(row, colptr, input_nodes,
                       sample_sizes: Sequence[int], sorted_eids=None,
                       return_eids=False, name=None):
    """Multi-hop neighbor sampling + reindex (graph_khop_sampler.py:63).
    Returns (edge_src, edge_dst, sample_index, reindex_nodes)."""
    frontier = ensure_tensor(input_nodes)
    all_nb, all_ct = [], []
    seeds = np.asarray(frontier.numpy()).reshape(-1)
    cur = seeds
    for size in sample_sizes:
        nb, ct = graph_sample_neighbors(row, colptr, Tensor(jnp.asarray(cur)),
                                        sample_size=size)
        all_nb.append(np.asarray(nb.numpy()))
        all_ct.append((cur, np.asarray(ct.numpy())))
        cur = np.unique(np.asarray(nb.numpy()))
    # flatten all hops into one edge list rooted at each hop's sources
    srcs, dsts = [], []
    mapping = {}
    order: List[int] = []

    def idx(v):
        if v not in mapping:
            mapping[v] = len(order)
            order.append(v)
        return mapping[v]

    for v in seeds.tolist():
        idx(v)
    for nb, (src_nodes, ct) in zip(all_nb, all_ct):
        pos = 0
        for s, c in zip(src_nodes.tolist(), ct.tolist()):
            si = idx(s)
            for v in nb[pos:pos + c].tolist():
                srcs.append(idx(v))
                dsts.append(si)
            pos += c
    dtype = seeds.dtype
    return (Tensor(jnp.asarray(np.asarray(srcs, dtype))),
            Tensor(jnp.asarray(np.asarray(dsts, dtype))),
            Tensor(jnp.asarray(seeds)),
            Tensor(jnp.asarray(np.asarray(order, dtype))))
