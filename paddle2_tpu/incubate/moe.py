"""Mixture-of-Experts with expert parallelism.

Parity target: /root/reference/python/paddle/incubate/distributed/models/
moe/moe_layer.py:263 (MoELayer), gate/*.py (naive/gshard/switch gates).

TPU-native redesign: the reference scatters tokens to experts with custom
CUDA ops + NCCL AllToAll; here routing is the GShard dense-dispatch
formulation — one-hot dispatch/combine tensors contracted on the MXU, with
a static per-expert capacity so every shape is jit-stable. Experts live
STACKED on a leading expert axis; on a mesh with an 'ep' (or 'mp') axis
the stacked weights and the [E, C, M] expert batches are sharded over it,
and GSPMD inserts the all-to-all that the reference issues by hand.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from .. import nn
from ..ops.dispatch import apply_op

__all__ = ["TopKGate", "SwitchGate", "MoELayer", "dispatch_stats",
           "token_ledger_closes", "router_reference_f64"]


def _one_hot(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


def _topk_pieces(logits, k, capacity):
    """GShard top-k routing, pieces form: per pick j of k, the chosen
    expert idx[j] [S], the in-expert slot pos[j] [S], and the normalized
    gate weight [S] (zero for capacity-dropped tokens); plus aux loss."""
    S, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    remaining = probs
    # position counters per expert, advanced k times
    fill = jnp.zeros((E,), jnp.int32)
    gates_sum = jnp.zeros((S,), jnp.float32)
    pieces = []
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)                   # [S]
        oh = _one_hot(idx, E)                                  # [S, E]
        gate = jnp.sum(probs * oh, axis=-1)                    # [S]
        # position of each token within its chosen expert
        pos_in_e = (jnp.cumsum(oh, axis=0) - 1.0) * oh         # [S, E]
        pos = jnp.sum(pos_in_e, axis=-1).astype(jnp.int32) + \
            jnp.sum(fill * oh, axis=-1).astype(jnp.int32)      # [S]
        keep = pos < capacity
        pieces.append((idx, gate * keep, pos))
        fill = fill + jnp.sum(oh, axis=0).astype(jnp.int32)
        gates_sum = gates_sum + gate * keep
        remaining = remaining * (1.0 - oh)
    # normalize combine weights over the k picks (gshard normalize_gate)
    denom = jnp.maximum(gates_sum, 1e-9)
    idxs = jnp.stack([p[0] for p in pieces])                   # [k, S]
    gates = jnp.stack([p[1] / denom for p in pieces])          # [k, S]
    poss = jnp.stack([p[2] for p in pieces])                   # [k, S]
    # load-balance auxiliary loss (GShard eq.4 / switch loss)
    me = jnp.mean(probs, axis=0)                               # [E]
    first_idx = jnp.argmax(logits, axis=-1)
    ce = jnp.mean(_one_hot(first_idx, E), axis=0)              # [E]
    aux = jnp.sum(me * ce) * E
    return idxs, gates, poss, aux


def _z_loss(logits):
    """Router z-loss (ST-MoE eq.5): mean over tokens of
    ``logsumexp(logits)^2`` — keeps the router logits from drifting to
    magnitudes where softmax saturates and the gate collapses."""
    return jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)


def dispatch_stats(idxs, poss, num_experts: int,
                   capacity: int) -> Dict[str, Any]:
    """EXACT host-side token accounting from the routing pieces
    (``idxs``/``poss``: [k, S] int arrays, device or host).

    Capacity-overflow drops are deterministic (the in-expert position is
    a cumsum over token order, so at an exactly-full expert the LOWER
    token index wins the last slot) — this makes them COUNTED and
    surfaced instead of silently zero-weighted:

    - per expert: ``assigned`` (the router's choice, pre-capacity) =
      ``routed`` (won a slot) + ``dropped`` (position >= capacity);
    - per token: routed through >= 1 pick, or residual-passthrough
      (every pick dropped — the layer's combine emits zeros and the
      surrounding residual connection carries the token through).

    The conservation identities this feeds are audited by
    :func:`token_ledger_closes` — the allocator-ledger discipline
    applied to tokens.
    """
    idx = np.asarray(idxs, np.int64)
    pos = np.asarray(poss, np.int64)
    k, S = idx.shape
    keep = pos < int(capacity)                                  # [k, S]
    assigned = np.zeros((num_experts,), np.int64)
    routed = np.zeros((num_experts,), np.int64)
    dropped = np.zeros((num_experts,), np.int64)
    for j in range(k):
        np.add.at(assigned, idx[j], 1)
        np.add.at(routed, idx[j][keep[j]], 1)
        np.add.at(dropped, idx[j][~keep[j]], 1)
    tokens_routed = int(keep.any(axis=0).sum())
    return {
        "idx": idx,
        "keep": keep,
        "tokens_total": int(S),
        "picks_total": int(k * S),
        "capacity": int(capacity),
        "assigned_per_expert": assigned,
        "routed_per_expert": routed,
        "dropped_per_expert": dropped,
        "routed_picks": int(routed.sum()),
        "dropped_picks": int(dropped.sum()),
        "tokens_routed": tokens_routed,
        "tokens_residual": int(S - tokens_routed),
    }


def token_ledger_closes(stats: Dict[str, Any]) -> bool:
    """The exact token-conservation ledger: routed + capacity-dropped
    == total picks (per expert AND in aggregate), routed tokens +
    residual-passthrough tokens == total tokens, and no expert holds
    more than its capacity. Must close after EVERY step — chaos
    included; a non-closing ledger means tokens were silently lost or
    double-dispatched."""
    assigned = np.asarray(stats["assigned_per_expert"])
    routed = np.asarray(stats["routed_per_expert"])
    dropped = np.asarray(stats["dropped_per_expert"])
    return bool(
        np.array_equal(assigned, routed + dropped)
        and int(assigned.sum()) == stats["picks_total"]
        and stats["routed_picks"] + stats["dropped_picks"]
        == stats["picks_total"]
        and stats["tokens_routed"] + stats["tokens_residual"]
        == stats["tokens_total"]
        and int(routed.max(initial=0)) <= stats["capacity"])


def router_reference_f64(logits: np.ndarray, k: int,
                         capacity: int) -> Dict[str, Any]:
    """Float64 numpy reference of the GShard routing math — the oracle
    the jitted f32 gate is verified against (tests + the
    ``--moe-training`` lane). Mirrors :func:`_topk_pieces` pick by
    pick, plus the aux (load-balance) and z losses."""
    lg = np.asarray(logits, np.float64)
    S, E = lg.shape
    ex = np.exp(lg - lg.max(axis=-1, keepdims=True))
    probs = ex / ex.sum(axis=-1, keepdims=True)
    remaining = probs.copy()
    fill = np.zeros((E,), np.int64)
    gates_sum = np.zeros((S,), np.float64)
    idxs, raw_gates, poss = [], [], []
    for _ in range(k):
        idx = remaining.argmax(axis=-1)
        oh = np.eye(E)[idx]
        gate = (probs * oh).sum(axis=-1)
        pos = ((np.cumsum(oh, axis=0) - 1.0) * oh).sum(axis=-1) \
            .astype(np.int64) + fill[idx]
        keep = pos < capacity
        idxs.append(idx)
        raw_gates.append(gate * keep)
        poss.append(pos)
        fill = fill + oh.sum(axis=0).astype(np.int64)
        gates_sum = gates_sum + gate * keep
        remaining = remaining * (1.0 - oh)
    denom = np.maximum(gates_sum, 1e-9)
    me = probs.mean(axis=0)
    ce = np.eye(E)[lg.argmax(axis=-1)].mean(axis=0)
    lse = lg.max(axis=-1) + np.log(ex.sum(axis=-1))
    return {
        "probs": probs,
        "idxs": np.stack(idxs),
        "gates": np.stack([g / denom for g in raw_gates]),
        "poss": np.stack(poss),
        "aux": float((me * ce).sum() * E),
        "z_loss": float((lse ** 2).mean()),
    }


def _topk_dispatch(logits, k, capacity):
    """Dense GShard tensors from the pieces: (combine [S,E,C], dispatch
    bool [S,E,C], aux). Tokens over capacity are dropped."""
    S, E = logits.shape
    idxs, gates, poss, aux = _topk_pieces(logits, k, capacity)
    combine = jnp.zeros((S, E, capacity), jnp.float32)
    for j in range(k):
        combine = combine + (_one_hot(idxs[j], E)[:, :, None]
                             * _one_hot(jnp.clip(poss[j], 0, capacity - 1),
                                        capacity)[:, None, :]
                             * gates[j][:, None, None])
    dispatch = combine > 0.0
    return combine, dispatch, aux


class TopKGate(nn.Layer):
    """gate/gshard_gate.py parity: learned router + top-k dispatch."""

    def __init__(self, d_model: int, num_experts: int, top_k: int = 2,
                 capacity_factor: float = 1.25):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.wg = nn.Linear(d_model, num_experts, bias_attr=False)

    def capacity(self, num_tokens: int) -> int:
        return max(self.top_k, int(math.ceil(
            self.capacity_factor * self.top_k * num_tokens
            / self.num_experts)))

    def forward(self, x: Tensor):
        logits = self.wg(x)
        cap = self.capacity(int(x.shape[0]))

        def route(lg):
            return _topk_dispatch(lg.astype(jnp.float32), self.top_k, cap)

        return apply_op("moe_gate", route, (logits,), {})

    def pieces(self, x: Tensor):
        """(idxs, gates, poss, aux) for the sort/scatter dispatch."""
        logits = self.wg(x)
        cap = self.capacity(int(x.shape[0]))

        def route(lg):
            return _topk_pieces(lg.astype(jnp.float32), self.top_k, cap)

        return apply_op("moe_gate_pieces", route, (logits,), {})

    def router_losses(self, x: Tensor):
        """(aux, z_loss) of the router on ``x`` — the load-balance loss
        the forward pass already produces plus the ST-MoE z-loss, as
        one traced op so accounting lanes can verify both against
        :func:`router_reference_f64` without re-deriving the gate."""
        logits = self.wg(x)
        cap = self.capacity(int(x.shape[0]))

        def losses(lg):
            lg32 = lg.astype(jnp.float32)
            aux = _topk_pieces(lg32, self.top_k, cap)[3]
            return aux, _z_loss(lg32)

        return apply_op("moe_router_losses", losses, (logits,), {})


class SwitchGate(TopKGate):
    """gate/switch_gate.py parity: top-1 routing."""

    def __init__(self, d_model, num_experts, capacity_factor=1.25):
        super().__init__(d_model, num_experts, top_k=1,
                         capacity_factor=capacity_factor)


class MoELayer(nn.Layer):
    """moe_layer.py:263 parity.

    ``experts`` is a list of homogeneous Layers (each maps [.., M]->[.., M]).
    Forward flattens tokens, routes with the gate, runs every expert on its
    capacity-C batch, and recombines — all static shapes. On a mesh with an
    expert axis the per-expert batch dim is sharded: XLA lowers the
    dispatch/combine contractions into all-to-alls over ICI.
    """

    def __init__(self, d_model: int, experts: Sequence[nn.Layer],
                 gate: Optional[nn.Layer] = None, top_k: int = 2,
                 capacity_factor: float = 1.25, group=None,
                 recompute_interval: int = 0, dispatch_mode: str = "auto",
                 collect_stats: bool = False):
        super().__init__()
        self.d_model = d_model
        self.experts = nn.LayerList(list(experts))
        self.num_experts = len(self.experts)
        self.gate = gate or TopKGate(d_model, self.num_experts, top_k,
                                     capacity_factor)
        self.aux_loss: Optional[Tensor] = None
        # capacity-drop surfacing (ISSUE 19 audit): with collect_stats
        # the forward pass materializes the routing pieces on host and
        # publishes the exact dispatch ledger as ``last_stats`` (plus
        # the moe_* counters) — a readback per step, so it is OPT-IN;
        # the clean path stays sync-free and numerically untouched
        self.collect_stats = bool(collect_stats)
        self.last_stats: Optional[Dict[str, Any]] = None
        # "sort": O(S*M) scatter/gather dispatch (the reference's custom
        # scatter kernels, expressed as one jnp scatter + k gathers) —
        # measured 15.5x over dense on v5e (S=8192, E=8, top-2 bf16:
        # 15.6ms vs 241ms fwd); "dense": GShard one-hot einsums,
        # O(S*E*C*M) but GSPMD-friendly under an ep-sharded mesh;
        # "auto" picks sort on a single device and dense when the
        # expert axis is sharded
        if dispatch_mode not in ("auto", "sort", "dense"):
            raise ValueError(f"dispatch_mode={dispatch_mode!r}")
        self.dispatch_mode = dispatch_mode

    def _expert_axis(self):
        from ..distributed import mesh as mesh_mod
        if not mesh_mod.mesh_initialized():
            return None
        mesh = mesh_mod.get_mesh()
        for name in ("ep", "mp", "sharding"):
            if name in mesh.axis_names and mesh.shape[name] > 1 \
                    and self.num_experts % mesh.shape[name] == 0:
                return name
        return None

    def _constrain_expert_batch(self, t: Tensor) -> Tensor:
        axis = self._expert_axis()
        if axis is None:
            return t
        from ..distributed.fleet.mp_layers import _constrain_tensor
        from jax.sharding import PartitionSpec as P
        return _constrain_tensor(t, P(axis, *([None] * (t.ndim - 1))))

    def _mode(self) -> str:
        if self.dispatch_mode != "auto":
            return self.dispatch_mode
        # custom gates may only implement the dense (combine, dispatch,
        # aux) protocol — sort needs the pieces() form
        if not hasattr(self.gate, "pieces"):
            return "dense"
        return "dense" if self._expert_axis() is not None else "sort"

    def _publish_stats(self, idxs, poss, capacity: int) -> None:
        from ..observability import metrics
        stats = dispatch_stats(idxs.numpy(), poss.numpy(),
                               self.num_experts, capacity)
        self.last_stats = stats
        metrics.inc("moe_tokens_routed_total", stats["routed_picks"])
        if stats["dropped_picks"]:
            metrics.inc("moe_tokens_dropped_total",
                        stats["dropped_picks"])

    def _run_experts(self, expert_in: Tensor) -> Tensor:
        expert_in = self._constrain_expert_batch(expert_in)
        outs = []
        for e, expert in enumerate(self.experts):
            outs.append(expert(expert_in[e]))
        from ..ops.manipulation import stack
        expert_out = stack(outs, axis=0)                       # [E, C, M]
        return self._constrain_expert_batch(expert_out)

    def forward(self, x: Tensor) -> Tensor:
        orig_shape = list(x.shape)
        M = orig_shape[-1]
        tokens = x.reshape([-1, M])                            # [S, M]
        if self._mode() == "sort":
            return self._forward_sort(tokens, M).reshape(orig_shape)
        combine, dispatch, aux = self.gate(tokens)
        self.aux_loss = aux
        if self.collect_stats and hasattr(self.gate, "pieces"):
            # the dense protocol hides the dropped picks (they are
            # simply zero-weighted in combine) — re-derive the pieces
            # for the ledger; opt-in, so the cost is the auditor's
            idxs, _gates, poss, _aux = self.gate.pieces(tokens)
            self._publish_stats(idxs, poss,
                                self.gate.capacity(int(tokens.shape[0])))

        # [S, E, C] x [S, M] -> [E, C, M]
        from ..ops.linalg import einsum
        expert_in = einsum("sec,sm->ecm", dispatch.astype(tokens.dtype),
                           tokens)
        expert_out = self._run_experts(expert_in)
        out = einsum("sec,ecm->sm", combine.astype(tokens.dtype),
                     expert_out)
        return out.reshape(orig_shape)

    def _forward_sort(self, tokens: Tensor, M: int) -> Tensor:
        """Scatter dispatch: each (token, pick) writes its row into its
        expert slot (unique destination by construction; drops land in a
        trash row), experts run on [E, C, M], and combine is k gathers
        weighted by the normalized gates — O(S*M) routing instead of the
        dense formulation's O(S*E*C*M)."""
        idxs, gates, poss, aux = self.gate.pieces(tokens)
        self.aux_loss = aux
        E = self.num_experts
        cap = self.gate.capacity(int(tokens.shape[0]))
        if self.collect_stats:
            self._publish_stats(idxs, poss, cap)

        def route(tok, idx_a, pos_a):
            k = idx_a.shape[0]
            dest = jnp.where(pos_a < cap, idx_a * cap + pos_a,
                             E * cap)                          # [k, S]
            buf = jnp.zeros((E * cap + 1, M), tok.dtype)
            buf = buf.at[dest.reshape(-1)].set(
                jnp.broadcast_to(tok, (k,) + tok.shape)
                .reshape(-1, M))
            return buf[: E * cap].reshape(E, cap, M), dest

        routed = apply_op("moe_scatter_dispatch", route,
                          (tokens, idxs, poss), {})
        expert_in, dest = routed
        expert_out = self._run_experts(expert_in)

        def combine_fn(eo, dest_a, gate_a):
            flat = jnp.concatenate(
                [eo.reshape(E * cap, M),
                 jnp.zeros((1, M), eo.dtype)], axis=0)
            out = jnp.zeros((gate_a.shape[1], M), eo.dtype)
            for j in range(gate_a.shape[0]):
                out = out + flat[dest_a[j]] * \
                    gate_a[j][:, None].astype(eo.dtype)
            return out

        return apply_op("moe_gather_combine", combine_fn,
                        (expert_out, dest, gates), {})
