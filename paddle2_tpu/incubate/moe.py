"""Mixture-of-Experts with expert parallelism.

Parity target: /root/reference/python/paddle/incubate/distributed/models/
moe/moe_layer.py:263 (MoELayer), gate/*.py (naive/gshard/switch gates).

TPU-native redesign: the reference scatters tokens to experts with custom
CUDA ops + NCCL AllToAll; here routing is the GShard dense-dispatch
formulation — one-hot dispatch/combine tensors contracted on the MXU, with
a static per-expert capacity so every shape is jit-stable. Experts live
STACKED on a leading expert axis; on a mesh with an 'ep' (or 'mp') axis
the stacked weights and the [E, C, M] expert batches are sharded over it,
and GSPMD inserts the all-to-all that the reference issues by hand.
"""

from __future__ import annotations

import math
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from .. import nn
from ..ops.dispatch import apply_op

__all__ = ["TopKGate", "SwitchGate", "MoELayer"]


def _one_hot(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


def _topk_dispatch(logits, k, capacity):
    """GShard top-k routing.

    logits: [S, E] f32. Returns (combine [S,E,C], dispatch bool [S,E,C],
    aux_loss scalar). Tokens over capacity are dropped (reference
    gate/gshard_gate.py capacity semantics).
    """
    S, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    combine = jnp.zeros((S, E, capacity), jnp.float32)
    remaining = probs
    # position counters per expert, advanced k times
    fill = jnp.zeros((E,), jnp.int32)
    gates_sum = jnp.zeros((S,), jnp.float32)
    pieces = []
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)                   # [S]
        oh = _one_hot(idx, E)                                  # [S, E]
        gate = jnp.sum(probs * oh, axis=-1)                    # [S]
        # position of each token within its chosen expert
        pos_in_e = (jnp.cumsum(oh, axis=0) - 1.0) * oh         # [S, E]
        pos = jnp.sum(pos_in_e, axis=-1).astype(jnp.int32) + \
            jnp.sum(fill * oh, axis=-1).astype(jnp.int32)      # [S]
        keep = pos < capacity
        pieces.append((idx, gate * keep, pos))
        fill = fill + jnp.sum(oh, axis=0).astype(jnp.int32)
        gates_sum = gates_sum + gate * keep
        remaining = remaining * (1.0 - oh)
    # normalize combine weights over the k picks (gshard normalize_gate)
    denom = jnp.maximum(gates_sum, 1e-9)
    for idx, gate, pos in pieces:
        combine = combine + (_one_hot(idx, E)[:, :, None]
                             * _one_hot(jnp.clip(pos, 0, capacity - 1),
                                        capacity)[:, None, :]
                             * (gate / denom)[:, None, None])
    dispatch = combine > 0.0
    # load-balance auxiliary loss (GShard eq.4 / switch loss)
    me = jnp.mean(probs, axis=0)                               # [E]
    first_idx = jnp.argmax(logits, axis=-1)
    ce = jnp.mean(_one_hot(first_idx, E), axis=0)              # [E]
    aux = jnp.sum(me * ce) * E
    return combine, dispatch, aux


class TopKGate(nn.Layer):
    """gate/gshard_gate.py parity: learned router + top-k dispatch."""

    def __init__(self, d_model: int, num_experts: int, top_k: int = 2,
                 capacity_factor: float = 1.25):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.wg = nn.Linear(d_model, num_experts, bias_attr=False)

    def capacity(self, num_tokens: int) -> int:
        return max(self.top_k, int(math.ceil(
            self.capacity_factor * self.top_k * num_tokens
            / self.num_experts)))

    def forward(self, x: Tensor):
        logits = self.wg(x)
        cap = self.capacity(int(x.shape[0]))

        def route(lg):
            return _topk_dispatch(lg.astype(jnp.float32), self.top_k, cap)

        return apply_op("moe_gate", route, (logits,), {})


class SwitchGate(TopKGate):
    """gate/switch_gate.py parity: top-1 routing."""

    def __init__(self, d_model, num_experts, capacity_factor=1.25):
        super().__init__(d_model, num_experts, top_k=1,
                         capacity_factor=capacity_factor)


class MoELayer(nn.Layer):
    """moe_layer.py:263 parity.

    ``experts`` is a list of homogeneous Layers (each maps [.., M]->[.., M]).
    Forward flattens tokens, routes with the gate, runs every expert on its
    capacity-C batch, and recombines — all static shapes. On a mesh with an
    expert axis the per-expert batch dim is sharded: XLA lowers the
    dispatch/combine contractions into all-to-alls over ICI.
    """

    def __init__(self, d_model: int, experts: Sequence[nn.Layer],
                 gate: Optional[nn.Layer] = None, top_k: int = 2,
                 capacity_factor: float = 1.25, group=None,
                 recompute_interval: int = 0):
        super().__init__()
        self.d_model = d_model
        self.experts = nn.LayerList(list(experts))
        self.num_experts = len(self.experts)
        self.gate = gate or TopKGate(d_model, self.num_experts, top_k,
                                     capacity_factor)
        self.aux_loss: Optional[Tensor] = None

    def _expert_axis(self):
        from ..distributed import mesh as mesh_mod
        if not mesh_mod.mesh_initialized():
            return None
        mesh = mesh_mod.get_mesh()
        for name in ("ep", "mp", "sharding"):
            if name in mesh.axis_names and mesh.shape[name] > 1 \
                    and self.num_experts % mesh.shape[name] == 0:
                return name
        return None

    def _constrain_expert_batch(self, t: Tensor) -> Tensor:
        axis = self._expert_axis()
        if axis is None:
            return t
        from ..distributed.fleet.mp_layers import _constrain_tensor
        from jax.sharding import PartitionSpec as P
        return _constrain_tensor(t, P(axis, *([None] * (t.ndim - 1))))

    def forward(self, x: Tensor) -> Tensor:
        orig_shape = list(x.shape)
        M = orig_shape[-1]
        tokens = x.reshape([-1, M])                            # [S, M]
        combine, dispatch, aux = self.gate(tokens)
        self.aux_loss = aux

        # [S, E, C] x [S, M] -> [E, C, M]
        from ..ops.linalg import einsum
        expert_in = einsum("sec,sm->ecm", dispatch.astype(tokens.dtype),
                           tokens)
        expert_in = self._constrain_expert_batch(expert_in)
        outs = []
        for e, expert in enumerate(self.experts):
            outs.append(expert(expert_in[e]))
        from ..ops.manipulation import stack
        expert_out = stack(outs, axis=0)                       # [E, C, M]
        expert_out = self._constrain_expert_batch(expert_out)
        out = einsum("sec,ecm->sm", combine.astype(tokens.dtype),
                     expert_out)
        return out.reshape(orig_shape)
