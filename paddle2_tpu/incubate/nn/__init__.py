"""paddle.incubate.nn — fused-layer namespace (reference incubate/nn/).

On TPU the "fused" variants are the plain layers: XLA fuses
matmul+bias+activation+residual chains itself, so these aliases keep the
reference API importable without bespoke kernels."""

from ...nn import MultiHeadAttention as FusedMultiHeadAttention  # noqa
from ...nn import Linear as FusedLinear  # noqa
from ...nn.layer.transformer import (  # noqa
    TransformerEncoderLayer as FusedTransformerEncoderLayer)
from ..moe import MoELayer  # noqa
from . import functional  # noqa

__all__ = ["FusedMultiHeadAttention", "FusedLinear",
           "FusedTransformerEncoderLayer", "MoELayer", "functional"]


from ...nn.layer.layers import Layer as _Layer


class FusedDropoutAdd(_Layer):
    """incubate/nn/layer/fused_dropout_add.py: dropout(x) + y."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return functional.fused_dropout_add(x, y, p=self.p,
                                            training=self.training,
                                            mode=self.mode)


class FusedBiasDropoutResidualLayerNorm(_Layer):
    """incubate/nn/layer/fused_transformer.py
    FusedBiasDropoutResidualLayerNorm."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.linear_bias = self.create_parameter([embed_dim],
                                                 is_bias=True)
        self.ln_scale = self.create_parameter([embed_dim])
        import jax.numpy as _j
        self.ln_scale._replace_data(_j.ones([embed_dim], _j.float32))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, x, residual):
        return functional.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self.dropout_rate,
            ln_epsilon=self.epsilon, training=self.training)


class FusedFeedForward(_Layer):
    """incubate/nn/layer/fused_transformer.py:534 FusedFeedForward."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        import jax.numpy as _j
        self.normalize_before = normalize_before
        self.activation = activation
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (act_dropout_rate
                                 if act_dropout_rate is not None
                                 else dropout_rate)
        self.epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr)
        self.linear1_bias = self.create_parameter([dim_feedforward],
                                                  is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr)
        self.linear2_bias = self.create_parameter([d_model], is_bias=True)
        self.ln1_scale = self.create_parameter([d_model])
        self.ln1_scale._replace_data(_j.ones([d_model], _j.float32))
        self.ln1_bias = self.create_parameter([d_model], is_bias=True)
        self.ln2_scale = self.create_parameter([d_model])
        self.ln2_scale._replace_data(_j.ones([d_model], _j.float32))
        self.ln2_bias = self.create_parameter([d_model], is_bias=True)

    def forward(self, src, cache=None):
        return functional.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight,
            self.linear1_bias, self.linear2_bias,
            ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            dropout1_rate=self.act_dropout_rate,
            dropout2_rate=self.dropout_rate,
            activation=self.activation, pre_layer_norm=
            self.normalize_before, ln1_epsilon=self.epsilon,
            ln2_epsilon=self.epsilon, training=self.training)


class FusedMultiTransformer(_Layer):
    """incubate/nn/layer/fused_transformer.py:750 FusedMultiTransformer:
    a pre-LN decoder stack stored as per-layer weight LISTS, executed
    through functional.fused_multi_transformer."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu",
                 normalize_before=True, num_layers=1, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None, **kwargs):
        super().__init__()
        import jax.numpy as _j
        if not normalize_before:
            raise ValueError(
                "FusedMultiTransformer is pre-LN only (reference "
                "fused_transformer.py assert)")
        head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.activation = activation
        self.epsilon = epsilon
        (self.ln_scales, self.ln_biases, self.qkv_weights,
         self.qkv_biases, self.linear_weights, self.linear_biases,
         self.ffn_ln_scales, self.ffn_ln_biases, self.ffn1_weights,
         self.ffn1_biases, self.ffn2_weights, self.ffn2_biases) = \
            ([] for _ in range(12))
        for i in range(num_layers):
            def mk(shape, bias=False, ones=False, tag=""):
                p = self.create_parameter(shape, is_bias=bias)
                if ones:
                    p._replace_data(_j.ones(shape, _j.float32))
                self.add_parameter(f"l{i}_{tag}", p)
                return p
            self.ln_scales.append(mk([embed_dim], ones=True,
                                     tag="ln_scale"))
            self.ln_biases.append(mk([embed_dim], bias=True,
                                     tag="ln_bias"))
            self.qkv_weights.append(mk([3, num_heads, head_dim,
                                        embed_dim], tag="qkv_w"))
            self.qkv_biases.append(mk([3, num_heads, head_dim],
                                      bias=True, tag="qkv_b"))
            self.linear_weights.append(mk([embed_dim, embed_dim],
                                          tag="out_w"))
            self.linear_biases.append(mk([embed_dim], bias=True,
                                         tag="out_b"))
            self.ffn_ln_scales.append(mk([embed_dim], ones=True,
                                         tag="ffn_ln_scale"))
            self.ffn_ln_biases.append(mk([embed_dim], bias=True,
                                         tag="ffn_ln_bias"))
            self.ffn1_weights.append(mk([embed_dim, dim_feedforward],
                                        tag="ffn1_w"))
            self.ffn1_biases.append(mk([dim_feedforward], bias=True,
                                       tag="ffn1_b"))
            self.ffn2_weights.append(mk([dim_feedforward, embed_dim],
                                        tag="ffn2_w"))
            self.ffn2_biases.append(mk([embed_dim], bias=True,
                                       tag="ffn2_b"))

    def forward(self, src, attn_mask=None, caches=None, time_step=None):
        return functional.fused_multi_transformer(
            src, self.ln_scales, self.ln_biases, self.qkv_weights,
            self.qkv_biases, self.linear_weights, self.linear_biases,
            self.ffn_ln_scales, self.ffn_ln_biases, self.ffn1_weights,
            self.ffn1_biases, self.ffn2_weights, self.ffn2_biases,
            attn_mask=attn_mask, dropout_rate=self.dropout_rate,
            activation=self.activation, epsilon=self.epsilon,
            training=self.training)


__all__ += ["FusedDropoutAdd", "FusedBiasDropoutResidualLayerNorm",
            "FusedFeedForward", "FusedMultiTransformer"]
