"""paddle.incubate.nn — fused-layer namespace (reference incubate/nn/).

On TPU the "fused" variants are the plain layers: XLA fuses
matmul+bias+activation+residual chains itself, so these aliases keep the
reference API importable without bespoke kernels."""

from ...nn import MultiHeadAttention as FusedMultiHeadAttention  # noqa
from ...nn import Linear as FusedLinear  # noqa
from ...nn.layer.transformer import (  # noqa
    TransformerEncoderLayer as FusedTransformerEncoderLayer)
from ..moe import MoELayer  # noqa
from . import functional  # noqa

__all__ = ["FusedMultiHeadAttention", "FusedLinear",
           "FusedTransformerEncoderLayer", "MoELayer", "functional"]
