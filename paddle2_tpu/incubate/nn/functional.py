"""paddle.incubate.nn.functional — genuinely fused TPU kernels.

Unlike the layer aliases in incubate.nn (where XLA's automatic fusion
covers the reference's fused_* kernels), the ops here are real fusions the
compiler cannot do on its own."""

from __future__ import annotations

from ...framework.tensor import Tensor
from ...ops.dispatch import apply_op, ensure_tensor

__all__ = ["fused_linear_cross_entropy"]


def fused_linear_cross_entropy(x, weight, label, ignore_index=-100,
                               reduction="mean", name=None):
    """Cross-entropy of `softmax(x @ weight)` without materializing the
    [N, vocab] logits (chunked head+loss; kernels/fused_ce.py). The
    memory/bandwidth saver for large-vocab LM heads — the analog of the
    reference's c_softmax_with_cross_entropy fusion
    (python/paddle/distributed/fleet/layers/mpu/mp_ops.py) for the
    single-device case.

    x: [N, hidden] (or [B, S, hidden], flattened internally);
    weight: [hidden, vocab]; label: int [N] / [B, S].
    reduction: 'mean' over non-ignored tokens | 'sum' | 'none'.
    """
    from ...kernels.fused_ce import fused_linear_cross_entropy as kern
    import jax.numpy as jnp

    x, weight, label = (ensure_tensor(x), ensure_tensor(weight),
                        ensure_tensor(label))

    def fn(xa, wa, la):
        hidden = xa.shape[-1]
        losses, valid = kern(xa.reshape(-1, hidden), wa,
                             la.reshape(-1).astype(jnp.int32),
                             int(ignore_index))
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
            return jnp.sum(losses) / denom
        if reduction == "sum":
            return jnp.sum(losses)
        return losses.reshape(la.shape)

    return apply_op("fused_linear_cross_entropy", fn, (x, weight, label), {})
