"""paddle.incubate.nn.functional — genuinely fused TPU kernels.

Unlike the layer aliases in incubate.nn (where XLA's automatic fusion
covers the reference's fused_* kernels), the ops here are real fusions the
compiler cannot do on its own."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.tensor import Tensor
from ...ops.dispatch import apply_op, ensure_tensor

__all__ = ["fused_linear_cross_entropy", "fused_rotary_position_embedding",
           "fused_rms_norm", "fused_adamw_kernel", "swiglu",
           "fused_matmul_bias", "fused_linear", "fused_linear_activation",
           "fused_bias_act", "fused_dropout_add", "fused_layer_norm",
           "fused_bias_dropout_residual_layer_norm", "fused_feedforward",
           "fused_multi_head_attention", "fused_moe",
           "masked_multihead_attention", "block_multihead_attention",
           "blha_get_max_len",
           "variable_length_memory_efficient_attention",
           "fused_multi_transformer"]

_ANGLE_CACHE: dict = {}


def _angle_table(S, D, base, neox, dtype):
    """Memoized rotary angle tables (decode loops call per step)."""
    import numpy as np
    import jax.numpy as jnp
    key = (S, D, base, neox, dtype)
    hit = _ANGLE_CACHE.get(key)
    if hit is not None:
        return hit
    import jax
    inv = 1.0 / (base ** (np.arange(0, D, 2, dtype=np.float64) / D))
    ang = np.arange(S, dtype=np.float64)[:, None] * inv[None]
    full = np.repeat(ang, 2, axis=1) if neox \
        else np.concatenate([ang, ang], axis=1)
    # concrete even under an active jit trace — otherwise the memo cache
    # would capture DynamicJaxprTracers and poison later eager calls
    with jax.ensure_compile_time_eval():
        out = (jnp.asarray(np.cos(full), dtype),
               jnp.asarray(np.sin(full), dtype))
    if len(_ANGLE_CACHE) > 64:
        _ANGLE_CACHE.clear()
    _ANGLE_CACHE[key] = out
    return out


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True,
                                    time_major=False,
                                    rotary_emb_base=10000.0):
    """Reference incubate fused_rotary_position_embedding.py:27 parity.

    q/k/v: [B, S, H, D]. On TPU the half-split convention
    (use_neox_rotary_style=False) runs the pallas fused_rope kernel —
    measured 2.23x over the XLA elementwise chain on v5e
    ([8,2048,16,128] bf16; the per-head angle broadcast stays in VMEM). The neox (adjacent-pair) convention and v (which rotary does
    not rotate in the reference either unless passed) use the XLA path.
    Returns (q_out, k_out, v_out) with None passthrough.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    first = next(t for t in (q, k, v) if t is not None)
    first = ensure_tensor(first)
    if time_major:
        raise NotImplementedError("time_major=True: transpose to "
                                  "[batch, seq, heads, dim] first")
    B, S, H, D = first.shape
    if sin is None or cos is None:
        rows = S
        if position_ids is not None \
                and not isinstance(ensure_tensor(position_ids)._data,
                                   jax.core.Tracer):
            # positions may exceed seq_len (decode loops index absolute
            # positions); JAX gathers clamp out-of-range indices, so an
            # S-row table would silently mis-rotate — size it to cover
            # the actual max position. Rows are bucketed to the next
            # multiple of 1024 so a decode loop reuses one memoized
            # table instead of rebuilding it every step. Traced
            # position_ids keep the S-row table (in-range by contract;
            # out-of-range needs explicit sin/cos sized to max position).
            pid = ensure_tensor(position_ids)._data
            max_pos = int(np.asarray(pid).max())
            if max_pos >= S:
                rows = -(-(max_pos + 1) // 1024) * 1024
        cos_a, sin_a = _angle_table(rows, D, float(rotary_emb_base),
                                    bool(use_neox_rotary_style),
                                    str(first._data.dtype))
    else:
        cos_a = ensure_tensor(cos)._data.reshape(-1, D)
        sin_a = ensure_tensor(sin)._data.reshape(-1, D)
        if cos_a.shape[0] != S and position_ids is None:
            if cos_a.shape[0] > S:
                # max-position table: positions are 0..S-1 here
                cos_a, sin_a = cos_a[:S], sin_a[:S]
            else:
                raise ValueError(
                    f"cos/sin table has {cos_a.shape[0]} positions but "
                    f"seq_len is {S}; pass position_ids or a table with "
                    "at least seq_len rows")
    if position_ids is not None:
        pos = ensure_tensor(position_ids)._data.astype(jnp.int32)
        cos_a = cos_a[pos].reshape(B * S, D)
        sin_a = sin_a[pos].reshape(B * S, D)

    try:
        on_accel = jax.devices()[0].platform.lower() != "cpu"
    except Exception:
        on_accel = False

    def rot_one(arr):
        if not use_neox_rotary_style and on_accel:
            from ...kernels.pallas_fused import fused_rope
            return fused_rope(arr, cos_a, sin_a)
        c = cos_a.reshape(-1, S, 1, D) if cos_a.shape[0] != S \
            else cos_a[None, :, None, :]
        s = sin_a.reshape(-1, S, 1, D) if sin_a.shape[0] != S \
            else sin_a[None, :, None, :]
        if use_neox_rotary_style:
            x1 = arr[..., 0::2]
            x2 = arr[..., 1::2]
            rot = jnp.stack([-x2, x1], axis=-1).reshape(arr.shape)
        else:
            x1 = arr[..., : D // 2]
            x2 = arr[..., D // 2:]
            rot = jnp.concatenate([-x2, x1], axis=-1)
        return arr * c + rot * s

    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        t = ensure_tensor(t)
        outs.append(apply_op("fused_rope", rot_one, (t,), {}))
    return tuple(outs)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None):
    """Pallas one-pass RMSNorm (fwd + custom bwd); with `bias`/`residual`
    the reference's fused add-then-norm: y = norm(x + bias + residual),
    returning (y, x + bias + residual) like fused_rms_norm's residual
    form. Quantized variants are not supported. NOTE: measured 0.83x of
    the XLA-fused chain on v5e ([8192,1024] bf16 fwd+bwd) — provided for
    reference parity and as a building block; prefer the plain
    expression under jit."""
    from ...kernels.pallas_fused import fused_rms_norm as kern
    x, w = ensure_tensor(x), ensure_tensor(norm_weight)
    nd = x.ndim
    if begin_norm_axis not in (-1, nd - 1):
        raise NotImplementedError(
            f"fused_rms_norm normalizes the LAST axis only "
            f"(begin_norm_axis={begin_norm_axis}, ndim={nd}); reshape "
            "so the normalized dims are flattened into the last axis")
    tensors = [x, w]
    if norm_bias is not None:
        tensors.append(ensure_tensor(norm_bias))
    if bias is not None:
        tensors.append(ensure_tensor(bias))
    if residual is not None:
        tensors.append(ensure_tensor(residual))

    def fn(xa, wa, *rest):
        it = iter(rest)
        nb = next(it) if norm_bias is not None else None
        ba = next(it) if bias is not None else None
        ra = next(it) if residual is not None else None
        pre = xa
        if ba is not None:
            pre = pre + ba
        if ra is not None:
            pre = pre + ra
        out = kern(pre, wa, epsilon=epsilon)
        if nb is not None:
            out = out + nb
        if residual is not None:
            return out, pre
        return out

    return apply_op("fused_rms_norm", fn, tuple(tensors), {})


def fused_adamw_kernel(param, grad, m, v, master, lr, beta1=0.9,
                       beta2=0.999, epsilon=1e-8, weight_decay=0.01,
                       step=1):
    """Single-pass pallas AdamW (fused_adam_kernel.cu parity). NOTE:
    measured 0.44x of XLA's fused update on v5e (84M f32 donated) — XLA
    already emits a one-pass loop for the update chain; kept for parity
    and for runtimes where the update is not under jit."""
    from ...kernels.pallas_fused import fused_adamw as kern
    outs = kern(ensure_tensor(param)._data, ensure_tensor(grad)._data,
                ensure_tensor(m)._data, ensure_tensor(v)._data,
                ensure_tensor(master)._data, lr, beta1, beta2, epsilon,
                weight_decay, step)
    return tuple(Tensor(a, stop_gradient=True) for a in outs)


def fused_linear_cross_entropy(x, weight, label, ignore_index=-100,
                               reduction="mean", name=None):
    """Cross-entropy of `softmax(x @ weight)` without materializing the
    [N, vocab] logits (chunked head+loss; kernels/fused_ce.py). The
    memory/bandwidth saver for large-vocab LM heads — the analog of the
    reference's c_softmax_with_cross_entropy fusion
    (python/paddle/distributed/fleet/layers/mpu/mp_ops.py) for the
    single-device case.

    x: [N, hidden] (or [B, S, hidden], flattened internally);
    weight: [hidden, vocab]; label: int [N] / [B, S].
    reduction: 'mean' over non-ignored tokens | 'sum' | 'none'.
    """
    from ...kernels.fused_ce import fused_linear_cross_entropy as kern
    import jax.numpy as jnp

    x, weight, label = (ensure_tensor(x), ensure_tensor(weight),
                        ensure_tensor(label))

    def fn(xa, wa, la):
        hidden = xa.shape[-1]
        losses, valid = kern(xa.reshape(-1, hidden), wa,
                             la.reshape(-1).astype(jnp.int32),
                             int(ignore_index))
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
            return jnp.sum(losses) / denom
        if reduction == "sum":
            return jnp.sum(losses)
        return losses.reshape(la.shape)

    return apply_op("fused_linear_cross_entropy", fn, (x, weight, label), {})


def swiglu(x, y=None, name=None):
    """fused swiglu (incubate/nn/functional/swiglu.py): silu(x) * y;
    single-input form splits the last dim in half."""
    if y is None:
        def fn(a):
            u, v = jnp.split(a, 2, axis=-1)
            return jax.nn.silu(u) * v
        return apply_op("swiglu", fn, (ensure_tensor(x),), {})
    return apply_op("swiglu",
                    lambda a, b: jax.nn.silu(a) * b,
                    (ensure_tensor(x), ensure_tensor(y)), {})


def fused_matmul_bias(x, y, bias=None, transpose_x=False,
                      transpose_y=False, name=None):
    """fused_matmul_bias: one XLA fusion of matmul + bias."""
    ts = [ensure_tensor(x), ensure_tensor(y)]
    if bias is not None:
        ts.append(ensure_tensor(bias))

    def fn(a, b, *rest):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        if rest:
            out = out + rest[0]
        return out
    return apply_op("fused_matmul_bias", fn, tuple(ts), {})


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias,
                             transpose_y=transpose_weight)


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation="gelu", name=None):
    out = fused_matmul_bias(x, y, bias, trans_x, trans_y)
    act = {"gelu": jax.nn.gelu, "relu": lambda a: jnp.maximum(a, 0),
           "none": lambda a: a, None: lambda a: a}[activation]
    return apply_op("fused_linear_act", act, (ensure_tensor(out),), {})


def fused_bias_act(x, bias=None, dequant_scales=None, shift=None,
                   smooth=None, act_method="gelu", compute_dtype="default",
                   quant_scale=-1, quant_round_type=0, quant_max_bound=0,
                   quant_min_bound=0, name=None):
    """fused_bias_act: bias + activation in one fusion (the quant knobs
    gate the int8 serving path; the float path is the TPU route)."""
    ts = [ensure_tensor(x)]
    if bias is not None:
        ts.append(ensure_tensor(bias))
    act = {"gelu": jax.nn.gelu, "relu": lambda a: jnp.maximum(a, 0),
           "swiglu": lambda a: (lambda u, v: jax.nn.silu(u) * v)(
               *jnp.split(a, 2, axis=-1)),
           "silu": jax.nn.silu}[act_method]

    def fn(a, *rest):
        if rest:
            a = a + rest[0]
        return act(a)
    return apply_op("fused_bias_act", fn, tuple(ts), {})


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """fused_dropout_add: dropout(x) + y in one pass."""
    from ...framework import random as fr
    if not training or p == 0:
        return apply_op("fused_dropout_add", lambda a, b: a + b,
                        (ensure_tensor(x), ensure_tensor(y)), {})
    key = fr.next_key()

    def fn(a, b):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        if mode == "upscale_in_train":
            a = jnp.where(keep, a / (1.0 - p), 0.0)
        else:
            a = jnp.where(keep, a, 0.0)
        return a + b
    return apply_op("fused_dropout_add", fn,
                    (ensure_tensor(x), ensure_tensor(y)), {})


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     residual_alpha=1.0, begin_norm_axis=1, bias=None,
                     residual=None, quant_scale=-1, quant_round_type=0,
                     quant_max_bound=0, quant_min_bound=0, name=None):
    """fused_layer_norm: (x + bias + alpha*residual) -> LayerNorm, one
    fusion. Returns (out, residual_out) when a residual is given, like
    the reference kernel."""
    ts = [ensure_tensor(x)]
    has_w = norm_weight is not None
    if has_w:
        ts.append(ensure_tensor(norm_weight))
    has_nb = norm_bias is not None
    if has_nb:
        ts.append(ensure_tensor(norm_bias))
    has_b = bias is not None
    if has_b:
        ts.append(ensure_tensor(bias))
    has_r = residual is not None
    if has_r:
        ts.append(ensure_tensor(residual))

    def fn(a, *rest):
        i = 0
        w = rest[i] if has_w else None
        i += has_w
        nb = rest[i] if has_nb else None
        i += has_nb
        b = rest[i] if has_b else None
        i += has_b
        r = rest[i] if has_r else None
        if b is not None:
            a = a + b
        if r is not None:
            a = a + residual_alpha * r
        red = tuple(range(begin_norm_axis, a.ndim))
        mu = jnp.mean(a, axis=red, keepdims=True)
        var = jnp.var(a, axis=red, keepdims=True)
        out = (a - mu) / jnp.sqrt(var + epsilon)
        if w is not None:
            out = out * w
        if nb is not None:
            out = out + nb
        return (out, a) if has_r else out
    return apply_op("fused_layer_norm", fn, tuple(ts), {})


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5,
                                           ln_epsilon=1e-5,
                                           training=True,
                                           mode="upscale_in_train",
                                           name=None):
    """fused_bias_dropout_residual_layer_norm (incubate op): LayerNorm(
    residual + dropout(x + bias))."""
    y = fused_dropout_add(
        ensure_tensor(x) if bias is None else ensure_tensor(x)
        + ensure_tensor(bias),
        residual, p=dropout_rate, training=training, mode=mode)
    return fused_layer_norm(y, ln_scale, ln_bias, epsilon=ln_epsilon,
                            begin_norm_axis=y.ndim - 1)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode=
                      "upscale_in_train", name=None):
    """fused_feedforward (fused_transformer.py): the transformer FFN
    block — LN / linear1 / act / dropout / linear2 / dropout + residual
    — as one fused expression chain."""
    inp = ensure_tensor(x)
    h = inp
    if pre_layer_norm and ln1_scale is not None:
        h = fused_layer_norm(h, ln1_scale, ln1_bias, epsilon=ln1_epsilon,
                             begin_norm_axis=h.ndim - 1)
    h = fused_linear_activation(h, linear1_weight, linear1_bias,
                                activation=activation
                                if activation != "none" else "none")
    if training and dropout1_rate:
        from ...nn import functional as F
        h = F.dropout(h, p=dropout1_rate, training=True)
    h = fused_linear(h, linear2_weight, linear2_bias)
    h = fused_dropout_add(h, inp, p=dropout2_rate, training=training,
                          mode=mode)
    if not pre_layer_norm and ln2_scale is not None:
        h = fused_layer_norm(h, ln2_scale, ln2_bias, epsilon=ln2_epsilon,
                             begin_norm_axis=h.ndim - 1)
    return h


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None,
                               cache_kv=None, attn_mask=None,
                               dropout_rate=0.5,
                               attn_dropout_rate=0.5, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, name=None):
    """fused_multi_head_attention (fused_transformer.py:213): the full
    MHA block with fused qkv [3, H, D, hidden] weights."""
    from ...ops.dispatch import apply_op, ensure_tensor
    if cache_kv is not None:
        raise NotImplementedError(
            "fused_multi_head_attention cache_kv is the CUDA decode "
            "path; on TPU use nn.MultiHeadAttention with cache= or "
            "models.gpt.generate (scan KV cache)")
    inp = ensure_tensor(x)
    h = inp
    if pre_layer_norm and pre_ln_scale is not None:
        h = fused_layer_norm(h, pre_ln_scale, pre_ln_bias,
                             epsilon=pre_ln_epsilon,
                             begin_norm_axis=h.ndim - 1)
    qkvw = ensure_tensor(qkv_weight)
    ts = [ensure_tensor(h), qkvw]
    has_qb = qkv_bias is not None
    if has_qb:
        ts.append(ensure_tensor(qkv_bias))
    has_m = attn_mask is not None
    if has_m:
        ts.append(ensure_tensor(attn_mask))

    def attn(a, w, *rest):
        i = 0
        qb = rest[i] if has_qb else None
        i += has_qb
        m = rest[i] if has_m else None
        B, S, H = a.shape
        three, nh, hd, _ = w.shape
        qkv = jnp.einsum("bsh,tndh->tbsnd", a, w)
        if qb is not None:
            qkv = qkv + qb[:, None, None]
        q, k, v = qkv[0], qkv[1], qkv[2]
        scores = jnp.einsum("bsnd,btnd->bnst", q, k) / np.sqrt(hd)
        if m is not None:
            scores = scores + m
        p = jax.nn.softmax(scores, axis=-1)
        if training and attn_dropout_rate:
            keep = jax.random.bernoulli(_drop_key, 1.0 - attn_dropout_rate,
                                        p.shape)
            p = jnp.where(keep, p / (1.0 - attn_dropout_rate), 0.0)
        return jnp.einsum("bnst,btnd->bsnd", p, v).reshape(B, S, nh * hd)

    from ...framework import random as _fr
    _drop_key = _fr.next_key() if (training and attn_dropout_rate) \
        else None
    ctx = apply_op("fused_mha", attn, tuple(ts), {})
    out = fused_linear(ctx, linear_weight, linear_bias)
    if add_residual:
        out = fused_dropout_add(out, inp, p=dropout_rate,
                                training=training, mode=mode)
    if not pre_layer_norm and ln_scale is not None:
        out = fused_layer_norm(out, ln_scale, ln_bias, epsilon=ln_epsilon,
                               begin_norm_axis=out.ndim - 1)
    return out


def fused_moe(x, gate_weight, ffn1_weights, ffn2_weights, *args, **kwargs):
    """fused_moe: use incubate.MoELayer / distributed MoE dispatch — the
    TPU path is the GShard sort/scatter dispatch, not a monolithic
    kernel."""
    raise NotImplementedError(
        "fused_moe's monolithic kernel has no TPU analog; build the "
        "block with paddle.incubate.MoELayer (GShard dispatch, "
        "expert-parallel over the mesh)")


def masked_multihead_attention(x, cache_kv=None, bias=None,
                               src_mask=None, *args, **kwargs):
    raise NotImplementedError(
        "masked_multihead_attention is the CUDA serving decode kernel; "
        "on TPU use nn.MultiHeadAttention with cache= for decode, or "
        "models.gpt.generate (scan-based KV cache)")


def block_multihead_attention(*args, **kwargs):
    raise NotImplementedError(
        "block_multihead_attention (paged KV cache) is a CUDA serving "
        "kernel; the TPU serving path is paddle.inference over StableHLO "
        "with the flash-attention kernels")


def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size):
    """Serving helper: max sequence lengths for the block attention —
    host-computable and kept functional."""
    import numpy as _np
    from ...framework.tensor import Tensor
    enc = _np.asarray(ensure_tensor(seq_lens_encoder).numpy())
    dec = _np.asarray(ensure_tensor(seq_lens_decoder).numpy())
    return (Tensor(jnp.asarray([int(enc.max()) if enc.size else 0])),
            Tensor(jnp.asarray([int(dec.max()) if dec.size else 0])))


def variable_length_memory_efficient_attention(query, key, value,
                                               seq_lens, kv_seq_lens,
                                               mask=None, scale=None,
                                               causal=False, pre_cache_length=0):
    """Varlen attention: routes to the packed varlen flash path (the
    TPU-native equivalent of the CUDA memory-efficient kernel)."""
    q = ensure_tensor(query)   # [B, H, S, D]
    k = ensure_tensor(key)
    v = ensure_tensor(value)
    sl = ensure_tensor(seq_lens)
    kl = ensure_tensor(kv_seq_lens)
    ts = [q, k, v, sl, kl]
    has_m = mask is not None
    if has_m:
        ts.append(ensure_tensor(mask))

    def fn(qa, ka, va, sla, kla, *rest):
        B, H, S, D = qa.shape
        sc = scale if scale is not None else 1.0 / np.sqrt(D)
        scores = jnp.einsum("bhsd,bhtd->bhst", qa, ka) * sc
        if rest:
            scores = scores + rest[0]   # additive mask (ALiBi/padding)
        q_pos = jnp.arange(S)[None, None, :, None]
        k_pos = jnp.arange(ka.shape[2])[None, None, None, :]
        valid = ((q_pos < sla.reshape(-1)[:, None, None, None])
                 & (k_pos < kla.reshape(-1)[:, None, None, None]))
        if causal:
            valid = valid & (k_pos <= q_pos)
        scores = jnp.where(valid, scores, -1e9)
        p = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhst,bhtd->bhsd", p, va)

    return apply_op("varlen_mem_eff_attn", fn, tuple(ts), {})


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights,
                            qkv_biases, linear_weights, linear_biases,
                            ffn_ln_scales, ffn_ln_biases, ffn1_weights,
                            ffn1_biases, ffn2_weights, ffn2_biases,
                            pre_layer_norm=True, epsilon=1e-5,
                            cache_kvs=None, time_step=None,
                            attn_mask=None, dropout_rate=0.0,
                            activation="gelu", training=False,
                            mode="upscale_in_train", trans_qkvw=True,
                            ring_id=-1, name=None):
    """fused_multi_transformer (fused_transformer.py:750): a whole stack
    of pre-LN transformer layers in one call, composed from the fused
    blocks above (XLA fuses within each; the scan-based GPT stack is the
    training-speed path)."""
    h = x
    L = len(qkv_weights)
    if not trans_qkvw:
        # reference alternate layout [hidden, 3, H, D] -> [3, H, D, hidden]
        from ...ops.dispatch import ensure_tensor as _et
        from ...framework.tensor import Tensor as _T
        qkv_weights = [_T(jnp.transpose(_et(w)._data, (1, 2, 3, 0)))
                       for w in qkv_weights]
    for i in range(L):
        h = fused_multi_head_attention(
            h, qkv_weights[i], linear_weights[i], pre_layer_norm=True,
            pre_ln_scale=ln_scales[i],
            pre_ln_bias=ln_biases[i] if ln_biases else None,
            qkv_bias=qkv_biases[i] if qkv_biases else None,
            linear_bias=linear_biases[i] if linear_biases else None,
            attn_mask=attn_mask, dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate, training=training, mode=mode,
            pre_ln_epsilon=epsilon)
        h = fused_feedforward(
            h, ffn1_weights[i], ffn2_weights[i],
            linear1_bias=ffn1_biases[i] if ffn1_biases else None,
            linear2_bias=ffn2_biases[i] if ffn2_biases else None,
            ln1_scale=ffn_ln_scales[i],
            ln1_bias=ffn_ln_biases[i] if ffn_ln_biases else None,
            dropout1_rate=dropout_rate, dropout2_rate=dropout_rate,
            activation=activation, pre_layer_norm=True,
            ln1_epsilon=epsilon, training=training, mode=mode)
    return h
