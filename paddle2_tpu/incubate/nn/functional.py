"""paddle.incubate.nn.functional — genuinely fused TPU kernels.

Unlike the layer aliases in incubate.nn (where XLA's automatic fusion
covers the reference's fused_* kernels), the ops here are real fusions the
compiler cannot do on its own."""

from __future__ import annotations

from ...framework.tensor import Tensor
from ...ops.dispatch import apply_op, ensure_tensor

__all__ = ["fused_linear_cross_entropy", "fused_rotary_position_embedding",
           "fused_rms_norm", "fused_adamw_kernel"]

_ANGLE_CACHE: dict = {}


def _angle_table(S, D, base, neox, dtype):
    """Memoized rotary angle tables (decode loops call per step)."""
    import numpy as np
    import jax.numpy as jnp
    key = (S, D, base, neox, dtype)
    hit = _ANGLE_CACHE.get(key)
    if hit is not None:
        return hit
    import jax
    inv = 1.0 / (base ** (np.arange(0, D, 2, dtype=np.float64) / D))
    ang = np.arange(S, dtype=np.float64)[:, None] * inv[None]
    full = np.repeat(ang, 2, axis=1) if neox \
        else np.concatenate([ang, ang], axis=1)
    # concrete even under an active jit trace — otherwise the memo cache
    # would capture DynamicJaxprTracers and poison later eager calls
    with jax.ensure_compile_time_eval():
        out = (jnp.asarray(np.cos(full), dtype),
               jnp.asarray(np.sin(full), dtype))
    if len(_ANGLE_CACHE) > 64:
        _ANGLE_CACHE.clear()
    _ANGLE_CACHE[key] = out
    return out


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True,
                                    time_major=False,
                                    rotary_emb_base=10000.0):
    """Reference incubate fused_rotary_position_embedding.py:27 parity.

    q/k/v: [B, S, H, D]. On TPU the half-split convention
    (use_neox_rotary_style=False) runs the pallas fused_rope kernel —
    measured 2.23x over the XLA elementwise chain on v5e
    ([8,2048,16,128] bf16; the per-head angle broadcast stays in VMEM). The neox (adjacent-pair) convention and v (which rotary does
    not rotate in the reference either unless passed) use the XLA path.
    Returns (q_out, k_out, v_out) with None passthrough.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    first = next(t for t in (q, k, v) if t is not None)
    first = ensure_tensor(first)
    if time_major:
        raise NotImplementedError("time_major=True: transpose to "
                                  "[batch, seq, heads, dim] first")
    B, S, H, D = first.shape
    if sin is None or cos is None:
        rows = S
        if position_ids is not None \
                and not isinstance(ensure_tensor(position_ids)._data,
                                   jax.core.Tracer):
            # positions may exceed seq_len (decode loops index absolute
            # positions); JAX gathers clamp out-of-range indices, so an
            # S-row table would silently mis-rotate — size it to cover
            # the actual max position. Rows are bucketed to the next
            # multiple of 1024 so a decode loop reuses one memoized
            # table instead of rebuilding it every step. Traced
            # position_ids keep the S-row table (in-range by contract;
            # out-of-range needs explicit sin/cos sized to max position).
            pid = ensure_tensor(position_ids)._data
            max_pos = int(np.asarray(pid).max())
            if max_pos >= S:
                rows = -(-(max_pos + 1) // 1024) * 1024
        cos_a, sin_a = _angle_table(rows, D, float(rotary_emb_base),
                                    bool(use_neox_rotary_style),
                                    str(first._data.dtype))
    else:
        cos_a = ensure_tensor(cos)._data.reshape(-1, D)
        sin_a = ensure_tensor(sin)._data.reshape(-1, D)
        if cos_a.shape[0] != S and position_ids is None:
            if cos_a.shape[0] > S:
                # max-position table: positions are 0..S-1 here
                cos_a, sin_a = cos_a[:S], sin_a[:S]
            else:
                raise ValueError(
                    f"cos/sin table has {cos_a.shape[0]} positions but "
                    f"seq_len is {S}; pass position_ids or a table with "
                    "at least seq_len rows")
    if position_ids is not None:
        pos = ensure_tensor(position_ids)._data.astype(jnp.int32)
        cos_a = cos_a[pos].reshape(B * S, D)
        sin_a = sin_a[pos].reshape(B * S, D)

    try:
        on_accel = jax.devices()[0].platform.lower() != "cpu"
    except Exception:
        on_accel = False

    def rot_one(arr):
        if not use_neox_rotary_style and on_accel:
            from ...kernels.pallas_fused import fused_rope
            return fused_rope(arr, cos_a, sin_a)
        c = cos_a.reshape(-1, S, 1, D) if cos_a.shape[0] != S \
            else cos_a[None, :, None, :]
        s = sin_a.reshape(-1, S, 1, D) if sin_a.shape[0] != S \
            else sin_a[None, :, None, :]
        if use_neox_rotary_style:
            x1 = arr[..., 0::2]
            x2 = arr[..., 1::2]
            rot = jnp.stack([-x2, x1], axis=-1).reshape(arr.shape)
        else:
            x1 = arr[..., : D // 2]
            x2 = arr[..., D // 2:]
            rot = jnp.concatenate([-x2, x1], axis=-1)
        return arr * c + rot * s

    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        t = ensure_tensor(t)
        outs.append(apply_op("fused_rope", rot_one, (t,), {}))
    return tuple(outs)


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None):
    """Pallas one-pass RMSNorm (fwd + custom bwd); with `bias`/`residual`
    the reference's fused add-then-norm: y = norm(x + bias + residual),
    returning (y, x + bias + residual) like fused_rms_norm's residual
    form. Quantized variants are not supported. NOTE: measured 0.83x of
    the XLA-fused chain on v5e ([8192,1024] bf16 fwd+bwd) — provided for
    reference parity and as a building block; prefer the plain
    expression under jit."""
    from ...kernels.pallas_fused import fused_rms_norm as kern
    x, w = ensure_tensor(x), ensure_tensor(norm_weight)
    nd = x.ndim
    if begin_norm_axis not in (-1, nd - 1):
        raise NotImplementedError(
            f"fused_rms_norm normalizes the LAST axis only "
            f"(begin_norm_axis={begin_norm_axis}, ndim={nd}); reshape "
            "so the normalized dims are flattened into the last axis")
    tensors = [x, w]
    if norm_bias is not None:
        tensors.append(ensure_tensor(norm_bias))
    if bias is not None:
        tensors.append(ensure_tensor(bias))
    if residual is not None:
        tensors.append(ensure_tensor(residual))

    def fn(xa, wa, *rest):
        it = iter(rest)
        nb = next(it) if norm_bias is not None else None
        ba = next(it) if bias is not None else None
        ra = next(it) if residual is not None else None
        pre = xa
        if ba is not None:
            pre = pre + ba
        if ra is not None:
            pre = pre + ra
        out = kern(pre, wa, epsilon=epsilon)
        if nb is not None:
            out = out + nb
        if residual is not None:
            return out, pre
        return out

    return apply_op("fused_rms_norm", fn, tuple(tensors), {})


def fused_adamw_kernel(param, grad, m, v, master, lr, beta1=0.9,
                       beta2=0.999, epsilon=1e-8, weight_decay=0.01,
                       step=1):
    """Single-pass pallas AdamW (fused_adam_kernel.cu parity). NOTE:
    measured 0.44x of XLA's fused update on v5e (84M f32 donated) — XLA
    already emits a one-pass loop for the update chain; kept for parity
    and for runtimes where the update is not under jit."""
    from ...kernels.pallas_fused import fused_adamw as kern
    outs = kern(ensure_tensor(param)._data, ensure_tensor(grad)._data,
                ensure_tensor(m)._data, ensure_tensor(v)._data,
                ensure_tensor(master)._data, lr, beta1, beta2, epsilon,
                weight_decay, step)
    return tuple(Tensor(a, stop_gradient=True) for a in outs)


def fused_linear_cross_entropy(x, weight, label, ignore_index=-100,
                               reduction="mean", name=None):
    """Cross-entropy of `softmax(x @ weight)` without materializing the
    [N, vocab] logits (chunked head+loss; kernels/fused_ce.py). The
    memory/bandwidth saver for large-vocab LM heads — the analog of the
    reference's c_softmax_with_cross_entropy fusion
    (python/paddle/distributed/fleet/layers/mpu/mp_ops.py) for the
    single-device case.

    x: [N, hidden] (or [B, S, hidden], flattened internally);
    weight: [hidden, vocab]; label: int [N] / [B, S].
    reduction: 'mean' over non-ignored tokens | 'sum' | 'none'.
    """
    from ...kernels.fused_ce import fused_linear_cross_entropy as kern
    import jax.numpy as jnp

    x, weight, label = (ensure_tensor(x), ensure_tensor(weight),
                        ensure_tensor(label))

    def fn(xa, wa, la):
        hidden = xa.shape[-1]
        losses, valid = kern(xa.reshape(-1, hidden), wa,
                             la.reshape(-1).astype(jnp.int32),
                             int(ignore_index))
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
            return jnp.sum(losses) / denom
        if reduction == "sum":
            return jnp.sum(losses)
        return losses.reshape(la.shape)

    return apply_op("fused_linear_cross_entropy", fn, (x, weight, label), {})
