"""paddle.incubate.optimizer — LookAhead and ModelAverage
(python/paddle/incubate/optimizer/lookahead.py:36, modelaverage.py).

Both are wrappers around an inner optimizer's parameters; the per-param
auxiliary arrays (slow weights, accumulation sums) live as device
arrays updated by small jitted expressions — no host loops over weights.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from ..framework import core

__all__ = ["LookAhead", "ModelAverage"]


@jax.jit
def _slow_update(slow, fast, alpha):
    new_slow = [s + alpha * (f - s) for s, f in zip(slow, fast)]
    return new_slow


class LookAhead:
    """lookahead.py:36: the inner optimizer updates fast weights every
    step; every ``k`` steps the slow weights move toward them
    (slow += alpha * (fast - slow)) and the fast weights reset to slow.
    """

    def __init__(self, inner_optimizer, alpha: float = 0.5, k: int = 5,
                 name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha should be in [0, 1]")
        if k < 1:
            raise ValueError("k should be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._k_step = 0
        self._slow: Dict[int, jax.Array] = {}

    def _params(self):
        return [p for g in self.inner_optimizer._param_groups
                for p in g["params"] if p is not None and p.trainable]

    @core.no_grad
    def step(self):
        # slow weights start at the param value BEFORE its first fast
        # update (reference _create_accumulators timing)
        for p in self._params():
            if id(p) not in self._slow:
                self._slow[id(p)] = p._data
        self.inner_optimizer.step()
        self._k_step += 1
        if self._k_step % self.k != 0:
            return
        params = self._params()
        fast = [p._data for p in params]
        slow = [self._slow[id(p)] for p in params]
        new_slow = _slow_update(slow, fast, jnp.float32(self.alpha))
        for p, s in zip(params, new_slow):
            self._slow[id(p)] = s
            p._replace_data(s.astype(p._data.dtype))

    def clear_grad(self, set_to_zero: bool = False):
        self.inner_optimizer.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, **kwargs):
        loss.backward()
        self.step()
        return None, None

    def state_dict(self):
        state = self.inner_optimizer.state_dict()
        state["@lookahead_k_step"] = self._k_step
        # slow weights keyed by parameter position (ids don't survive a
        # process restart)
        params = self._params()
        state["@lookahead_slow"] = {
            i: self._slow[id(p)] for i, p in enumerate(params)
            if id(p) in self._slow}
        return state

    def set_state_dict(self, state):
        state = dict(state)   # leave the caller's dict reusable
        self._k_step = state.pop("@lookahead_k_step", 0)
        slow = state.pop("@lookahead_slow", {})
        params = self._params()
        self._slow = {id(params[int(i)]): jnp.asarray(v)
                      for i, v in slow.items()}
        self.inner_optimizer.set_state_dict(state)

    def __getattr__(self, name):
        if name == "inner_optimizer":   # unpickle/deepcopy guard
            raise AttributeError(name)
        return getattr(self.inner_optimizer, name)


class ModelAverage:
    """modelaverage.py: accumulate parameter values over a sliding
    window; ``apply()`` swaps in the window average for evaluation,
    ``restore()`` swaps the live weights back.

    Window reset rule (modelaverage.py:63): when num_accumulates >=
    min_average_window and >= min(max_average_window,
    num_updates * average_window_rate), the current sum rolls into the
    previous-window sum and restarts.
    """

    def __init__(self, average_window_rate: float, parameters=None,
                 min_average_window: int = 10000,
                 max_average_window: int = 10000, name=None):
        if parameters is None:
            raise ValueError("parameters is required (pass "
                             "model.parameters())")
        self.average_window = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        self._params = [p for p in parameters if p is not None]
        self._sum_cur = {id(p): jnp.zeros_like(p._data, jnp.float32)
                         for p in self._params}
        self._sum_prev = {id(p): jnp.zeros_like(p._data, jnp.float32)
                          for p in self._params}
        self._num_accumulates = 0
        self._old_num_accumulates = 0
        self._num_updates = 0
        self._saved = None

    @core.no_grad
    def step(self):
        """Accumulate the current parameter values (call after the inner
        optimizer's step)."""
        self._num_updates += 1
        self._num_accumulates += 1
        for p in self._params:
            self._sum_cur[id(p)] = (self._sum_cur[id(p)]
                                    + p._data.astype(jnp.float32))
        window = min(self.max_average_window,
                     self._num_updates * self.average_window)
        if (self._num_accumulates >= self.min_average_window
                and self._num_accumulates >= window):
            for p in self._params:
                self._sum_prev[id(p)] = self._sum_cur[id(p)]
                self._sum_cur[id(p)] = jnp.zeros_like(p._data, jnp.float32)
            self._old_num_accumulates = self._num_accumulates
            self._num_accumulates = 0

    @core.no_grad
    def apply(self, executor=None, need_restore: bool = True):
        """Swap the window-averaged weights in (for evaluation). With
        ``need_restore=False`` the live weights are NOT backed up and a
        later restore() is a no-op (the averaged weights become final —
        the reference's deploy path)."""
        total = self._num_accumulates + self._old_num_accumulates
        if total == 0:
            return
        self._saved = ({id(p): p._data for p in self._params}
                       if need_restore else None)
        for p in self._params:
            avg = (self._sum_cur[id(p)] + self._sum_prev[id(p)]) / total
            p._replace_data(avg.astype(p._data.dtype))

    @core.no_grad
    def restore(self, executor=None):
        """Swap the live (non-averaged) weights back."""
        if self._saved is None:
            return
        for p in self._params:
            p._replace_data(self._saved[id(p)])
        self._saved = None

    def minimize(self, loss, **kwargs):
        self.step()
        return None, None
