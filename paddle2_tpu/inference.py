"""paddle.inference (reference paddle/fluid/inference/api/
paddle_inference_api.h:53 Config/Predictor contract).

TPU-native inference engine: the artifact is the StableHLO program that
jit.save exports (.pdmodel + .pdiparams); Predictor wraps the deserialized
executable. The reference's GPU/TensorRT/MKLDNN toggles are accepted and
recorded but inert — XLA owns codegen on TPU.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "PredictorPool",
           "get_version"]


class Config:
    """AnalysisConfig parity (inference_api.cc Config)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self._prefix = prog_file
        self._params_file = params_file          # explicit path wins
        self._use_gpu = False
        self._device_id = 0
        self._cpu_math_threads = 1
        self._memory_optim = True
        self._ir_optim = True
        self._switches: Dict[str, bool] = {}
        self._serving: Optional[dict] = None

    # -- model location --------------------------------------------------
    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        self._prefix = prog_file[:-len(".pdmodel")] \
            if prog_file.endswith(".pdmodel") else prog_file
        # an explicit params_file is honored verbatim (the reference
        # contract — weights may live under a different prefix than the
        # program); omitting it falls back to prefix-derived
        self._params_file = params_file

    def model_dir(self):
        return os.path.dirname(self._prefix or "")

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        if self._params_file:
            return self._params_file
        return (self._prefix or "") + ".pdiparams"

    # -- serving (paddle2_tpu.serving integration) -----------------------
    def enable_continuous_batching(self, **engine_kwargs):
        """Route this config to the continuous-batching
        :class:`~paddle2_tpu.serving.ServingEngine` instead of the
        one-request-at-a-time Predictor. ``engine_kwargs`` are
        :class:`~paddle2_tpu.serving.EngineConfig` fields (block_size,
        num_blocks, max_batch, weight_only_int8, ...). Build the
        engine with :meth:`create_serving_engine` — it needs the GPT
        architecture config, which the serialized artifact does not
        carry."""
        self._serving = dict(engine_kwargs)

    def continuous_batching_enabled(self) -> bool:
        return self._serving is not None

    def create_serving_engine(self, gpt_config):
        from .serving import EngineConfig, ServingEngine
        if self._serving is None:
            raise ValueError("call enable_continuous_batching() first")
        return ServingEngine(artifact_path=self._prefix,
                             artifact_params_path=self.params_file(),
                             gpt_config=gpt_config,
                             config=EngineConfig(**self._serving))

    # -- device knobs (recorded; XLA decides on TPU) ---------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._use_gpu = True
        self._device_id = device_id

    def disable_gpu(self):
        self._use_gpu = False

    def use_gpu(self):
        return self._use_gpu

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_math_threads = n

    def enable_memory_optim(self, flag=True):
        self._memory_optim = flag

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def enable_tensorrt_engine(self, *a, **k):
        self._switches["tensorrt"] = True  # inert on TPU

    def enable_mkldnn(self):
        self._switches["mkldnn"] = True  # inert on TPU

    def summary(self):
        return {"model": self._prefix, "use_gpu": self._use_gpu,
                "switches": dict(self._switches)}


class _IOTensor:
    """PaddleTensor-ish handle (copy_from_cpu / copy_to_cpu contract)."""

    def __init__(self, owner: "Predictor", name: str, is_input: bool):
        self._owner = owner
        self.name = name
        self._is_input = is_input

    def copy_from_cpu(self, arr: np.ndarray):
        self._owner._feed[self.name] = np.asarray(arr)

    def reshape(self, shape):
        pass  # shapes flow from the fed array

    def copy_to_cpu(self) -> np.ndarray:
        return self._owner._fetch[self.name]


class Predictor:
    """paddle_infer::Predictor parity over a TranslatedLayer."""

    def __init__(self, config: Config):
        from .jit.api import load as jit_load
        if not os.path.exists(config.prog_file()):
            raise ValueError(
                f"no program at {config.prog_file()}; produce it with "
                "paddle.jit.save(layer, path, input_spec=[...])")
        # honor an explicitly-set params file (set_model's second arg)
        self._loaded = jit_load(config._prefix,
                                params_path=config.params_file())
        self._config = config
        self._n_inputs = None
        self._feed: Dict[str, np.ndarray] = {}
        self._fetch: Dict[str, np.ndarray] = {}

    def get_input_names(self) -> List[str]:
        n = self._n_inputs
        if n is None:
            try:
                n = len(self._loaded._exported.in_avals[1])
            except Exception:
                n = 1
            self._n_inputs = n
        return [f"x{i}" for i in range(n)]

    def get_input_handle(self, name: str) -> _IOTensor:
        return _IOTensor(self, name, True)

    def get_output_names(self) -> List[str]:
        return [f"out{i}" for i in range(len(self._fetch) or 1)]

    def get_output_handle(self, name: str) -> _IOTensor:
        return _IOTensor(self, name, False)

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        if inputs is None:
            names = self.get_input_names()
            missing = [k for k in names if k not in self._feed]
            if missing:
                raise ValueError(
                    f"inputs not fed: {missing}; call copy_from_cpu on "
                    "every input handle before run()")
            inputs = [self._feed[k] for k in names]
        outs = self._loaded(*[np.asarray(a) for a in inputs])
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        self._fetch = {f"out{i}": np.asarray(o.numpy())
                       for i, o in enumerate(outs)}
        return [self._fetch[f"out{i}"] for i in range(len(outs))]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


class PredictorPool:
    """Fixed pool of Predictors for multi-threaded callers
    (paddle_infer::services::PredictorPool parity).

    Hand-out is thread-safe: ``acquire()`` pops the oldest free slot
    (FIFO) under a condition variable and ``release()`` returns it —
    the free-list bookkeeping is the shared state; Predictor.run
    itself is per-instance. ``retrieve(idx)`` keeps the reference's
    direct-index contract."""

    def __init__(self, config: Config, size: int = 1):
        self._preds = [Predictor(config) for _ in range(size)]
        self._mu = threading.Lock()
        self._free = list(range(size))
        self._cv = threading.Condition(self._mu)

    def retrieve(self, idx: int) -> Predictor:
        return self._preds[idx]

    def acquire(self, timeout: Optional[float] = None) -> Predictor:
        """Check out a free Predictor (blocks until one is released;
        raises TimeoutError past ``timeout`` seconds)."""
        with self._cv:
            if not self._cv.wait_for(lambda: bool(self._free),
                                     timeout=timeout):
                raise TimeoutError("no free Predictor in pool")
            idx = self._free.pop(0)
            pred = self._preds[idx]
            pred._pool_idx = idx
            return pred

    def release(self, pred: Predictor) -> None:
        with self._cv:
            idx = getattr(pred, "_pool_idx", None)
            if idx is None or self._preds[idx] is not pred:
                raise ValueError("predictor does not belong to this pool")
            if idx in self._free:
                raise ValueError(f"double release of pool slot {idx}")
            self._free.append(idx)
            self._cv.notify()


def get_version() -> str:
    from .version import full_version
    return full_version
