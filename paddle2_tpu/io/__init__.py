from .dataloader import *  # noqa: F401,F403
