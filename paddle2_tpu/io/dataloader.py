"""Datasets, samplers, DataLoader (python/paddle/io/ parity).

The reference's multiprocess worker pool over shared memory
(dataloader/dataloader_iter.py:368,448) maps to a thread pool + prefetch
queue here: workers produce numpy batches (GIL released in numpy/IO), the
main thread uploads to HBM — the standard input pipeline shape for TPU
hosts. num_workers>0 enables the pool; 0 is synchronous.
"""

from __future__ import annotations

import itertools
import queue
import threading
from typing import Any, Callable, Iterable, List, Optional, Sequence, Union

import numpy as np

from ..framework.tensor import Tensor

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "ConcatDataset", "Subset", "random_split",
           "Sampler", "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
           "BatchSampler", "DistributedBatchSampler", "SubsetRandomSampler",
           "DataLoader", "default_collate_fn", "get_worker_info"]


# ---------------------------------------------------------------------------
# datasets
# ---------------------------------------------------------------------------

class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            item = ds[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(ds) for ds in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cum[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if ds_idx == 0 else self.cum[ds_idx - 1]
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        sizes = [int(np.floor(n * l)) for l in lengths]
        for i in range(n - sum(sizes)):
            sizes[i % len(sizes)] += 1
        lengths = sizes
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths != dataset size")
    perm = np.random.permutation(len(dataset)).tolist()
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset:offset + l]))
        offset += l
    return out


# ---------------------------------------------------------------------------
# samplers
# ---------------------------------------------------------------------------

class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices):
        super().__init__(None)
        self.indices = list(indices)

    def __iter__(self):
        return iter(np.random.permutation(self.indices).tolist())

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__(None)
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        super().__init__(dataset)
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Rank-sharded batches (python/paddle/io/dataloader/batch_sampler.py
    DistributedBatchSampler parity)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import env as dist_env
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = (num_replicas if num_replicas is not None
                       else dist_env.get_world_size())
        self.local_rank = rank if rank is not None else dist_env.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        indices = np.arange(len(self.dataset))
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate(
            [indices, indices[: self.total_size - len(indices)]])
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


# ---------------------------------------------------------------------------
# collate + loader
# ---------------------------------------------------------------------------

def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return type(sample)(default_collate_fn(list(items))
                            for items in zip(*batch))
    raise TypeError(f"cannot collate {type(sample)}")


class _WorkerInfo:
    def __init__(self, id_, num_workers, dataset):
        self.id = id_
        self.num_workers = num_workers
        self.dataset = dataset


_worker_tls = threading.local()


def get_worker_info():
    return getattr(_worker_tls, "info", None)


class _SyncIter:
    """num_workers=0 path, tracked: exposes the emitted-batch cursor
    (``next_emit``) that DataLoader.state_dict reads for exact resume."""

    def __init__(self, loader, batches):
        self.loader = loader
        self.batches = batches
        self.next_emit = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self.next_emit >= len(self.batches):
            self.loader._note_epoch_end(self)
            raise StopIteration
        batch = self.loader._fetch(self.batches[self.next_emit])
        self.next_emit += 1
        return batch


class _PrefetchIter:
    """Thread-pool prefetcher: ordered batch delivery, bounded queue."""

    def __init__(self, loader, index_iter):
        self.loader = loader
        self.index_iter = enumerate(index_iter)
        self.results: dict = {}
        self.next_emit = 0
        self.next_submit = 0
        self.lock = threading.Lock()
        self.done = False
        self.sem = threading.Semaphore(0)
        self.error = None
        n = loader.num_workers
        self.threads = [threading.Thread(target=self._worker, args=(i,),
                                         daemon=True) for i in range(n)]
        for t in self.threads:
            t.start()

    def _worker(self, wid):
        _worker_tls.info = _WorkerInfo(wid, self.loader.num_workers,
                                       self.loader.dataset)
        while True:
            with self.lock:
                if self.error is not None or self.done:
                    return
                try:
                    i, indices = next(self.index_iter)
                except StopIteration:
                    self.done = True
                    self.sem.release()
                    return
            try:
                batch = self.loader._fetch(indices)
            except BaseException as e:  # propagate to main thread
                with self.lock:
                    self.error = e
                self.sem.release()
                return
            with self.lock:
                self.results[i] = batch
            self.sem.release()

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            with self.lock:
                if self.error is not None:
                    raise self.error
                if self.next_emit in self.results:
                    batch = self.results.pop(self.next_emit)
                    self.next_emit += 1
                    return batch
                if self.done and not self.results and all(
                        not t.is_alive() for t in self.threads):
                    self.loader._note_epoch_end(self)
                    raise StopIteration
            self.sem.acquire(timeout=1.0)


class DataLoader:
    """python/paddle/io/reader.py:262 parity, plus EXACT-RESUME state:
    ``state_dict()`` captures the in-flight epoch (the materialized batch
    index sequence — shuffle already applied — the emitted-batch cursor,
    the sampler epoch, and the numpy RNG state) and
    ``load_state_dict()`` arms the next ``__iter__`` to continue at the
    exact next batch with no replay and no skip. Register the loader
    with ``fault_tolerance.CheckpointManager.register_stateful`` so a
    preempt/rollback resumes the data stream with the model."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, worker_restarts=2):
        self.dataset = dataset
        self.num_workers = max(0, num_workers)
        self.collate_fn = collate_fn or default_collate_fn
        self.worker_init_fn = worker_init_fn
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        # restart budget per shm worker before the iterator escalates
        # a crashed worker to the step-level retry loop
        self.worker_restarts = max(0, int(worker_restarts))
        self._iterable_mode = isinstance(dataset, IterableDataset)
        self._epoch = 0
        self._active = None      # (epoch batch list, start, live iterator)
        self._resume = None      # armed by load_state_dict
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)
            self.batch_size = batch_size

    def _fetch(self, indices):
        samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        batches, start = self._epoch_plan()
        remaining = batches[start:]
        it = None
        if self.num_workers == 0:
            it = _SyncIter(self, remaining)
        elif self.use_shared_memory and \
                self.collate_fn is default_collate_fn:
            # multiprocess + C++ shm ring: Python decode escapes the GIL
            # (reference dataloader_iter.py:368 design); falls back to the
            # thread prefetcher when the native lib can't build
            try:
                from .shm_loader import ShmProcessIter
                it = ShmProcessIter(self, remaining)
            except (RuntimeError, OSError):
                it = None
        if it is None:
            it = _PrefetchIter(self, iter(remaining))
        self._active = (batches, start, it)
        return it

    def _epoch_plan(self):
        """Batch index sequence for the epoch about to start, plus the
        cursor to resume from (0 unless load_state_dict armed one)."""
        if self._resume is not None:
            st, self._resume = self._resume, None
            return [list(b) for b in st["batches"]], int(st["cursor"])
        return [list(b) for b in self.batch_sampler], 0

    def _note_epoch_end(self, it):
        if self._active is not None and self._active[2] is it:
            self._active = None
            self._epoch += 1

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not getattr(self, "drop_last", False):
            yield self.collate_fn(batch)

    # -- resumable-pipeline state ---------------------------------------
    def state_dict(self):
        """Snapshot the data stream position. Mid-epoch, the in-flight
        epoch's exact batch sequence (shuffle RNG already applied) and
        the emitted-batch cursor are captured, so a restore yields the
        REMAINING batches only — no duplicates, no gaps; prefetched but
        not-yet-emitted batches are re-decoded, never re-trained. The
        numpy RNG state rides along so every SUBSEQUENT epoch's shuffle
        also replays identically."""
        if self._iterable_mode:
            raise TypeError(
                "IterableDataset pipelines stream without an index "
                "order, so DataLoader.state_dict() cannot capture an "
                "exact cursor; give the dataset itself "
                "state_dict/load_state_dict and register it directly")
        state = {"version": 1, "epoch": self._epoch, "cursor": 0,
                 "batches": None,
                 "sampler_epoch": getattr(self.batch_sampler, "epoch",
                                          None),
                 "np_rng_state": np.random.get_state()}
        if self._active is not None:
            batches, start, it = self._active
            state["cursor"] = start + int(it.next_emit)
            state["batches"] = [list(b) for b in batches]
        elif self._resume is not None:   # saved again before iterating
            state["cursor"] = int(self._resume["cursor"])
            state["batches"] = [list(b) for b in self._resume["batches"]]
        return state

    def load_state_dict(self, state):
        if not isinstance(state, dict) or "epoch" not in state:
            raise ValueError("not a DataLoader state_dict")
        if int(state.get("version", 1)) != 1:
            raise ValueError(
                f"DataLoader state version {state.get('version')} is "
                f"newer than this runtime understands")
        self._epoch = int(state["epoch"])
        if state.get("np_rng_state") is not None:
            np.random.set_state(state["np_rng_state"])
        if state.get("sampler_epoch") is not None and \
                hasattr(self.batch_sampler, "set_epoch"):
            self.batch_sampler.set_epoch(int(state["sampler_epoch"]))
        batches, cursor = state.get("batches"), int(state.get("cursor", 0))
        if batches is not None and cursor < len(batches):
            self._resume = {"batches": batches, "cursor": cursor}
        else:
            self._resume = None
