"""Native runtime components: build + ctypes bindings for the C++
shared-memory ring buffer (the DataLoader data plane)."""

from .build import load_shm_ring  # noqa: F401
