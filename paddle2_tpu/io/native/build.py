"""Builds libshmring.so on first use with g++ (cached next to the source;
no pip/pybind11 — plain C ABI consumed via ctypes)."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "shm_ring.cpp")
_LIB = os.path.join(_HERE, "libshmring.so")
_lock = threading.Lock()
_lib = None


def _compile() -> str:
    # pid-unique output: concurrent ranks may build simultaneously and
    # os.replace must publish only a COMPLETE library
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++14", _SRC,
           "-o", tmp, "-lrt", "-pthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, _LIB)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return _LIB


def load_shm_ring():
    """Returns the bound ctypes library, building it if needed; raises
    RuntimeError when no toolchain is available (callers fall back to the
    thread-pool loader)."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB) or (
                os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            try:
                _compile()
            except (subprocess.CalledProcessError, FileNotFoundError) as e:
                raise RuntimeError(f"cannot build libshmring.so: {e}")
        lib = ctypes.CDLL(_LIB)
        lib.rb_create.restype = ctypes.c_void_p
        lib.rb_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.rb_attach.restype = ctypes.c_void_p
        lib.rb_attach.argtypes = [ctypes.c_char_p]
        lib.rb_push.restype = ctypes.c_int
        lib.rb_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_uint64, ctypes.c_int]
        lib.rb_next_len.restype = ctypes.c_int64
        lib.rb_next_len.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.rb_pop.restype = ctypes.c_int
        lib.rb_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                               ctypes.c_uint64]
        lib.rb_close_producer.argtypes = [ctypes.c_void_p]
        lib.rb_used.restype = ctypes.c_uint64
        lib.rb_used.argtypes = [ctypes.c_void_p]
        lib.rb_detach.argtypes = [ctypes.c_void_p]
        lib.rb_unlink.argtypes = [ctypes.c_char_p]
        _lib = lib
        return lib
