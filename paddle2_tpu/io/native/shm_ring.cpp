// Shared-memory SPSC ring buffer — the native data plane of the
// multiprocess DataLoader (parity target: the reference's shared-memory
// LoDTensor transport in python/paddle/fluid/dataloader/dataloader_iter.py
// + paddle/fluid/memory/allocation (shm blocks); re-designed as a lockless
// single-producer/single-consumer byte ring per worker, C ABI for ctypes).
//
// Layout in the shm segment:
//   [Header{head, tail, capacity, closed} | data bytes ...]
// Records are [u64 len][payload]; the ring wraps byte-wise. head is
// advanced by the consumer, tail by the producer; both are C++11 atomics
// on cache-line-separated fields, so no locks are needed.
//
// Build: g++ -O2 -shared -fPIC shm_ring.cpp -o libshmring.so -lrt

#include <atomic>
#include <new>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct alignas(64) Header {
  std::atomic<uint64_t> head;   // consumer cursor (bytes consumed)
  char pad0[64 - sizeof(std::atomic<uint64_t>)];
  std::atomic<uint64_t> tail;   // producer cursor (bytes written)
  char pad1[64 - sizeof(std::atomic<uint64_t>)];
  uint64_t capacity;            // data area size in bytes
  std::atomic<uint32_t> closed; // producer hung up
};

struct Ring {
  Header* hdr;
  uint8_t* data;
  size_t map_len;
  bool owner;
  char name[256];
};

inline uint64_t used(const Header* h) {
  return h->tail.load(std::memory_order_acquire)
       - h->head.load(std::memory_order_acquire);
}

void sleep_us(long us) {
  struct timespec ts{0, us * 1000L};
  nanosleep(&ts, nullptr);
}

// byte-wise circular copy in/out of the data area
void write_bytes(Ring* r, uint64_t pos, const uint8_t* src, uint64_t len) {
  uint64_t cap = r->hdr->capacity;
  uint64_t off = pos % cap;
  uint64_t first = (off + len <= cap) ? len : cap - off;
  memcpy(r->data + off, src, first);
  if (first < len) memcpy(r->data, src + first, len - first);
}

void read_bytes(Ring* r, uint64_t pos, uint8_t* dst, uint64_t len) {
  uint64_t cap = r->hdr->capacity;
  uint64_t off = pos % cap;
  uint64_t first = (off + len <= cap) ? len : cap - off;
  memcpy(dst, r->data + off, first);
  if (first < len) memcpy(dst + first, r->data, len - first);
}

}  // namespace

extern "C" {

void* rb_create(const char* name, uint64_t capacity) {
  shm_unlink(name);  // stale segment from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  size_t total = sizeof(Header) + capacity;
  if (ftruncate(fd, (off_t)total) != 0) { close(fd); return nullptr; }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* h = new (mem) Header();
  h->head.store(0); h->tail.store(0);
  h->capacity = capacity;
  h->closed.store(0);
  Ring* r = new Ring();
  r->hdr = h;
  r->data = (uint8_t*)mem + sizeof(Header);
  r->map_len = total;
  r->owner = true;
  strncpy(r->name, name, sizeof(r->name) - 1);
  return r;
}

void* rb_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
  void* mem = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Ring* r = new Ring();
  r->hdr = (Header*)mem;
  r->data = (uint8_t*)mem + sizeof(Header);
  r->map_len = (size_t)st.st_size;
  r->owner = false;
  strncpy(r->name, name, sizeof(r->name) - 1);
  return r;
}

// push one [len][payload] record; blocks while the ring is full.
// returns 0 ok, -1 timeout, -2 record larger than capacity.
int rb_push(void* rv, const void* buf, uint64_t len, int timeout_ms) {
  Ring* r = (Ring*)rv;
  Header* h = r->hdr;
  uint64_t need = len + 8;
  if (need > h->capacity) return -2;
  long waited_us = 0;
  while (h->capacity - used(h) < need) {
    if (timeout_ms >= 0 && waited_us / 1000 >= timeout_ms) return -1;
    sleep_us(200);
    waited_us += 200;
  }
  uint64_t tail = h->tail.load(std::memory_order_relaxed);
  uint64_t len_le = len;
  write_bytes(r, tail, (const uint8_t*)&len_le, 8);
  write_bytes(r, tail + 8, (const uint8_t*)buf, len);
  h->tail.store(tail + need, std::memory_order_release);
  return 0;
}

// size of the next record, blocking until one exists.
// returns len >= 0, -1 on timeout, -3 if closed and drained.
int64_t rb_next_len(void* rv, int timeout_ms) {
  Ring* r = (Ring*)rv;
  Header* h = r->hdr;
  long waited_us = 0;
  while (used(h) < 8) {
    if (h->closed.load(std::memory_order_acquire) && used(h) == 0)
      return -3;
    if (timeout_ms >= 0 && waited_us / 1000 >= timeout_ms) return -1;
    sleep_us(200);
    waited_us += 200;
  }
  uint64_t len;
  read_bytes(r, h->head.load(std::memory_order_relaxed), (uint8_t*)&len, 8);
  return (int64_t)len;
}

// copy out the next record (len from rb_next_len) and advance.
int rb_pop(void* rv, void* out, uint64_t len) {
  Ring* r = (Ring*)rv;
  Header* h = r->hdr;
  uint64_t head = h->head.load(std::memory_order_relaxed);
  read_bytes(r, head + 8, (uint8_t*)out, len);
  h->head.store(head + 8 + len, std::memory_order_release);
  return 0;
}

void rb_close_producer(void* rv) {
  ((Ring*)rv)->hdr->closed.store(1, std::memory_order_release);
}

uint64_t rb_used(void* rv) { return used(((Ring*)rv)->hdr); }

void rb_detach(void* rv) {
  Ring* r = (Ring*)rv;
  munmap((void*)r->hdr, r->map_len);
  delete r;
}

void rb_unlink(const char* name) { shm_unlink(name); }

}  // extern "C"
