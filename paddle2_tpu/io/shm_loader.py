"""Multiprocess DataLoader path over the native shared-memory ring
(reference dataloader_iter.py:368 _DataLoaderIterMultiProcess + its shm
LoDTensor transport; here the data plane is the C++ SPSC ring in
io/native/shm_ring.cpp and workers are forked processes, so Python decode
work escapes the GIL — the exact limitation of the thread prefetcher).

Workers are jax-free: they decode+collate to NUMPY trees, pickle into
their ring, and the main process materializes Tensors. Batch order is
deterministic: worker w owns batches w, w+W, ... and the consumer drains
rings round-robin.
"""

from __future__ import annotations

import ctypes
import os
import pickle
import signal
import threading
import traceback
from typing import Any, List

import numpy as np

_DEF_RING_BYTES = 64 << 20  # per worker


def _np_collate(batch):
    """default_collate_fn shape contract, numpy leaves only."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: _np_collate([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return type(sample)(_np_collate(list(items))
                            for items in zip(*batch))
    if hasattr(sample, "numpy"):  # Tensor-like snuck into a worker
        return np.stack([np.asarray(s.numpy()) for s in batch])
    raise TypeError(f"cannot collate {type(sample)}")


def _to_tensor_tree(obj):
    from ..framework.tensor import Tensor
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, dict):
        return {k: _to_tensor_tree(v) for k, v in obj.items()}
    if isinstance(obj, (tuple, list)):
        return type(obj)(_to_tensor_tree(v) for v in obj)
    return obj


class ShmProcessIter:
    """Ordered multiprocess iterator (one ring per worker)."""

    def __init__(self, loader, batches: List[List[int]],
                 ring_bytes: int = 0):
        from .native import load_shm_ring
        self._lib = load_shm_ring()
        self.loader = loader
        self.batches = batches
        self.W = loader.num_workers
        self.next_emit = 0
        # timeout=0 means wait forever (reference DataLoader semantics)
        t = float(getattr(loader, "timeout", 0) or 0)
        self._timeout_ms = int(t * 1000) if t > 0 else -1
        ring_bytes = ring_bytes or int(os.environ.get(
            "PADDLE2_TPU_SHM_RING_BYTES", _DEF_RING_BYTES))
        uid = f"/p2t_{os.getpid()}_{id(self) & 0xFFFFFF}"
        self._names = [f"{uid}_{w}".encode() for w in range(self.W)]
        # error side-channel per worker: survives a full data ring
        self._err_names = [f"{uid}_{w}e".encode() for w in range(self.W)]
        self._rings = []
        self._err_rings = []
        self._created = []  # exact (ring, name) pairs for cleanup
        self._procs = []
        self._closed = False
        try:
            for n, en in zip(self._names, self._err_names):
                r = self._lib.rb_create(n, ring_bytes)
                if not r:
                    raise RuntimeError(f"shm ring create failed ({n!r})")
                self._rings.append(r)
                self._created.append((r, n))
                er = self._lib.rb_create(en, 1 << 20)
                if not er:
                    raise RuntimeError(f"shm ring create failed ({en!r})")
                self._err_rings.append(er)
                self._created.append((er, en))
            import warnings
            for w in range(self.W):
                with warnings.catch_warnings():
                    # jax warns on fork because ITS threads could hold
                    # locks; our children never enter jax (numpy-only
                    # decode), the same posture as the reference's forked
                    # workers
                    warnings.simplefilter("ignore", RuntimeWarning)
                    pid = os.fork()
                if pid == 0:  # child: jax-free decode loop
                    code = 1
                    try:
                        self._worker_main(w)
                        code = 0
                    finally:
                        os._exit(code)
                self._procs.append(pid)
        except BaseException:
            self.close()
            raise

    # -- worker side -----------------------------------------------------
    def _worker_main(self, w: int):
        lib = self._lib
        ring = lib.rb_attach(self._names[w])
        err_ring = lib.rb_attach(self._err_names[w])
        if not ring or not err_ring:
            os._exit(2)  # parent's liveness poll reports the death
        try:
            ds = self.loader.dataset
            from .dataloader import _WorkerInfo, _worker_tls
            _worker_tls.info = _WorkerInfo(w, self.W, ds)
            if self.loader.worker_init_fn is not None:
                self.loader.worker_init_fn(w)
            for i in range(w, len(self.batches), self.W):
                samples = [ds[j] for j in self.batches[i]]
                payload = pickle.dumps((i, _np_collate(samples)),
                                       protocol=4)
                rc = lib.rb_push(ring, payload, len(payload), -1)
                if rc == -2:
                    raise RuntimeError(
                        f"batch {i} ({len(payload)} bytes) exceeds the shm "
                        f"ring capacity; set PADDLE2_TPU_SHM_RING_BYTES "
                        f"higher or use_shared_memory=False")
        except BaseException as e:
            try:  # keep the original exception type when picklable
                blob = pickle.dumps((e, traceback.format_exc()),
                                    protocol=4)
            except Exception:
                blob = pickle.dumps((None, traceback.format_exc()),
                                    protocol=4)
            # the DATA ring may be full; errors ride their own channel
            lib.rb_push(err_ring, blob, len(blob), 2000)
        finally:
            lib.rb_close_producer(ring)
            lib.rb_close_producer(err_ring)
            lib.rb_detach(ring)
            lib.rb_detach(err_ring)

    # -- consumer side ---------------------------------------------------
    def __iter__(self):
        return self

    def _raise_worker_error(self, w: int, fallback: str):
        n = self._lib.rb_next_len(self._err_rings[w], 0)
        if n >= 0:
            buf = ctypes.create_string_buffer(int(n))
            self._lib.rb_pop(self._err_rings[w], buf, int(n))
            exc, tb = pickle.loads(buf.raw)
            self.close()
            if exc is not None:
                raise exc
            raise RuntimeError(f"DataLoader worker failed:\n{tb}")
        self.close()
        raise RuntimeError(fallback)

    def _worker_dead(self, w: int) -> bool:
        pid = self._procs[w]
        try:
            done, _ = os.waitpid(pid, os.WNOHANG)
            return done == pid
        except ChildProcessError:
            return True

    def __next__(self):
        if self.next_emit >= len(self.batches):
            self.close()
            raise StopIteration
        w = self.next_emit % self.W
        waited = 0
        while True:  # 1s slices: detect killed/odd-death workers
            n = self._lib.rb_next_len(self._rings[w], 1000)
            if n >= 0 or n == -3:
                break
            waited += 1000
            if self._worker_dead(w) and \
                    self._lib.rb_next_len(self._rings[w], 0) < 0:
                self._raise_worker_error(
                    w, f"worker {w} (pid {self._procs[w]}) died without "
                       f"reporting an error (OOM-killed?)")
            if 0 <= self._timeout_ms <= waited:
                self._raise_worker_error(
                    w, f"shm DataLoader timed out after "
                       f"{waited / 1000:.0f}s waiting on worker {w}")
        if n == -3:
            self._raise_worker_error(
                w, f"worker {w} exited early (batch "
                   f"{self.next_emit} missing)")
        buf = ctypes.create_string_buffer(int(n))
        self._lib.rb_pop(self._rings[w], buf, int(n))
        tag, payload = pickle.loads(buf.raw)
        assert tag == self.next_emit, (tag, self.next_emit)
        self.next_emit += 1
        return _to_tensor_tree(payload)

    def close(self):
        if self._closed:
            return
        self._closed = True
        for pid in self._procs:
            try:
                os.kill(pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        for pid in self._procs:
            try:
                os.waitpid(pid, 0)
            except (ChildProcessError, OSError):
                pass
        for r, n in self._created:
            self._lib.rb_detach(r)
            self._lib.rb_unlink(n)
        self._created = []
        self._rings = []
        self._err_rings = []

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
