"""Multiprocess DataLoader path over the native shared-memory ring
(reference dataloader_iter.py:368 _DataLoaderIterMultiProcess + its shm
LoDTensor transport; here the data plane is the C++ SPSC ring in
io/native/shm_ring.cpp and workers are forked processes, so Python decode
work escapes the GIL — the exact limitation of the thread prefetcher).

Workers are jax-free: they decode+collate to NUMPY trees, pickle into
their ring, and the main process materializes Tensors. Batch order is
deterministic: worker w owns batches w, w+W, ... and the consumer drains
rings round-robin.

Self-healing: a worker that DIES (OOM-kill, segfault, chaos
``worker_crash``) is respawned up to ``loader.worker_restarts`` times
per worker. The dead worker's completed-but-undelivered batches are
drained out of its ring first (the ring commits records atomically — a
kill mid-push leaves only whole records), its rings are recreated, and
the replacement worker resubmits every in-flight batch — the epoch
still yields every batch exactly once, in order. Only when the restart
budget is exhausted does the iterator escalate with
:class:`WorkerCrashError`, a ``TransientStepError`` subclass so
ReliableStep treats it as a retryable fault. A worker that raises a
Python EXCEPTION (a dataset bug — deterministic, a respawn would just
re-raise) still propagates immediately.
"""

from __future__ import annotations

import ctypes
import os
import pickle
import signal
import time
import traceback
from typing import Any, Dict, List

import numpy as np

_DEF_RING_BYTES = 64 << 20  # per worker

# how long close() waits for SIGTERMed workers before SIGKILL: a hung
# worker (stuck decode, wedged FS) must never block interpreter exit
_JOIN_TIMEOUT_S = 2.0


def _np_collate(batch):
    """default_collate_fn shape contract, numpy leaves only."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: _np_collate([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return type(sample)(_np_collate(list(items))
                            for items in zip(*batch))
    if hasattr(sample, "numpy"):  # Tensor-like snuck into a worker
        return np.stack([np.asarray(s.numpy()) for s in batch])
    raise TypeError(f"cannot collate {type(sample)}")


def _to_tensor_tree(obj):
    from ..framework.tensor import Tensor
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, dict):
        return {k: _to_tensor_tree(v) for k, v in obj.items()}
    if isinstance(obj, (tuple, list)):
        return type(obj)(_to_tensor_tree(v) for v in obj)
    return obj


class ShmProcessIter:
    """Ordered multiprocess iterator (one ring per worker)."""

    def __init__(self, loader, batches: List[List[int]],
                 ring_bytes: int = 0):
        from .native import load_shm_ring
        self._lib = load_shm_ring()
        self.loader = loader
        self.batches = batches
        self.W = loader.num_workers
        self.next_emit = 0
        # timeout=0 means wait forever (reference DataLoader semantics)
        t = float(getattr(loader, "timeout", 0) or 0)
        self._timeout_ms = int(t * 1000) if t > 0 else -1
        self._ring_bytes = ring_bytes or int(os.environ.get(
            "PADDLE2_TPU_SHM_RING_BYTES", _DEF_RING_BYTES))
        uid = f"/p2t_{os.getpid()}_{id(self) & 0xFFFFFF}"
        self._names = [f"{uid}_{w}".encode() for w in range(self.W)]
        # error side-channel per worker: survives a full data ring
        self._err_names = [f"{uid}_{w}e".encode() for w in range(self.W)]
        self._rings: List[Any] = [None] * self.W
        self._err_rings: List[Any] = [None] * self.W
        self._created = []  # exact (ring, name) pairs for cleanup
        self._procs: List[int] = [0] * self.W
        self._closed = False
        # self-healing state: drained-but-unemitted payloads from a dead
        # worker's ring, and the per-worker restart ledger
        self._stash: Dict[int, Any] = {}
        self._skip: List[frozenset] = [frozenset()] * self.W
        self._restarts = [0] * self.W
        self._restart_budget = int(getattr(loader, "worker_restarts", 2))
        try:
            for w in range(self.W):
                self._make_rings(w)
                self._procs[w] = self._fork_worker(w)
        except BaseException:
            self.close()
            raise

    def _make_rings(self, w: int) -> None:
        """(Re)create worker w's data + error rings."""
        for slot, names, nbytes in ((self._rings, self._names,
                                     self._ring_bytes),
                                    (self._err_rings, self._err_names,
                                     1 << 20)):
            old = slot[w]
            if old is not None:
                self._created.remove((old, names[w]))
                self._lib.rb_detach(old)
                self._lib.rb_unlink(names[w])
            r = self._lib.rb_create(names[w], nbytes)
            if not r:
                raise RuntimeError(f"shm ring create failed "
                                   f"({names[w]!r})")
            slot[w] = r
            self._created.append((r, names[w]))

    def _fork_worker(self, w: int) -> int:
        import warnings
        with warnings.catch_warnings():
            # jax warns on fork because ITS threads could hold locks; our
            # children never enter jax (numpy-only decode), the same
            # posture as the reference's forked workers
            warnings.simplefilter("ignore", RuntimeWarning)
            pid = os.fork()
        if pid == 0:  # child: jax-free decode loop
            code = 1
            try:
                self._worker_main(w)
                code = 0
            finally:
                os._exit(code)
        return pid

    # -- worker side -----------------------------------------------------
    def _worker_main(self, w: int):
        lib = self._lib
        ring = lib.rb_attach(self._names[w])
        err_ring = lib.rb_attach(self._err_names[w])
        if not ring or not err_ring:
            os._exit(2)  # parent's liveness poll reports the death
        try:
            ds = self.loader.dataset
            from .dataloader import _WorkerInfo, _worker_tls
            _worker_tls.info = _WorkerInfo(w, self.W, ds)
            if self.loader.worker_init_fn is not None:
                self.loader.worker_init_fn(w)
            # a respawned worker resubmits only the in-flight batches:
            # tags already emitted (< resume floor) or drained into the
            # parent's stash (skip set) are not decoded again
            start = self.next_emit
            skip = self._skip[w]
            for i in range(w, len(self.batches), self.W):
                if i < start or i in skip:
                    continue
                samples = [ds[j] for j in self.batches[i]]
                payload = pickle.dumps((i, _np_collate(samples)),
                                       protocol=4)
                rc = lib.rb_push(ring, payload, len(payload), -1)
                if rc == -2:
                    raise RuntimeError(
                        f"batch {i} ({len(payload)} bytes) exceeds the shm "
                        f"ring capacity; set PADDLE2_TPU_SHM_RING_BYTES "
                        f"higher or use_shared_memory=False")
        except BaseException as e:
            try:  # keep the original exception type when picklable
                blob = pickle.dumps((e, traceback.format_exc()),
                                    protocol=4)
            except Exception:
                blob = pickle.dumps((None, traceback.format_exc()),
                                    protocol=4)
            # the DATA ring may be full; errors ride their own channel
            lib.rb_push(err_ring, blob, len(blob), 2000)
        finally:
            lib.rb_close_producer(ring)
            lib.rb_close_producer(err_ring)
            lib.rb_detach(ring)
            lib.rb_detach(err_ring)

    # -- consumer side ---------------------------------------------------
    def __iter__(self):
        return self

    def _pop_error(self, w: int):
        """(exc, tb) reported by worker w, or None."""
        n = self._lib.rb_next_len(self._err_rings[w], 0)
        if n < 0:
            return None
        buf = ctypes.create_string_buffer(int(n))
        self._lib.rb_pop(self._err_rings[w], buf, int(n))
        return pickle.loads(buf.raw)

    def _raise_worker_error(self, w: int, fallback: str):
        reported = self._pop_error(w)
        self.close()
        if reported is not None:
            exc, tb = reported
            if exc is not None:
                raise exc
            raise RuntimeError(f"DataLoader worker failed:\n{tb}")
        raise RuntimeError(fallback)

    def _worker_dead(self, w: int) -> bool:
        pid = self._procs[w]
        try:
            done, _ = os.waitpid(pid, os.WNOHANG)
            return done == pid
        except ChildProcessError:
            return True

    # -- self-healing ----------------------------------------------------
    def _drain_ring(self, w: int) -> None:
        """Salvage completed batches out of a dead worker's ring. The
        ring publishes a record only after its full payload is copied
        (release-store on tail), so everything readable is whole."""
        while True:
            n = self._lib.rb_next_len(self._rings[w], 0)
            if n < 0:
                return
            buf = ctypes.create_string_buffer(int(n))
            self._lib.rb_pop(self._rings[w], buf, int(n))
            tag, payload = pickle.loads(buf.raw)
            self._stash[tag] = payload

    def _escalate(self, w: int, detail: str):
        from ..distributed.fault_tolerance import flight_recorder
        from ..distributed.fault_tolerance.reliable import WorkerCrashError
        flight_recorder.record("worker_crash_escalate", worker=w,
                               restarts=self._restarts[w],
                               next_batch=self.next_emit)
        flight_recorder.dump(f"worker_crash:worker{w}")
        self.close()
        raise WorkerCrashError(detail + flight_recorder.dump_hint())

    def _respawn(self, w: int) -> None:
        """Replace dead worker w: drain its ring, rebuild the rings
        (a killed producer never set `closed`; fresh rings keep the
        -3 'producer done' signal trustworthy), and fork a replacement
        that resubmits the in-flight batches."""
        self._restarts[w] += 1
        from ..distributed.fault_tolerance import flight_recorder
        from ..observability import metrics as _metrics
        _metrics.inc("data_worker_respawns_total")
        flight_recorder.record("worker_respawn", worker=w,
                               restarts=self._restarts[w],
                               salvaged=len(self._stash),
                               next_batch=self.next_emit)
        self._drain_ring(w)
        self._make_rings(w)
        self._skip[w] = frozenset(self._stash)
        self._procs[w] = self._fork_worker(w)

    def __next__(self):
        # metrics: blocking on the ring is INPUT WAIT in the step-time
        # breakdown (one attribute load when the plane is off)
        from ..observability import metrics as _metrics
        pl = _metrics._ACTIVE
        if pl is None:
            return self._next_impl()
        pl.phase_enter("input")
        try:
            return self._next_impl()
        finally:
            pl.phase_exit()

    def _next_impl(self):
        if self.next_emit >= len(self.batches):
            self.close()
            self._note_epoch_end()
            raise StopIteration
        from ..distributed.fault_tolerance import chaos, flight_recorder
        chaos.maybe_crash_worker(self._procs)
        if self.next_emit in self._stash:  # salvaged from a dead ring
            payload = self._stash.pop(self.next_emit)
            flight_recorder.record("dataloader_batch",
                                   batch=self.next_emit, salvaged=True)
            self.next_emit += 1
            return _to_tensor_tree(payload)
        w = self.next_emit % self.W
        waited = 0
        while True:  # 1s slices: detect killed/odd-death workers
            n = self._lib.rb_next_len(self._rings[w], 1000)
            if n >= 0 or n == -3:
                break
            waited += 1000
            if self._worker_dead(w) and \
                    self._lib.rb_next_len(self._rings[w], 0) < 0:
                reported = self._pop_error(w)
                if reported is not None:
                    # a Python exception is a DATASET bug: deterministic,
                    # a respawn would re-raise it — propagate
                    exc, tb = reported
                    self.close()
                    if exc is not None:
                        raise exc
                    raise RuntimeError(f"DataLoader worker failed:\n{tb}")
                if self._restarts[w] < self._restart_budget:
                    self._respawn(w)
                    if self.next_emit in self._stash:
                        payload = self._stash.pop(self.next_emit)
                        flight_recorder.record("dataloader_batch",
                                               batch=self.next_emit,
                                               salvaged=True)
                        self.next_emit += 1
                        return _to_tensor_tree(payload)
                    waited = 0  # fresh worker gets a fresh timeout clock
                    continue
                self._escalate(
                    w, f"DataLoader worker {w} died without reporting an "
                       f"error (OOM-killed?) and exhausted its restart "
                       f"budget ({self._restart_budget}); escalating to "
                       f"the step-level retry loop")
            if 0 <= self._timeout_ms <= waited:
                self._raise_worker_error(
                    w, f"shm DataLoader timed out after "
                       f"{waited / 1000:.0f}s waiting on worker {w}")
        if n == -3:
            self._raise_worker_error(
                w, f"worker {w} exited early (batch "
                   f"{self.next_emit} missing)")
        buf = ctypes.create_string_buffer(int(n))
        self._lib.rb_pop(self._rings[w], buf, int(n))
        tag, payload = pickle.loads(buf.raw)
        assert tag == self.next_emit, (tag, self.next_emit)
        flight_recorder.record("dataloader_batch", batch=tag, worker=w)
        self.next_emit += 1
        return _to_tensor_tree(payload)

    def _note_epoch_end(self):
        note = getattr(self.loader, "_note_epoch_end", None)
        if note is not None:
            note(self)

    def close(self):
        """Idempotent teardown. Workers get SIGTERM, a bounded join
        (``_JOIN_TIMEOUT_S``), then SIGKILL — a hung or SIGSTOPped
        worker can never block interpreter exit (the old unconditional
        ``waitpid`` could deadlock ``__del__``)."""
        if self._closed:
            return
        self._closed = True
        procs = [p for p in self._procs if p]
        for pid in procs:
            try:
                os.kill(pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        alive = set(procs)
        deadline = time.monotonic() + _JOIN_TIMEOUT_S
        while alive and time.monotonic() < deadline:
            for pid in list(alive):
                try:
                    done, _ = os.waitpid(pid, os.WNOHANG)
                    if done == pid:
                        alive.discard(pid)
                except (ChildProcessError, OSError):
                    alive.discard(pid)
            if alive:
                time.sleep(0.02)
        for pid in alive:  # join timed out: escalate
            try:
                os.kill(pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        for pid in alive:
            try:
                os.waitpid(pid, 0)
            except (ChildProcessError, OSError):
                pass
        for r, n in self._created:
            self._lib.rb_detach(r)
            self._lib.rb_unlink(n)
        self._created = []
        self._rings = []
        self._err_rings = []
        self._procs = []

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
