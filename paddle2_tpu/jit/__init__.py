from .api import (InputSpec, TranslatedLayer, enable_to_static,  # noqa: F401
                  ignore_module, load, not_to_static, save, to_static)
from .functional import TracedProgram  # noqa: F401
from .train_step import TrainStepProgram, train_step  # noqa: F401
