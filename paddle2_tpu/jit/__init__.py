from .api import (InputSpec, TranslatedLayer, enable_to_static,  # noqa: F401
                  ignore_module, load, not_to_static, save, to_static)
from .functional import TracedProgram  # noqa: F401
from .train_step import TrainStepProgram, train_step  # noqa: F401


_SOT_LOG = {"code_level": 0, "verbosity": 0}


def set_code_level(level=100):
    """jit/sot set_code_level (reference jit/__init__.py): controls how
    much generated-code logging SOT emits. The graph-break tracer logs
    through the standard logger; the level is recorded and applied."""
    import logging
    _SOT_LOG["code_level"] = int(level)
    logging.getLogger("paddle2_tpu.jit").setLevel(
        logging.DEBUG if level > 0 else logging.WARNING)


def set_verbosity(level=0, also_to_stderr=False):
    """jit/sot set_verbosity parity."""
    import logging
    _SOT_LOG["verbosity"] = int(level)
    lg = logging.getLogger("paddle2_tpu.jit")
    lg.setLevel(logging.DEBUG if level > 0 else logging.WARNING)
    if also_to_stderr and not lg.handlers:
        lg.addHandler(logging.StreamHandler())
