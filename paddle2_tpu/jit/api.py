"""paddle.jit API: to_static / not_to_static / save / load
(python/paddle/jit/api.py:196 parity)."""

from __future__ import annotations

import functools
import os
import pickle
from typing import Any, Callable, List, Optional

from ..framework.tensor import Tensor
from .functional import TracedProgram

__all__ = ["to_static", "not_to_static", "ignore_module", "save", "load",
           "enable_to_static", "InputSpec", "TranslatedLayer"]

_to_static_enabled = True


def enable_to_static(flag: bool):
    global _to_static_enabled
    _to_static_enabled = bool(flag)


class InputSpec:
    """Static input signature (paddle.static.InputSpec parity)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def _discover_layers(fn) -> List[Any]:
    """Layers a plain function closes over (its model state).

    Parameters of every discovered Layer become ARGUMENTS of the compiled
    program. Without this, a closed-over model's weights trace in as HLO
    constants — megabytes-to-gigabytes of literals that explode compile
    time and, worse, receive no gradients (the reference's
    partial_program passes params explicitly for the same reason).
    """
    from ..nn import Layer
    found: List[Any] = []
    seen = set()

    def add(obj, depth=0):
        if isinstance(obj, Layer):
            if id(obj) not in seen:
                seen.add(id(obj))
                found.append(obj)
        elif depth < 2 and isinstance(obj, (list, tuple)):
            for o in obj:
                add(o, depth + 1)
        elif depth < 2 and isinstance(obj, dict):
            for o in obj.values():
                add(o, depth + 1)

    self_obj = getattr(fn, "__self__", None)
    if self_obj is not None:
        add(self_obj)
    for cell in getattr(fn, "__closure__", None) or ():
        try:
            add(cell.cell_contents)
        except ValueError:  # pragma: no cover (empty cell)
            pass
    # module-level functions reach their model through globals; only the
    # names the code object references are considered
    code = getattr(fn, "__code__", None)
    globs = getattr(fn, "__globals__", None)
    if code is not None and globs is not None:
        for name in code.co_names:
            if name in globs:
                add(globs[name])
    if isinstance(fn, functools.partial):
        add(list(fn.args))
        add(fn.keywords or {})
        found.extend(l for l in _discover_layers(fn.func)
                     if id(l) not in seen)
    return found


class StaticFunction:
    def __init__(self, function: Callable, layer=None, input_spec=None,
                 build_strategy=None, full_graph=True):
        self._function = function
        self._layer = layer
        self._input_spec = input_spec
        layers = [layer] if layer is not None else _discover_layers(function)
        self._program = TracedProgram(function, layers)
        self._rediscover = layer is None
        functools.update_wrapper(self, function,
                                 assigned=("__name__", "__doc__"), updated=())

    def __get__(self, instance, owner):
        if instance is None:
            return self
        # method access: bind the layer instance
        bound = StaticFunction(self._function.__get__(instance, owner),
                               layer=instance, input_spec=self._input_spec)
        setattr(instance, self._function.__name__, bound)
        return bound

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            return self._function(*args, **kwargs)
        if self._rediscover:
            # decoration can precede model construction (`@to_static` above
            # `model = ...`): re-resolve globals/closure at call time so a
            # late-bound model's params still become program arguments
            layers = _discover_layers(self._function)
            if [id(l) for l in layers] != [id(l)
                                           for l in self._program.layers]:
                self._program = TracedProgram(self._function, layers)
        return self._program(*args, **kwargs)

    @property
    def program_cache_size(self):
        return self._program.program_cache_size

    def concrete_program(self):
        return self._program


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """Compile a function or Layer to a single XLA executable
    (python/paddle/jit/api.py:196 parity; the SOT/AST front-end is replaced
    by direct JAX tracing — see jit/functional.py)."""

    def decorate(obj):
        from ..nn import Layer
        if isinstance(obj, Layer):
            orig_forward = obj.forward
            program = TracedProgram(orig_forward, [obj])
            obj._traced_program = program
            obj.forward = program  # Layer.__call__ routes through the program
            return obj
        # plain function or unbound method
        layer = getattr(obj, "__self__", None)
        from ..nn import Layer as _L
        layer = layer if isinstance(layer, _L) else None
        return StaticFunction(obj, layer=layer, input_spec=input_spec,
                              build_strategy=build_strategy)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(func):
    func._not_to_static = True
    return func


def ignore_module(modules: List[Any]):
    pass


class TranslatedLayer:
    """Loaded inference artifact (jit.load result)."""

    def __init__(self, state_dict, config, layer_factory=None):
        self._state_dict = state_dict
        self._config = config

    def state_dict(self):
        return self._state_dict

    def __call__(self, *args):
        raise RuntimeError(
            "TranslatedLayer from jit.load holds weights + config only; "
            "rebuild the architecture and use set_state_dict (StableHLO "
            "export lands with the inference milestone)")


def save(layer, path, input_spec=None, **configs):
    """jit.save: persist weights + spec. Weights as numpy pickle; a full
    StableHLO export (jax.export) is the inference-engine milestone."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    from ..nn import Layer
    payload = {"config": {"input_spec": [repr(s) for s in (input_spec or [])]}}
    if isinstance(layer, Layer):
        payload["state_dict"] = {k: v.numpy()
                                 for k, v in layer.state_dict().items()}
    with open(path + ".pdparams", "wb") as f:
        pickle.dump(payload, f)


def load(path, **configs) -> TranslatedLayer:
    with open(path + ".pdparams", "rb") as f:
        payload = pickle.load(f)
    return TranslatedLayer(payload.get("state_dict", {}),
                           payload.get("config", {}))
