"""paddle.jit API: to_static / not_to_static / save / load
(python/paddle/jit/api.py:196 parity)."""

from __future__ import annotations

import functools
import os
import pickle
from typing import Any, Callable, List, Optional

from ..framework.tensor import Tensor
from .functional import TracedProgram

__all__ = ["to_static", "not_to_static", "ignore_module", "save", "load",
           "enable_to_static", "InputSpec", "TranslatedLayer"]

_to_static_enabled = True


def enable_to_static(flag: bool):
    global _to_static_enabled
    _to_static_enabled = bool(flag)


class InputSpec:
    """Static input signature (paddle.static.InputSpec parity)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


class StaticFunction:
    def __init__(self, function: Callable, layer=None, input_spec=None,
                 build_strategy=None, full_graph=True):
        self._function = function
        self._layer = layer
        self._input_spec = input_spec
        layers = [layer] if layer is not None else []
        self._program = TracedProgram(function, layers)
        functools.update_wrapper(self, function,
                                 assigned=("__name__", "__doc__"), updated=())

    def __get__(self, instance, owner):
        if instance is None:
            return self
        # method access: bind the layer instance
        bound = StaticFunction(self._function.__get__(instance, owner),
                               layer=instance, input_spec=self._input_spec)
        setattr(instance, self._function.__name__, bound)
        return bound

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            return self._function(*args, **kwargs)
        return self._program(*args, **kwargs)

    @property
    def program_cache_size(self):
        return self._program.program_cache_size

    def concrete_program(self):
        return self._program


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """Compile a function or Layer to a single XLA executable
    (python/paddle/jit/api.py:196 parity; the SOT/AST front-end is replaced
    by direct JAX tracing — see jit/functional.py)."""

    def decorate(obj):
        from ..nn import Layer
        if isinstance(obj, Layer):
            orig_forward = obj.forward
            program = TracedProgram(orig_forward, [obj])
            obj._traced_program = program
            obj.forward = program  # Layer.__call__ routes through the program
            return obj
        # plain function or unbound method
        layer = getattr(obj, "__self__", None)
        from ..nn import Layer as _L
        layer = layer if isinstance(layer, _L) else None
        return StaticFunction(obj, layer=layer, input_spec=input_spec,
                              build_strategy=build_strategy)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(func):
    func._not_to_static = True
    return func


def ignore_module(modules: List[Any]):
    pass


class TranslatedLayer:
    """Loaded inference artifact (jit.load result)."""

    def __init__(self, state_dict, config, layer_factory=None):
        self._state_dict = state_dict
        self._config = config

    def state_dict(self):
        return self._state_dict

    def __call__(self, *args):
        raise RuntimeError(
            "TranslatedLayer from jit.load holds weights + config only; "
            "rebuild the architecture and use set_state_dict (StableHLO "
            "export lands with the inference milestone)")


def save(layer, path, input_spec=None, **configs):
    """jit.save: persist weights + spec. Weights as numpy pickle; a full
    StableHLO export (jax.export) is the inference-engine milestone."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    from ..nn import Layer
    payload = {"config": {"input_spec": [repr(s) for s in (input_spec or [])]}}
    if isinstance(layer, Layer):
        payload["state_dict"] = {k: v.numpy()
                                 for k, v in layer.state_dict().items()}
    with open(path + ".pdparams", "wb") as f:
        pickle.dump(payload, f)


def load(path, **configs) -> TranslatedLayer:
    with open(path + ".pdparams", "rb") as f:
        payload = pickle.load(f)
    return TranslatedLayer(payload.get("state_dict", {}),
                           payload.get("config", {}))
