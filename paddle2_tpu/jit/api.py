"""paddle.jit API: to_static / not_to_static / save / load
(python/paddle/jit/api.py:196 parity)."""

from __future__ import annotations

import functools
import os
import pickle

import numpy as np
from typing import Any, Callable, List, Optional

from ..framework.tensor import Tensor
from .functional import TracedProgram

__all__ = ["to_static", "not_to_static", "ignore_module", "save", "load",
           "enable_to_static", "InputSpec", "TranslatedLayer"]

_to_static_enabled = True


def enable_to_static(flag: bool):
    global _to_static_enabled
    _to_static_enabled = bool(flag)


class InputSpec:
    """Static input signature (paddle.static.InputSpec parity)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def _discover_layers(fn) -> List[Any]:
    """Layers a plain function closes over (its model state).

    Parameters of every discovered Layer become ARGUMENTS of the compiled
    program. Without this, a closed-over model's weights trace in as HLO
    constants — megabytes-to-gigabytes of literals that explode compile
    time and, worse, receive no gradients (the reference's
    partial_program passes params explicitly for the same reason).
    """
    from ..nn import Layer
    found: List[Any] = []
    seen = set()

    def add(obj, depth=0):
        if isinstance(obj, Layer):
            if id(obj) not in seen:
                seen.add(id(obj))
                found.append(obj)
        elif depth < 2 and isinstance(obj, (list, tuple)):
            for o in obj:
                add(o, depth + 1)
        elif depth < 2 and isinstance(obj, dict):
            for o in obj.values():
                add(o, depth + 1)

    self_obj = getattr(fn, "__self__", None)
    if self_obj is not None:
        add(self_obj)
    for cell in getattr(fn, "__closure__", None) or ():
        try:
            add(cell.cell_contents)
        except ValueError:  # pragma: no cover (empty cell)
            pass
    # module-level functions reach their model through globals; only the
    # names the code object references are considered
    code = getattr(fn, "__code__", None)
    globs = getattr(fn, "__globals__", None)
    if code is not None and globs is not None:
        for name in code.co_names:
            if name in globs:
                add(globs[name])
    if isinstance(fn, functools.partial):
        add(list(fn.args))
        add(fn.keywords or {})
        found.extend(l for l in _discover_layers(fn.func)
                     if id(l) not in seen)
    return found


class StaticFunction:
    def __init__(self, function: Callable, layer=None, input_spec=None,
                 build_strategy=None, full_graph=True):
        self._function = function
        self._layer = layer
        self._input_spec = input_spec
        layers = [layer] if layer is not None else _discover_layers(function)
        self._program = TracedProgram(function, layers)
        self._rediscover = layer is None
        functools.update_wrapper(self, function,
                                 assigned=("__name__", "__doc__"), updated=())

    def __get__(self, instance, owner):
        if instance is None:
            return self
        # method access: bind the layer instance
        bound = StaticFunction(self._function.__get__(instance, owner),
                               layer=instance, input_spec=self._input_spec)
        setattr(instance, self._function.__name__, bound)
        return bound

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            return self._function(*args, **kwargs)
        if self._rediscover:
            # decoration can precede model construction (`@to_static` above
            # `model = ...`): re-resolve globals/closure at call time so a
            # late-bound model's params still become program arguments
            layers = _discover_layers(self._function)
            if [id(l) for l in layers] != [id(l)
                                           for l in self._program.layers]:
                self._program = TracedProgram(self._function, layers)
        return self._program(*args, **kwargs)

    @property
    def program_cache_size(self):
        return self._program.program_cache_size

    def concrete_program(self):
        return self._program


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """Compile a function or Layer to a single XLA executable
    (python/paddle/jit/api.py:196 parity; the SOT/AST front-end is replaced
    by direct JAX tracing — see jit/functional.py)."""

    def decorate(obj):
        from ..nn import Layer
        if isinstance(obj, Layer):
            orig_forward = obj.forward
            program = TracedProgram(orig_forward, [obj])
            obj._traced_program = program
            obj.forward = program  # Layer.__call__ routes through the program
            return obj
        # plain function or unbound method
        layer = getattr(obj, "__self__", None)
        from ..nn import Layer as _L
        layer = layer if isinstance(layer, _L) else None
        return StaticFunction(obj, layer=layer, input_spec=input_spec,
                              build_strategy=build_strategy)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(func):
    func._not_to_static = True
    return func


def ignore_module(modules: List[Any]):
    pass


class TranslatedLayer:
    """Loaded inference artifact (jit.load result; reference
    TranslatedLayer from paddle.jit.api — a callable program + weights).

    Holds a deserialized StableHLO executable (jax.export) plus the
    weights it consumes; ``__call__`` runs the compiled program. The
    artifact is the TPU-native .pdmodel: a portable, architecture-free
    serialized program (exported for both cpu and tpu)."""

    def __init__(self, state_dict, config, exported=None, treedef=None):
        self._state_dict = state_dict
        self._config = config
        self._exported = exported
        self._treedef = treedef
        self._weights_dev = None  # device copies, materialized on 1st call

    def state_dict(self):
        return self._state_dict

    def eval(self):
        return self

    def __call__(self, *args):
        if self._exported is None:
            raise RuntimeError(
                "this artifact was saved without a program (weights only); "
                "rebuild the architecture and use set_state_dict")
        import jax
        from ..framework.tensor import Tensor
        arg_arrays = [a._data if isinstance(a, Tensor) else jnp_asarray(a)
                      for a in args]
        if self._weights_dev is None:
            self._weights_dev = [jnp_asarray(v)
                                 for v in self._state_dict.values()]
        outs = self._exported.call(self._weights_dev, arg_arrays)
        out_tensors = [Tensor(o, stop_gradient=True) for o in outs]
        import jax.tree_util as tu
        if self._treedef is not None:
            return tu.tree_unflatten(self._treedef, out_tensors)
        return out_tensors[0] if len(out_tensors) == 1 else out_tensors


def jnp_asarray(x):
    import jax.numpy as jnp
    return jnp.asarray(x)


def _export_program(fn_call, input_spec, layers=None):
    """StableHLO-export fn_call(*input_spec) with the layers' weights as
    runtime arguments (portable across cpu/tpu)."""
    import jax
    import jax.numpy as jnp
    from jax import export as jexport
    from ..framework import core
    from ..framework import random as fr
    from ..framework.tensor import Tensor
    from .functional import _collect_state

    layers = layers if layers is not None else [fn_call]
    params, buffers = _collect_state(layers)
    state = params + buffers
    # names mirror _collect_state's order + id-dedup exactly; with
    # multiple discovered layers a layer-index prefix keeps keys unique
    # (two layers may both expose 'fc.weight' — a bare dict would
    # collapse entries and misalign weight_avals); the single-layer case
    # keeps bare names so saved keys match the layer's own state_dict
    p_names, b_names = [], []
    seen = set()
    multi = len(layers) > 1
    for li, l in enumerate(layers):
        pre = f"l{li}." if multi else ""
        for n, p2 in l.named_parameters():
            if id(p2) not in seen:
                seen.add(id(p2))
                p_names.append(pre + n)
        for n, b2 in l.named_buffers():
            if b2 is not None and id(b2) not in seen:
                seen.add(id(b2))
                b_names.append(pre + n)
    names = p_names + b_names
    trainings = [getattr(l, "training", False) for l in layers]
    for l in layers:
        l.eval()
    was_training = any(trainings)
    meta = {}

    def pure_infer(weight_arrays, arg_arrays):
        originals = [t._data for t in state]
        for t, a in zip(state, weight_arrays):
            t._data = a
        try:
            with core.no_grad(), fr.scoped_rng(jax.random.PRNGKey(0)):
                out = fn_call(*[Tensor(a) for a in arg_arrays])
        finally:
            for t, a in zip(state, originals):
                t._data = a
        flat, treedef = jax.tree_util.tree_flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor))
        meta["treedef"] = treedef
        return tuple(o._data if isinstance(o, Tensor) else jnp.asarray(o)
                     for o in flat)

    weight_avals = [jax.ShapeDtypeStruct(tuple(t.shape),
                                         t._data.dtype) for t in state]
    arg_avals = []
    n_dyn = 0
    for s in input_spec:
        if isinstance(s, InputSpec):
            parts = []
            for d in s.shape:
                if d is None or (isinstance(d, int) and d < 0):
                    parts.append(f"_dyn{n_dyn}")  # symbolic batch etc.
                    n_dyn += 1
                else:
                    parts.append(str(int(d)))
            if any(p.startswith("_dyn") for p in parts):
                shape = jexport.symbolic_shape(", ".join(parts))
            else:
                shape = tuple(int(p) for p in parts)
            arg_avals.append(jax.ShapeDtypeStruct(tuple(shape),
                                                  jnp.dtype(s.dtype)))
        elif isinstance(s, Tensor):
            arg_avals.append(jax.ShapeDtypeStruct(tuple(s.shape),
                                                  s._data.dtype))
        else:
            a = jnp.asarray(s)
            arg_avals.append(jax.ShapeDtypeStruct(a.shape, a.dtype))
    try:
        exp = jexport.export(jax.jit(pure_infer),
                             platforms=("cpu", "tpu"))(
            weight_avals, arg_avals)
    except Exception:
        # some kernels only lower for the current backend
        exp = jexport.export(jax.jit(pure_infer))(weight_avals, arg_avals)
    finally:
        for l, tr in zip(layers, trainings):
            if tr:
                l.train()
    weights = {n: np.asarray(t._data) for n, t in zip(names, state)}
    return exp.serialize(), weights, meta["treedef"]


def save(layer, path, input_spec=None, **configs):
    """jit.save (api.py:744 contract): writes path.pdmodel (serialized
    StableHLO program) + path.pdiparams (weights). Without input_spec only
    the weights are written."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    from ..nn import Layer
    fn_call = None
    layers = None
    if isinstance(layer, StaticFunction):
        if layer._layer is not None:
            layer = layer._layer
        else:  # plain function: export it over its discovered layers
            fn_call = layer._function
            layers = list(layer._program.layers)
            layer = None
    if layer is not None:
        fn_call = layer
        layers = [layer]
    if fn_call is None or (not layers and input_spec):
        raise TypeError("jit.save expects a Layer or a to_static function "
                        "that references one")
    config = {"input_spec": [repr(s) for s in (input_spec or [])]}
    if input_spec:
        blob, weights, treedef = _export_program(fn_call, input_spec,
                                                 layers=layers)
        with open(path + ".pdmodel", "wb") as f:
            f.write(blob)
        config["treedef"] = pickle.dumps(treedef)
    else:
        state = {}
        for l in (layers or []):
            state.update(l.state_dict())
        weights = {k: v.numpy() for k, v in state.items()}
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump({"state_dict": weights, "config": config}, f,
                    protocol=4)


def load(path, params_path=None, **configs) -> TranslatedLayer:
    """jit.load: returns a CALLABLE TranslatedLayer executing the exported
    program (api.py:1065 contract). ``params_path`` overrides the
    prefix-derived ``path + '.pdiparams'`` — the hook
    ``inference.Config.set_model(prog_file, params_file)`` uses when
    weights live under a different prefix than the program."""
    with open(params_path or (path + ".pdiparams"), "rb") as f:
        payload = pickle.load(f)
    exported = treedef = None
    model_path = path + ".pdmodel"
    if os.path.exists(model_path):
        from jax import export as jexport
        with open(model_path, "rb") as f:
            exported = jexport.deserialize(f.read())
        td = payload.get("config", {}).get("treedef")
        if td is not None:
            treedef = pickle.loads(td)
    return TranslatedLayer(payload.get("state_dict", {}),
                           payload.get("config", {}), exported, treedef)
