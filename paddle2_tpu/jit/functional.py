"""Functionalization bridge: run imperative Layer code as a pure JAX function.

The TPU-native replacement for the reference's SOT/AST dy2static stack
(python/paddle/jit/sot/ bytecode tracing + PartialProgramLayer running a
captured program via the run_program op, SURVEY.md §3.3). Because every eager
op is already a pure JAX call on `Tensor._data`, capturing the program is just
tracing the same Python code with tracer payloads: parameters/buffers are
temporarily rebound to traced arrays, the function runs once under jit, and
XLA compiles the whole graph. Guards (arg shapes/dtypes, training mode, grad
mode) key the executable cache, mirroring the reference's guard-based compile
cache (sot/symbolic/compile_cache.py).

Backward: the forward executable returns the linearization residuals
(jax.vjp's Partial is a pytree, so it crosses the jit boundary); backward is
a second executable applying them — forward runs ONCE per step, like the
reference's program-pair (forward program + backward program) split.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import core
from ..framework import random as fr
from ..framework.tensor import Tensor
from ..autograd.tape import GradNode

_trace_lock = threading.RLock()
_SENTINEL = "__TENSOR__"


def _collect_state(layers) -> Tuple[List[Tensor], List[Tensor]]:
    params: List[Tensor] = []
    buffers: List[Tensor] = []
    seen = set()
    for layer in layers:
        for _, p in layer.named_parameters():
            if id(p) not in seen:
                seen.add(id(p))
                params.append(p)
        for _, b in layer.named_buffers():
            if b is not None and id(b) not in seen:
                seen.add(id(b))
                buffers.append(b)
    return params, buffers


def _split_tensors(args, kwargs):
    """Flatten (args, kwargs) into (template, tensor_list)."""
    flat, treedef = jax.tree_util.tree_flatten(
        (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
    tensors = [a for a in flat if isinstance(a, Tensor)]
    template = jax.tree_util.tree_unflatten(
        treedef, [_SENTINEL if isinstance(a, Tensor) else a for a in flat])
    return template, tensors


def _fill_template(template, tensors):
    it = iter(tensors)
    return jax.tree_util.tree_map(
        lambda x: next(it) if x == _SENTINEL else x, template)


def _rebound_call(fn, state_tensors, state_arrays, template, arg_arrays,
                  rng_key, buffers):
    """Run imperative `fn` functionally: temporarily rebind the given state
    tensors to (traced) arrays, fill the arg template, call under
    no_grad + scoped RNG. Returns (out, post_buffer_arrays)."""
    originals = [t._data for t in state_tensors]
    for t, a in zip(state_tensors, state_arrays):
        t._data = a
    try:
        with core.no_grad(), fr.scoped_rng(rng_key):
            call_args, call_kwargs = _fill_template(
                template, [Tensor(a) for a in arg_arrays])
            out = fn(*call_args, **call_kwargs)
        post_buffers = tuple(b._data for b in buffers)
    finally:
        for t, a in zip(state_tensors, originals):
            t._data = a
    return out, post_buffers


def _guard_key(template, arg_arrays, layers):
    """Shared compile-cache guard: arg treedef + non-tensor leaves +
    tensor shapes/dtypes + per-layer training mode."""
    return (jax.tree_util.tree_structure(template),
            tuple(str(x) for x in jax.tree_util.tree_leaves(template)
                  if not isinstance(x, (jnp.ndarray,))),
            tuple((tuple(a.shape), str(a.dtype)) for a in arg_arrays),
            tuple(getattr(l, "training", False) for l in layers))


class TracedProgram:
    """One traced function: guarded cache of compiled executables.

    Data-dependent Python control flow (``if t:`` on a traced Tensor)
    graph-breaks instead of failing or dropping the whole function to
    eager: see jit/graph_break.py — per read site a compiled predicate
    program resolves the value, and the full function compiles
    SPECIALIZED per branch outcome, guard-cached on the value."""

    def __init__(self, fn: Callable, layers: Sequence = ()):
        self.fn = fn
        self.layers = list(layers)
        self._compiled: Dict[Any, Any] = {}
        # per-base-guard trie of graph-break predicates:
        # node = {"pred": jitted prefix or None, "children": {value_key:
        # node}}; a leaf chain of resolved values selects the entry
        self._break_trie: Dict[Any, Dict] = {}
        self._warned_fallback = False
        self._warned_pred_cost = False

    # -- public ----------------------------------------------------------
    def __call__(self, *args, **kwargs):
        with _trace_lock:
            return self._call(args, kwargs)

    @property
    def program_cache_size(self):
        return len(self._compiled)

    # -- internals -------------------------------------------------------
    def _call(self, args, kwargs):
        params, buffers = _collect_state(self.layers)
        template, args_t = _split_tensors(args, kwargs)
        # mesh-placed params + single-device args cannot share a jit
        # computation: promote stragglers to mesh-replicated (writes back)
        from ..ops.dispatch import _harmonize_placements
        _harmonize_placements(params + buffers + args_t)
        arg_arrays = [t._data for t in args_t]

        diff_inputs = params + args_t
        needs_grad = (core.is_grad_enabled()
                      and any(not t.stop_gradient
                              and jnp.issubdtype(jnp.result_type(t._data),
                                                 jnp.inexact)
                              for t in diff_inputs))

        base_key = _guard_key(template, arg_arrays, self.layers) + (
            core.is_grad_enabled(),)
        from ..flags import flag_value
        from .graph_break import (GraphBreakCapture, break_scope,
                                  value_key)
        limit = int(flag_value("max_program_cache_size"))

        def _eager_fallback():
            if not self._warned_fallback:
                self._warned_fallback = True
                import warnings
                warnings.warn(
                    f"to_static({getattr(self.fn, '__name__', '?')}): "
                    f"{limit} cached programs — guard misses or "
                    "graph-break branch outcomes exceed the budget; "
                    "falling back to EAGER execution for this function "
                    "(the reference's SOT bail-out). Raise "
                    "FLAGS_max_program_cache_size if the "
                    "specializations are intentional.",
                    RuntimeWarning, stacklevel=4)
            return self.fn(*args, **kwargs)

        param_arrays = [p._data for p in params]
        buffer_arrays = [b._data for b in buffers]
        rng_key = fr.next_key()

        # resolve known graph breaks: walk the predicate trie, running
        # each compiled prefix to get this call's branch values. Once the
        # specialization budget is spent, unknown values go straight to
        # eager WITHOUT growing the trie (else a per-value read leaks a
        # node + pays a predicate dispatch per call forever)
        at_limit = len(self._compiled) >= limit
        node = self._break_trie.setdefault(base_key, {"pred": None,
                                                      "children": {}})
        break_values: List[Any] = []
        while node["pred"] is not None:
            v = np.asarray(node["pred"](param_arrays, buffer_arrays,
                                        arg_arrays, rng_key))
            break_values.append(v)
            child = node["children"].get(value_key(v))
            if child is None:
                if at_limit:
                    return _eager_fallback()
                child = {"pred": None, "children": {}}
                node["children"][value_key(v)] = child
            node = child

        new_preds: List[Any] = []        # predicates built THIS call
        while True:
            key = base_key + (len(break_values),
                              tuple(value_key(v) for v in break_values))
            entry = self._compiled.get(key)
            if entry is None and len(self._compiled) >= limit:
                return _eager_fallback()
            try:
                if entry is None:
                    entry = self._build(template, params, buffers,
                                        len(args_t))
                fwd_jit, fwd_vjp_jit, vjp_apply_jit, meta = entry
                with break_scope(break_values, capture=True):
                    if needs_grad:
                        out_arrays, post_buffers, f_vjp = fwd_vjp_jit(
                            param_arrays, buffer_arrays, arg_arrays,
                            rng_key)
                    else:
                        out_arrays, post_buffers = fwd_jit(
                            param_arrays, buffer_arrays, arg_arrays,
                            rng_key)
                self._compiled[key] = entry
                break
            except GraphBreakCapture:
                # new break at read index len(break_values): build the
                # prefix predicate, resolve this call's value, descend
                if len(self._compiled) + 1 >= limit:
                    return _eager_fallback()
                node["pred"] = self._build_pred(template, params, buffers,
                                                list(break_values))
                new_preds.append((len(break_values), node["pred"]))
                v = np.asarray(node["pred"](param_arrays, buffer_arrays,
                                            arg_arrays, rng_key))
                break_values.append(v)
                node = node["children"].setdefault(
                    value_key(v), {"pred": None, "children": {}})
        if new_preds and not self._warned_pred_cost:
            self._check_pred_cost(
                new_preds, fwd_vjp_jit if needs_grad else fwd_jit,
                param_arrays, buffer_arrays, arg_arrays, rng_key,
                break_values)
        for b, a in zip(buffers, post_buffers):
            b._replace_data(a)

        out_tensors = [Tensor(a, stop_gradient=not needs_grad)
                       for a in out_arrays]
        if needs_grad:
            def run_vjp(cts):
                if not isinstance(cts, tuple):
                    cts = (cts,)
                full = list(cts) + [jnp.zeros(a.shape, a.dtype)
                                    for a in out_arrays[len(cts):]]
                g_params, g_args = vjp_apply_jit(f_vjp, tuple(full))
                grads = list(g_params) + list(g_args)
                return tuple(
                    None if (g is None or g.dtype == jax.dtypes.float0) else g
                    for g in grads)

            avals = [(tuple(a.shape), a.dtype) for a in out_arrays]
            node = GradNode("to_static", run_vjp, diff_inputs, avals,
                            out_is_tuple=True)
            for i, t in enumerate(out_tensors):
                t._grad_node = node
                t._output_index = i
        return jax.tree_util.tree_unflatten(meta["treedef"], out_tensors)

    def _check_pred_cost(self, new_preds, full_jit, param_arrays,
                         buffer_arrays, arg_arrays, rng_key, break_values):
        """One-time guard (r4 verdict #10): a graph-break predicate
        re-executes the function PREFIX on every call — cheap for scalar
        predicates, but a read site after heavy compute silently pays the
        prefix twice (predicate + specialized program). Estimate both
        programs' FLOPs from the lowered HLO and warn once when the
        predicate is a non-trivial fraction of the whole."""
        from .graph_break import break_scope

        def _flops(jfn, scope_values):
            try:
                with break_scope(list(scope_values), capture=False):
                    lowered = jfn.lower(param_arrays, buffer_arrays,
                                        arg_arrays, rng_key)
                ca = lowered.cost_analysis()
                if isinstance(ca, (list, tuple)):
                    ca = ca[0] if ca else {}
                return float((ca or {}).get("flops", 0.0))
            except Exception:
                return None

        # retracing the FULL program just for cost analysis is expensive
        # (it was already traced+compiled this call): only pay it when a
        # predicate is heavy in ABSOLUTE terms (cheap scalar predicates —
        # the common case — never trigger it), and cache the result
        _HEAVY_PRED_FLOPS = 1e7
        full = None
        for read_idx, p in new_preds:
            pf = _flops(p, ())    # pred bakes its own earlier answers
            if pf is None or pf < _HEAVY_PRED_FLOPS:
                continue
            if full is None:
                full = getattr(self, "_full_flops", None)
                if full is None:
                    full = _flops(full_jit, break_values)
                    self._full_flops = full
            if not full:
                return
            frac = pf / full
            if frac >= 0.1:
                self._warned_pred_cost = True
                import warnings
                warnings.warn(
                    f"to_static({getattr(self.fn, '__name__', '?')}): the "
                    f"graph-break predicate for value read #{read_idx} "
                    f"re-executes ~{frac:.0%} of the full program's FLOPs "
                    "on EVERY call (the prefix runs twice: predicate + "
                    "specialized program). Move the value read before the "
                    "heavy compute, or express the branch with "
                    "paddle.where/lax.cond so it stays inside one "
                    "compiled program.", RuntimeWarning, stacklevel=5)
                return

    def _build_pred(self, template, params, buffers, answers):
        """Compile the PREFIX of fn up to value-read #len(answers): runs
        fn with earlier reads answered (baked, guarded by the trie path)
        and returns the newly-read traced value as the program output."""
        fn = self.fn
        state_tensors = params + buffers
        from .graph_break import GraphBreakCapture, break_scope

        def pred(param_arrays, buffer_arrays, arg_arrays, rng_key):
            try:
                with break_scope(answers, capture=True):
                    _rebound_call(
                        fn, state_tensors,
                        list(param_arrays) + list(buffer_arrays),
                        template, arg_arrays, rng_key, buffers)
            except GraphBreakCapture as e:
                return e.tracer
            raise RuntimeError(
                f"graph-break predicate: expected a value read at break "
                f"index {len(answers)} but the function completed — "
                "read order is input-dependent; run this function "
                "eagerly")

        return jax.jit(pred)

    def _build(self, template, params, buffers, n_args):
        # NOTE: branch specialization is NOT baked here — the break_scope
        # installed around the entry's first execution answers the value
        # reads at trace time; the entry is valid only under the values
        # its cache key names
        fn = self.fn
        state_tensors = params + buffers
        meta: Dict[str, Any] = {}

        def pure(param_arrays, buffer_arrays, arg_arrays, rng_key):
            """Run the imperative fn functionally.
            Returns (out_arrays tuple, post_buffer_arrays tuple)."""
            out, post_buffers = _rebound_call(
                fn, state_tensors, list(param_arrays) + list(buffer_arrays),
                template, arg_arrays, rng_key, buffers)
            flat, treedef = jax.tree_util.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, Tensor))
            out_arrays = tuple(o._data if isinstance(o, Tensor)
                               else jnp.asarray(o) for o in flat)
            meta["treedef"] = treedef
            return out_arrays, post_buffers

        fwd_jit = jax.jit(pure)

        @jax.jit
        def fwd_vjp_jit(param_arrays, buffer_arrays, arg_arrays, rng_key):
            # jax.vjp's bound residual function is a pytree (Partial), so it
            # crosses the jit boundary: forward executes ONCE and backward
            # replays only the transpose over saved residuals.
            def f(p_arrays, a_arrays):
                outs, post_b = pure(p_arrays, buffer_arrays, a_arrays,
                                    rng_key)
                return outs, post_b

            outs, f_vjp, post_b = jax.vjp(f, list(param_arrays),
                                          list(arg_arrays), has_aux=True)
            return outs, post_b, f_vjp

        vjp_apply_jit = jax.jit(lambda f_vjp, cts: f_vjp(cts))

        return fwd_jit, fwd_vjp_jit, vjp_apply_jit, meta
