"""Subgraph graph-break capture for jit.to_static (round-3 verdict item 5).

The reference compiles up to a data-dependent branch and resumes after it
via a CPython eval-frame hook (paddle/fluid/pybind/sot/eval_frame.c:41,
python/paddle/jit/sot/symbolic/compile_cache.py). The TPU-native
equivalent here needs no bytecode interception: when a trace reads the
VALUE of a traced Tensor (``if t:``, ``float(t)``, ``t.numpy()``), the
trace aborts and the read site becomes a graph break resolved by

1. a compiled PREDICATE program — the prefix of the function up to the
   read, returning exactly the read value (small, cached); and
2. a per-branch-outcome SPECIALIZED full program — the whole function
   compiled with that concrete value baked in, guard-cached on the value.

Each call then runs predicate(s) to resolve the branch values and
dispatches the matching specialized executable: the matmul-heavy prefix
and suffix both run compiled; only the scalar branch decision crosses to
the host — the same split SOT's guard-cached subgraphs produce, with the
prefix re-executed (cheap for scalar predicates) instead of resumed.
Functions with several reads build a trie of predicates; the path count
is bounded by FLAGS_max_program_cache_size, beyond which the existing
whole-function eager fallback applies.
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional

import numpy as np

_TLS = threading.local()


class GraphBreakCapture(Exception):
    """Raised INSIDE a trace when a value read has no answer yet; carries
    the traced array so the predicate builder can return it."""

    def __init__(self, tracer, what: str):
        super().__init__(what)
        self.tracer = tracer
        self.what = what


class BreakController:
    """Answers value reads during a trace from a list of concrete values
    (one per read site, in execution order); reads past the list abort
    the trace with :class:`GraphBreakCapture`."""

    def __init__(self, answers: List[np.ndarray], capture: bool = True):
        self.answers = list(answers)
        self.i = 0
        self.capture = capture

    def on_value_read(self, arr, what: str):
        if self.i < len(self.answers):
            v = self.answers[self.i]
            self.i += 1
            return v
        if self.capture:
            raise GraphBreakCapture(arr, what)
        raise RuntimeError(
            f"jit.to_static graph break: unexpected extra value read "
            f"({what}) beyond the {len(self.answers)} resolved breaks — "
            "the function's read order is input-dependent; run it "
            "eagerly")


class _Scope:
    def __init__(self, ctl: Optional[BreakController]):
        self.ctl = ctl

    def __enter__(self):
        self.prev = getattr(_TLS, "ctl", None)
        _TLS.ctl = self.ctl
        return self.ctl

    def __exit__(self, *exc):
        _TLS.ctl = self.prev


def break_scope(answers: List[np.ndarray], capture: bool = True) -> _Scope:
    return _Scope(BreakController(answers, capture))


def active_break_controller() -> Optional[BreakController]:
    return getattr(_TLS, "ctl", None)


def value_key(v) -> Any:
    """Hashable guard key for a resolved break value."""
    a = np.asarray(v)
    return (a.shape, str(a.dtype), a.tobytes())
