"""Fused training step: forward + backward + optimizer in ONE executable.

The TPU-native answer to the reference's fused-optimizer + program-cache
stack (paddle/phi/kernels/fusion/fused_adam_kernel.cu multi-tensor update;
paddle/fluid/framework/new_executor/ program caching;
python/paddle/jit/dy2static/partial_program.py:146 forward/backward program
pair). Instead of three executables per step (forward-with-residuals,
vjp-apply, optimizer) the whole training step — loss, gradients, fused
optimizer update — is traced into a single XLA program with parameter and
optimizer-state buffers DONATED, so XLA updates weights and Adam moments in
place (no ~3x-model-size HBM copy per step) and schedules backward and
update together.

Usage::

    step = paddle.jit.train_step(train_fn, optimizer)   # train_fn -> loss
    for batch in loader:
        loss = step(ids, labels)      # one device dispatch, updated params

`train_fn` must return a scalar loss Tensor (or a tuple whose FIRST element
is the scalar loss). Gradient clipping, weight decay, multi-precision
master weights, and LR schedulers all flow through the optimizer's fused
update as in eager `opt.step()`, with ONE semantic difference: params the
loss does not reach get an all-zeros gradient here (value_and_grad), so
weight decay and moment updates still apply to them — the eager path skips
params whose `.grad is None` entirely. Exclude such params from the
optimizer if they must stay untouched.

Unlike the eager path (which only donates optimizer states), this API also
donates the parameter buffers themselves: do not hold `detach()`/view
aliases of parameter arrays across steps while using it.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from ..framework import random as fr
from ..framework.tensor import Tensor
from .functional import (_collect_state, _guard_key, _rebound_call,
                         _split_tensors, _trace_lock)

__all__ = ["train_step", "TrainStepProgram"]


class TrainStepProgram:
    """Guarded cache of compiled fused-train-step executables.

    Optimizer wrappers fuse too (round-3 verdict lifted the restriction):
    - ZeRO ``ShardedOptimizer`` — its whole policy is buffer placement;
      states (and params at stage 3) are placed once after creation and
      the executable's ``out_shardings`` pin them there, so the donated
      single-program path IS the sharded step (GSPMD inserts the gathers
      and reduce-scatters the placements imply).
    - gradient-accumulation ``_ShardOptimizer`` — grads accumulate into a
      donated f32 buffer for k-1 calls (params/states pass through), and
      the k-th call folds the average into the fused update. Two compiled
      variants (accumulate / apply) share the cache entry.
    """

    def __init__(self, fn: Callable, optimizer, layers: Sequence = ()):
        self.fn = fn
        self.optimizer = optimizer
        # unwrap the wrapper chain down to the plain Optimizer that owns
        # update math and state storage
        self._accum_k = 1
        self._accum_avg = True
        self._zero = None
        inner = optimizer
        from ..optimizer.optimizer import Optimizer
        while not isinstance(inner, Optimizer):
            kind = type(inner).__name__
            if kind == "_ShardOptimizer":
                self._accum_k = max(1, int(inner._k))
                self._accum_avg = bool(getattr(inner, "_avg", True))
            elif kind == "ShardedOptimizer":
                self._zero = inner
            else:
                raise TypeError(
                    f"jit.train_step cannot fuse optimizer wrapper "
                    f"{kind}; supported: plain Optimizer, "
                    "dist.shard_optimizer (gradient accumulation), "
                    "sharding.ShardedOptimizer (ZeRO)")
            inner = inner._inner
        self.inner_optimizer = inner
        self.layers = list(layers)
        self._compiled: Dict[Any, Any] = {}
        self._micro_calls = 0
        self._accum_buffers: Optional[list] = None
        self._zero_placed = False

    @property
    def program_cache_size(self):
        return len(self._compiled)

    def __call__(self, *args, **kwargs) -> Tensor:
        with _trace_lock:
            return self._call(args, kwargs)

    # -- internals -------------------------------------------------------
    def _call(self, args, kwargs):
        opt = self.inner_optimizer
        all_params, buffers = _collect_state(self.layers)
        opt_params = [p for p in opt._parameter_list()
                      if p is not None and p.trainable]
        opt_ids = {id(p) for p in opt_params}
        # layer params the optimizer does not own (frozen) ride along as
        # non-differentiated state, like buffers
        frozen = [p for p in all_params if id(p) not in opt_ids]
        for p in opt_params:
            opt._ensure_state(p)
        if self._zero is not None and not self._zero_placed:
            # ZeRO is placement: shard the freshly-created states (and
            # stage-3 params) once; out_shardings keep them there
            self._zero._shard_states()
            self._zero._place_params_and_grads()
            self._zero_placed = True
        states = [opt._states[id(p)] for p in opt_params]

        template, args_t = _split_tensors(args, kwargs)
        # mesh-placed params + single-device args cannot share a jit
        # computation: promote stragglers to mesh-replicated (writes back)
        from ..ops.dispatch import _harmonize_placements
        _harmonize_placements(list(opt_params) + list(frozen)
                              + list(buffers) + list(args_t))
        arg_arrays = [t._data for t in args_t]

        need_clip = tuple(bool(getattr(p, "need_clip", True))
                          for p in opt_params)
        decay_flags = tuple(not getattr(p, "no_weight_decay", False)
                            for p in opt_params)
        from ..flags import flag_value
        donate = bool(flag_value("donate_optimizer_buffers"))

        k = self._accum_k
        self._micro_calls += 1
        apply_update = k == 1 or (self._micro_calls % k == 0)
        if k > 1 and self._accum_buffers is None:
            self._accum_buffers = [
                jnp.zeros(p._data.shape, jnp.float32) for p in opt_params]
            if self._zero is not None:
                # accumulated grads follow the ZeRO GRAD placement: at
                # stage >= 2 grads are sharded even though params are
                # replicated — a param-placed bank would hold a full
                # f32 grad copy per device
                from ..distributed.sharding import _place, _shard_spec
                axis = self._zero._axis
                if self._zero._level >= 2:
                    self._accum_buffers = [
                        _place(a, _shard_spec(a, axis))
                        for a in self._accum_buffers]
                else:
                    self._accum_buffers = [
                        jax.device_put(a, p._data.sharding)
                        if hasattr(p._data, "sharding") else a
                        for a, p in zip(self._accum_buffers, opt_params)]
        accum = self._accum_buffers if k > 1 else []

        key = _guard_key(template, arg_arrays, self.layers) + (
            len(opt_params), need_clip, decay_flags, donate, k,
            apply_update, self._accum_avg)
        entry = self._compiled.get(key)
        if entry is None:
            entry = self._build(template, opt_params, frozen, buffers,
                                need_clip, decay_flags, donate,
                                apply_update, states, accum)
            self._compiled[key] = entry

        if apply_update:
            opt._step_count += 1
        lr = jnp.asarray(opt.get_lr(), jnp.float32)
        step_no = jnp.asarray(max(1, opt._step_count), jnp.int32)
        rng_key = fr.next_key()

        loss, new_params, new_states, post_buffers, new_accum = entry(
            [p._data for p in opt_params],
            states,
            [p._data for p in frozen],
            [b._data for b in buffers],
            arg_arrays, rng_key, lr, step_no, accum)

        for p, a in zip(opt_params, new_params):
            p._replace_data(a)
        for p, s in zip(opt_params, new_states):
            opt._states[id(p)] = s
        for b, a in zip(buffers, post_buffers):
            b._replace_data(a)
        if k > 1:
            self._accum_buffers = list(new_accum)
        return Tensor(loss, stop_gradient=True)

    def _build(self, template, opt_params, frozen, buffers, need_clip,
               decay_flags, donate, apply_update, states, accum):
        fn = self.fn
        k, avg = self._accum_k, self._accum_avg
        update = self.inner_optimizer._build_update(need_clip, decay_flags)
        state_tensors = list(opt_params) + list(frozen) + list(buffers)

        def run_model(param_arrays, frozen_arrays, buffer_arrays,
                      arg_arrays, rng_key):
            out, post_buffers = _rebound_call(
                fn, state_tensors,
                list(param_arrays) + list(frozen_arrays)
                + list(buffer_arrays),
                template, arg_arrays, rng_key, buffers)
            loss = out[0] if isinstance(out, (tuple, list)) else out
            if isinstance(loss, Tensor):
                loss = loss._data
            if loss.ndim != 0 and loss.size == 1:
                loss = loss.reshape(())
            if loss.ndim != 0:
                raise ValueError(
                    "jit.train_step: train_fn must return a scalar loss "
                    f"(got shape {loss.shape})")
            return loss, post_buffers

        def pure_step(param_arrays, states, frozen_arrays, buffer_arrays,
                      arg_arrays, rng_key, lr, step_no, accum):
            def loss_of(p_arrays):
                loss, post_b = run_model(p_arrays, frozen_arrays,
                                         buffer_arrays, arg_arrays, rng_key)
                return loss.astype(jnp.float32), post_b
            (loss, post_buffers), grads = jax.value_and_grad(
                loss_of, has_aux=True)(list(param_arrays))
            if k > 1:
                totals = [a + g.astype(jnp.float32)
                          for a, g in zip(accum, grads)]
                if not apply_update:
                    # accumulation-only microstep: params/states ride
                    # through untouched, grads bank into the f32 buffer
                    return (loss, list(param_arrays), states, post_buffers,
                            totals)
                scale = 1.0 / k if avg else 1.0
                grads = [(t * scale).astype(g.dtype)
                         for t, g in zip(totals, grads)]
                new_accum = [jnp.zeros_like(a) for a in accum]
            else:
                new_accum = []
            new_params, new_states = update(list(param_arrays), grads,
                                            states, lr, step_no)
            return loss, new_params, new_states, post_buffers, new_accum

        out_shardings = None
        if self._zero is not None:
            # pin the ZeRO placements across steps: without this, GSPMD
            # may choose to materialize updated states replicated and the
            # memory savings silently evaporate after step 1
            sh = lambda a: getattr(a, "sharding", None)
            out_shardings = (
                None,
                [sh(p._data) for p in opt_params],
                jax.tree_util.tree_map(sh, states),
                None,
                [sh(a) for a in accum] if accum else [],
            )
        return jax.jit(pure_step,
                       donate_argnums=(0, 1, 3, 8) if donate else (),
                       out_shardings=out_shardings)


def train_step(fn: Callable, optimizer, layers: Optional[Sequence] = None
               ) -> TrainStepProgram:
    """Compile `fn` (returning a scalar loss) plus `optimizer`'s update
    into one donated XLA executable. Layers are discovered from `fn`'s
    closure/globals like `to_static` when not given explicitly.

    Accepts a plain Optimizer, a ZeRO ``ShardedOptimizer``, or a
    gradient-accumulation ``dist.shard_optimizer`` wrapper (in any
    nesting) — wrapper policies are folded INTO the donated executable:
    ZeRO as buffer placements + pinned out_shardings, accumulation as a
    donated f32 grad bank with a k-th-call fused update. Unknown wrapper
    types raise."""
    if layers is None:
        from .api import _discover_layers
        layers = _discover_layers(fn)
    return TrainStepProgram(fn, optimizer, layers)
