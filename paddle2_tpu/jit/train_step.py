"""Fused training step: forward + backward + optimizer in ONE executable.

The TPU-native answer to the reference's fused-optimizer + program-cache
stack (paddle/phi/kernels/fusion/fused_adam_kernel.cu multi-tensor update;
paddle/fluid/framework/new_executor/ program caching;
python/paddle/jit/dy2static/partial_program.py:146 forward/backward program
pair). Instead of three executables per step (forward-with-residuals,
vjp-apply, optimizer) the whole training step — loss, gradients, fused
optimizer update — is traced into a single XLA program with parameter and
optimizer-state buffers DONATED, so XLA updates weights and Adam moments in
place (no ~3x-model-size HBM copy per step) and schedules backward and
update together.

Usage::

    step = paddle.jit.train_step(train_fn, optimizer)   # train_fn -> loss
    for batch in loader:
        loss = step(ids, labels)      # one device dispatch, updated params

`train_fn` must return a scalar loss Tensor (or a tuple whose FIRST element
is the scalar loss). Gradient clipping, weight decay, multi-precision
master weights, and LR schedulers all flow through the optimizer's fused
update as in eager `opt.step()`, with ONE semantic difference: params the
loss does not reach get an all-zeros gradient here (value_and_grad), so
weight decay and moment updates still apply to them — the eager path skips
params whose `.grad is None` entirely. Exclude such params from the
optimizer if they must stay untouched.

Unlike the eager path (which only donates optimizer states), this API also
donates the parameter buffers themselves: do not hold `detach()`/view
aliases of parameter arrays across steps while using it.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from ..framework import random as fr
from ..framework.tensor import Tensor
from ..observability import metrics as _metrics
from .functional import (_collect_state, _guard_key, _rebound_call,
                         _split_tensors, _trace_lock)

__all__ = ["train_step", "TrainStepProgram"]


class TrainStepProgram:
    """Guarded cache of compiled fused-train-step executables.

    Optimizer wrappers fuse too (round-3 verdict lifted the restriction):
    - ZeRO ``ShardedOptimizer`` — its whole policy is buffer placement;
      states (and params at stage 3) are placed once after creation and
      the executable's ``out_shardings`` pin them there, so the donated
      single-program path IS the sharded step (GSPMD inserts the gathers
      and reduce-scatters the placements imply).
    - gradient-accumulation ``_ShardOptimizer`` — grads accumulate into a
      donated f32 buffer for k-1 calls (params/states pass through), and
      the k-th call folds the average into the fused update. Two compiled
      variants (accumulate / apply) share the cache entry.
    """

    def __init__(self, fn: Callable, optimizer, layers: Sequence = (),
                 instrument: bool = False):
        self.fn = fn
        self.optimizer = optimizer
        # instrument=True fuses the reliability plane INTO the donated
        # executable: the program additionally returns ONE packed
        # uint32[4] auxiliary output (non-finite count + SDC
        # fingerprint triple over the gradients the update consumed,
        # numerics.packed_step_sentinel) stashed on `self.last_aux` —
        # never read here, so the clean path pays zero extra host
        # syncs; the ReliableTrainStep wrapper decides when (and
        # whether) to pay the single packed readback
        self._instrument = bool(instrument)
        # optional GradScaler (set by the reliability wrapper): the
        # program scales the loss and unscales the grads IN-PROGRAM
        # (scale rides in as a runtime scalar — no recompile when it
        # moves) and makes the fused update conditional on the packed
        # found_inf lane, so an overflow step is skipped inside the
        # executable exactly like eager GradScaler.step would
        self._scaler = None
        self.last_aux = None
        # compile/MTTR accounting (instrumented path): wall time of the
        # most recent build+first-execution of a NEW cache entry, and
        # whether the persistent XLA cache served it (None = no fresh
        # build happened on the last call / no cache dir configured)
        self.last_build_s: Optional[float] = None
        self.last_build_cache_hit: Optional[bool] = None
        # bench hook: when set, a fresh build also runs XLA
        # cost_analysis on the lowered program (deterministic op
        # accounting — no wall clock) into last_cost_flops, and stashes
        # the entry + abstract (donation-safe) call args so the
        # observability cost model can re-lower it later
        self.collect_cost = False
        self.last_cost_flops: Optional[float] = None
        self.last_cost: Optional[Dict[str, float]] = None
        self.last_entry = None
        self.last_abstract_args = None
        # pure-function fault hook threaded through the builder (the
        # chaos drill's seam into the jitted step): a callable polled
        # once per dispatch returning None or a hashable spec for
        # chaos.apply_compiled_grad_fault. Per-PROGRAM, so an
        # in-process multi-replica drill can corrupt one replica while
        # the env-gated FLAGS_chaos path serves real gangs
        self.grad_fault_hook: Optional[Callable] = None
        # unwrap the wrapper chain down to the plain Optimizer that owns
        # update math and state storage
        self._accum_k = 1
        self._accum_avg = True
        self._zero = None
        inner = optimizer
        from ..optimizer.optimizer import Optimizer
        while not isinstance(inner, Optimizer):
            kind = type(inner).__name__
            if kind == "_ShardOptimizer":
                self._accum_k = max(1, int(inner._k))
                self._accum_avg = bool(getattr(inner, "_avg", True))
            elif kind == "ShardedOptimizer":
                self._zero = inner
            else:
                raise TypeError(
                    f"jit.train_step cannot fuse optimizer wrapper "
                    f"{kind}; supported: plain Optimizer, "
                    "dist.shard_optimizer (gradient accumulation), "
                    "sharding.ShardedOptimizer (ZeRO)")
            inner = inner._inner
        self.inner_optimizer = inner
        self.layers = list(layers)
        self._compiled: Dict[Any, Any] = {}
        self._micro_calls = 0
        self._accum_buffers: Optional[list] = None
        self._zero_placed = False

    @property
    def program_cache_size(self):
        return len(self._compiled)

    def __call__(self, *args, **kwargs) -> Tensor:
        with _trace_lock:
            return self._call(args, kwargs)

    # -- internals -------------------------------------------------------
    def _call(self, args, kwargs):
        opt = self.inner_optimizer
        all_params, buffers = _collect_state(self.layers)
        opt_params = [p for p in opt._parameter_list()
                      if p is not None and p.trainable]
        opt_ids = {id(p) for p in opt_params}
        # layer params the optimizer does not own (frozen) ride along as
        # non-differentiated state, like buffers
        frozen = [p for p in all_params if id(p) not in opt_ids]
        for p in opt_params:
            opt._ensure_state(p)
        if self._zero is not None and not self._zero_placed:
            # ZeRO is placement: shard the freshly-created states (and
            # stage-3 params) once; out_shardings keep them there
            self._zero._shard_states()
            self._zero._place_params_and_grads()
            self._zero_placed = True
        states = [opt._states[id(p)] for p in opt_params]

        template, args_t = _split_tensors(args, kwargs)
        # mesh-placed params + single-device args cannot share a jit
        # computation: promote stragglers to mesh-replicated (writes back)
        from ..ops.dispatch import _harmonize_placements
        _harmonize_placements(list(opt_params) + list(frozen)
                              + list(buffers) + list(args_t))
        arg_arrays = [t._data for t in args_t]

        need_clip = tuple(bool(getattr(p, "need_clip", True))
                          for p in opt_params)
        decay_flags = tuple(not getattr(p, "no_weight_decay", False)
                            for p in opt_params)
        from ..flags import flag_value
        donate = bool(flag_value("donate_optimizer_buffers"))

        k = self._accum_k
        self._micro_calls += 1
        apply_update = k == 1 or (self._micro_calls % k == 0)
        if k > 1 and self._accum_buffers is None:
            self._accum_buffers = [
                jnp.zeros(p._data.shape, jnp.float32) for p in opt_params]
            if self._zero is not None:
                # accumulated grads follow the ZeRO GRAD placement: at
                # stage >= 2 grads are sharded even though params are
                # replicated — a param-placed bank would hold a full
                # f32 grad copy per device
                from ..distributed.sharding import _place, _shard_spec
                axis = self._zero._axis
                if self._zero._level >= 2:
                    self._accum_buffers = [
                        _place(a, _shard_spec(a, axis))
                        for a in self._accum_buffers]
                else:
                    self._accum_buffers = [
                        jax.device_put(a, p._data.sharding)
                        if hasattr(p._data, "sharding") else a
                        for a, p in zip(self._accum_buffers, opt_params)]
        accum = self._accum_buffers if k > 1 else []

        # instrumented extras decided PER DISPATCH: a firing chaos drill
        # compiles a one-off variant (the spec keys the cache); the
        # clean path sees a single module-attribute check
        fault = None
        has_scaler = False
        if self._instrument:
            from ..distributed.fault_tolerance import chaos as _chaos
            has_scaler = (self._scaler is not None
                          and self._scaler.is_enable())
            if self.grad_fault_hook is not None:
                fault = self.grad_fault_hook()
            if fault is None:
                fault = _chaos.compiled_grad_fault(amp=has_scaler)
            if has_scaler and k > 1:
                raise NotImplementedError(
                    "jit.train_step: GradScaler inside an instrumented "
                    "gradient-accumulation step is not supported — "
                    "run AMP without dist.shard_optimizer accumulation")

        # ZeRO-3 prefetch is a schedule shape baked into the trace —
        # toggling it must key a distinct cache entry
        prefetch = (self._zero is not None and self._zero._level >= 3
                    and getattr(self._zero, "_prefetch", False))
        prefetch_depth = (getattr(self._zero, "_prefetch_depth", 1)
                          if prefetch else 0)
        # searched remat policies are resolved BEFORE the key is
        # computed (layers expose the _prepare_remat protocol — the
        # GPT trunk runs the cost-model search against this call's
        # batch shape) and the resolved plan keys the cache: two
        # models differing only in searched policy trace different
        # programs
        remat_tokens = tuple(
            l._prepare_remat(arg_arrays)
            if hasattr(l, "_prepare_remat")
            else getattr(l, "_remat_token", None)
            for l in self.layers)
        key = _guard_key(template, arg_arrays, self.layers) + (
            len(opt_params), need_clip, decay_flags, donate, k,
            apply_update, self._accum_avg, self._instrument,
            has_scaler, fault, prefetch, prefetch_depth, remat_tokens,
            opt._use_fused_step())
        entry = self._compiled.get(key)
        built_now = entry is None
        if built_now:
            entry = self._build(template, opt_params, frozen, buffers,
                                need_clip, decay_flags, donate,
                                apply_update, states, accum,
                                has_scaler, fault)
            self._compiled[key] = entry

        if apply_update:
            opt._step_count += 1
        lr = jnp.asarray(opt.get_lr(), jnp.float32)
        step_no = jnp.asarray(max(1, opt._step_count), jnp.int32)
        rng_key = fr.next_key()

        call_args = (
            [p._data for p in opt_params],
            states,
            [p._data for p in frozen],
            [b._data for b in buffers],
            arg_arrays, rng_key, lr, step_no, accum)
        if self._instrument:
            scale = jnp.asarray(
                self._scaler.get_loss_scaling() if has_scaler else 1.0,
                jnp.float32)
            call_args = call_args + (scale,)

        self.last_build_s = None
        self.last_build_cache_hit = None
        if built_now:
            _metrics.inc("train_step_compiles_total")
            if self.collect_cost:
                from ..observability import cost_model as _cm
                self.last_entry = entry
                self.last_abstract_args = _cm.abstractify(call_args)
                self.last_cost = _cm.program_cost(
                    entry, self.last_abstract_args)
                self.last_cost_flops = (
                    None if not self.last_cost
                    else self.last_cost.get("flops"))
        pl = _metrics._ACTIVE
        if pl is not None:
            pl.phase_enter("compute")
        try:
            if built_now and self._instrument:
                out = self._timed_first_call(entry, call_args)
            else:
                out = entry(*call_args)
        finally:
            if pl is not None:
                pl.phase_exit()

        if self._instrument:
            (loss, aux, new_params, new_states, post_buffers,
             new_accum) = out
            self.last_aux = aux
        else:
            loss, new_params, new_states, post_buffers, new_accum = out

        for p, a in zip(opt_params, new_params):
            p._replace_data(a)
        for p, s in zip(opt_params, new_states):
            opt._states[id(p)] = s
        for b, a in zip(buffers, post_buffers):
            b._replace_data(a)
        if k > 1:
            self._accum_buffers = list(new_accum)
        if pl is not None:
            self._note_step_metrics(pl, args_t, has_scaler)
        return Tensor(loss, stop_gradient=True)

    def _note_step_metrics(self, pl, args_t, has_scaler: bool) -> None:
        """Close this dispatch's step window: tokens/samples inferred
        from the first batch argument (exactly-2-D int16/32/64 ids ->
        B*S tokens; uint8 image batches and >2-D int features must not
        masquerade as token counts, and int8 is EXCLUDED outright —
        2-D int8 first args are quantized payloads, e.g. a serving
        engine's int8 KV blocks, never plausible token ids; serving
        reports its token counts explicitly via step_end(tokens=...)),
        loss scale when AMP is fused, program-cache gauge. Reads
        NOTHING off the device — host-known values only."""
        tokens = samples = None
        if args_t:
            shp = tuple(args_t[0].shape)
            if shp:
                samples = int(shp[0])
            if (len(shp) == 2
                    and str(args_t[0].dtype) in
                    ("int16", "int32", "int64")):
                tokens = int(shp[0]) * int(shp[1])
        scale = (self._scaler.get_loss_scaling()
                 if has_scaler and self._scaler is not None else None)
        pl.set_gauge("train_step_program_cache_size",
                     len(self._compiled))
        pl.step_end(tokens=tokens, samples=samples, loss_scale=scale)

    def _timed_first_call(self, entry, call_args):
        """Execute a FRESHLY BUILT entry blocking, timing compile +
        first step — the span that is pure MTTR on every respawn — and
        detect whether the persistent XLA cache served the executable.
        Hit detection listens to the compiler's own CACHE HIT/MISS log
        records during the call: counting cache FILES would misreport a
        sub-threshold compile (below
        ``jax_persistent_cache_min_compile_time_secs`` nothing is
        written, so "no new file" does NOT mean "served from cache").
        Only the instrumented path pays this (one blocking step per new
        program variant); steady state never re-enters."""
        import logging
        import time as _time
        from ..flags import flag_value
        cache_dir = str(flag_value("compilation_cache_dir") or "")
        tally = {"hit": 0, "miss": 0}

        class _CacheTap(logging.Handler):
            def emit(self, record):
                try:
                    msg = record.getMessage()
                except Exception:
                    return
                # jax logs the miss ALL-CAPS and the hit sentence-case
                # (jax/_src/compiler.py) — match case-insensitively so
                # a style change in either doesn't blind the tap
                low = msg.lower()
                if "persistent compilation cache hit" in low:
                    tally["hit"] += 1
                elif "persistent compilation cache miss" in low:
                    tally["miss"] += 1

        logger = logging.getLogger("jax._src.compiler")
        tap = _CacheTap(level=logging.DEBUG)
        prev_level = logger.level
        if cache_dir:
            logger.addHandler(tap)
            if not logger.isEnabledFor(logging.DEBUG):
                logger.setLevel(logging.DEBUG)
        try:
            t0 = _time.perf_counter()
            out = entry(*call_args)
            jax.block_until_ready(out)
            self.last_build_s = _time.perf_counter() - t0
        finally:
            if cache_dir:
                logger.removeHandler(tap)
                logger.setLevel(prev_level)
        if cache_dir and (tally["hit"] or tally["miss"]):
            self.last_build_cache_hit = tally["miss"] == 0
        # else: compiler logged nothing (cache off for this backend, or
        # log plumbing changed) — leave None, "unknown" must never be
        # reported as a hit
        return out

    def _build(self, template, opt_params, frozen, buffers, need_clip,
               decay_flags, donate, apply_update, states, accum,
               has_scaler=False, fault=None):
        fn = self.fn
        k, avg = self._accum_k, self._accum_avg
        instrument = self._instrument
        update = self.inner_optimizer._build_update(need_clip, decay_flags)
        state_tensors = list(opt_params) + list(frozen) + list(buffers)

        # ZeRO-3: the forward re-gather of sharded params is made
        # EXPLICIT — one all-gather (replicated constraint) per module
        # group — on BOTH schedules, so the model math always sees the
        # same gathered values and eager-vs-prefetch stays bitwise by
        # construction (GSPMD left to regather implicitly may partition
        # the consuming matmuls differently — a rounding-order change).
        # prefetch=False: gathers unchained (gather-all, scheduler
        # free). prefetch=True: barrier-chained so gather i waits only
        # on gather i-depth (never on compute) — the latency-hiding
        # scheduler overlaps it with the previous layer's math while
        # replicated live memory stays bounded to ~depth groups.
        prefetch_groups = None
        prefetch_depth = 0
        if self._zero is not None and self._zero._level >= 3:
            from ..distributed.sharding import layer_param_groups
            prefetch_groups = layer_param_groups(self.layers, opt_params)
            if getattr(self._zero, "_prefetch", False):
                prefetch_depth = self._zero._prefetch_depth

        def run_model(param_arrays, frozen_arrays, buffer_arrays,
                      arg_arrays, rng_key):
            if prefetch_groups is not None:
                from ..distributed.sharding import prefetch_gather
                param_arrays = prefetch_gather(
                    list(param_arrays), prefetch_groups, prefetch_depth)
            out, post_buffers = _rebound_call(
                fn, state_tensors,
                list(param_arrays) + list(frozen_arrays)
                + list(buffer_arrays),
                template, arg_arrays, rng_key, buffers)
            loss = out[0] if isinstance(out, (tuple, list)) else out
            if isinstance(loss, Tensor):
                loss = loss._data
            if loss.ndim != 0 and loss.size == 1:
                loss = loss.reshape(())
            if loss.ndim != 0:
                raise ValueError(
                    "jit.train_step: train_fn must return a scalar loss "
                    f"(got shape {loss.shape})")
            return loss, post_buffers

        def pure_step(param_arrays, states, frozen_arrays, buffer_arrays,
                      arg_arrays, rng_key, lr, step_no, accum):
            def loss_of(p_arrays):
                loss, post_b = run_model(p_arrays, frozen_arrays,
                                         buffer_arrays, arg_arrays, rng_key)
                return loss.astype(jnp.float32), post_b
            (loss, post_buffers), grads = jax.value_and_grad(
                loss_of, has_aux=True)(list(param_arrays))
            if k > 1:
                totals = [a + g.astype(jnp.float32)
                          for a, g in zip(accum, grads)]
                if not apply_update:
                    # accumulation-only microstep: params/states ride
                    # through untouched, grads bank into the f32 buffer
                    return (loss, list(param_arrays), states, post_buffers,
                            totals)
                scale = 1.0 / k if avg else 1.0
                grads = [(t * scale).astype(g.dtype)
                         for t, g in zip(totals, grads)]
                new_accum = [jnp.zeros_like(a) for a in accum]
            else:
                new_accum = []
            new_params, new_states = update(list(param_arrays), grads,
                                            states, lr, step_no)
            return loss, new_params, new_states, post_buffers, new_accum

        def pure_step_instrumented(param_arrays, states, frozen_arrays,
                                   buffer_arrays, arg_arrays, rng_key,
                                   lr, step_no, accum, loss_scale):
            """The reliability plane fused into the donated executable:
            AMP loss scale/unscale, injected chaos faults, the
            non-finite sentinel and the SDC fingerprint all become part
            of THIS program — one dispatch, one packed uint32[4] aux
            output, no extra host round-trips on the clean path."""
            from ..distributed.fault_tolerance import chaos as _chaos
            from ..distributed.fault_tolerance import numerics as _num

            def loss_of(p_arrays):
                loss, post_b = run_model(p_arrays, frozen_arrays,
                                         buffer_arrays, arg_arrays,
                                         rng_key)
                l32 = loss.astype(jnp.float32)
                scaled = l32 * loss_scale if has_scaler else l32
                return scaled, (l32, post_b)
            (_, (loss, post_buffers)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(list(param_arrays))
            if has_scaler:
                # fused unscale-and-check: the eager GradScaler's
                # unscale_ multiply, traced into the step (the sentinel
                # below then sees the UNSCALED f32 values, matching
                # numerics.grads_nonfinite_flag(optimizer, inv))
                inv = 1.0 / loss_scale
                grads = [(g.astype(jnp.float32) * inv).astype(g.dtype)
                         for g in grads]
            # chaos parity: flip_bits:grads / poison_grads land INSIDE
            # the jitted step (pure transform, baked per firing call)
            grads = _chaos.apply_compiled_grad_fault(fault, grads)

            def sentinel(gs):
                aux = _num.packed_step_sentinel(gs)
                return (jnp.zeros((4,), jnp.uint32) if aux is None
                        else aux)

            def guard_loss(l, aux):
                # fold the grad sentinel into the loss so the wrapper's
                # DEFERRED loss check (free — the loss materializes for
                # logging anyway) sees grad corruption with zero extra
                # readbacks. With a scaler the flag means "skip", not
                # "retry": the update below absorbs it instead.
                if has_scaler:
                    return l
                return jnp.where(aux[0] > 0, jnp.full_like(l, jnp.nan),
                                 l)
            if k > 1:
                totals = [a + g.astype(jnp.float32)
                          for a, g in zip(accum, grads)]
                if not apply_update:
                    # microstep: fingerprint THIS microstep's grads (the
                    # contribution being banked — what replicas must
                    # agree on) and bank them untouched
                    aux = sentinel(grads)
                    return (guard_loss(loss, aux), aux,
                            list(param_arrays), states, post_buffers,
                            totals)
                scale = 1.0 / k if avg else 1.0
                grads = [(t * scale).astype(g.dtype)
                         for t, g in zip(totals, grads)]
                new_accum = [jnp.zeros_like(a) for a in accum]
            else:
                new_accum = []
            # sentinel + fingerprint over the grads the update CONSUMES
            # (post-unscale, post-fold) — the same capture point as
            # SDCGuard's wrapped optimizer.step on the eager path
            aux = sentinel(grads)
            new_params, new_states = update(list(param_arrays), grads,
                                            states, lr, step_no)
            if has_scaler:
                # in-program skip: non-finite grads keep params/states
                # bit-identical (eager GradScaler.step's "don't step"),
                # decided on device — the host learns from the packed
                # flag, deferred, without a second readback
                found = aux[0] > 0

                def keep(new, old):
                    return jnp.where(found, old, new)
                new_params = [keep(n, o) for n, o
                              in zip(new_params, list(param_arrays))]
                new_states = jax.tree_util.tree_map(keep, new_states,
                                                    states)
            return (guard_loss(loss, aux), aux, new_params, new_states,
                    post_buffers, new_accum)

        out_shardings = None
        if self._zero is not None:
            # pin the ZeRO placements across steps: without this, GSPMD
            # may choose to materialize updated states replicated and the
            # memory savings silently evaporate after step 1
            sh = lambda a: getattr(a, "sharding", None)
            out_shardings = (
                None,
                [sh(p._data) for p in opt_params],
                jax.tree_util.tree_map(sh, states),
                None,
                [sh(a) for a in accum] if accum else [],
            )
            if instrument:
                out_shardings = (out_shardings[0], None) + out_shardings[1:]
        return jax.jit(pure_step_instrumented if instrument else pure_step,
                       donate_argnums=(0, 1, 3, 8) if donate else (),
                       out_shardings=out_shardings)


def train_step(fn: Callable, optimizer, layers: Optional[Sequence] = None,
               reliability: Any = None):
    """Compile `fn` (returning a scalar loss) plus `optimizer`'s update
    into one donated XLA executable. Layers are discovered from `fn`'s
    closure/globals like `to_static` when not given explicitly.

    Accepts a plain Optimizer, a ZeRO ``ShardedOptimizer``, or a
    gradient-accumulation ``dist.shard_optimizer`` wrapper (in any
    nesting) — wrapper policies are folded INTO the donated executable:
    ZeRO as buffer placements + pinned out_shardings, accumulation as a
    donated f32 grad bank with a k-th-call fused update. Unknown wrapper
    types raise.

    ``reliability`` folds the fault-tolerance plane INTO the compiled
    step and returns a
    :class:`~paddle2_tpu.distributed.fault_tolerance.compiled_step.ReliableTrainStep`
    instead: the non-finite sentinel and the SDC gradient fingerprint
    are computed inside the donated executable (one packed aux output,
    zero extra host readbacks on the clean path), snapshots are
    scheduled donation-safely before each submit, and ReliableStep's
    rewind+replay, flight-recorder events, buddy replication, and
    quarantine self-eviction all apply to the compiled program. Pass
    ``True`` for defaults, a
    :class:`~paddle2_tpu.distributed.fault_tolerance.compiled_step.ReliabilityConfig`,
    or a dict of its kwargs."""
    if layers is None:
        from .api import _discover_layers
        layers = _discover_layers(fn)
    if reliability is None or reliability is False:
        return TrainStepProgram(fn, optimizer, layers)
    from ..distributed.fault_tolerance.compiled_step import (
        ReliabilityConfig, ReliableTrainStep)
    if reliability is True:
        config = ReliabilityConfig()
    elif isinstance(reliability, dict):
        config = ReliabilityConfig(**reliability)
    elif isinstance(reliability, ReliabilityConfig):
        config = reliability
    else:
        raise TypeError(
            "reliability must be True, a ReliabilityConfig, or a dict "
            f"of its kwargs; got {type(reliability).__name__}")
    program = TrainStepProgram(fn, optimizer, layers, instrument=True)
    return ReliableTrainStep(program, config)
