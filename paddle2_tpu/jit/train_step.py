"""Fused training step: forward + backward + optimizer in ONE executable.

The TPU-native answer to the reference's fused-optimizer + program-cache
stack (paddle/phi/kernels/fusion/fused_adam_kernel.cu multi-tensor update;
paddle/fluid/framework/new_executor/ program caching;
python/paddle/jit/dy2static/partial_program.py:146 forward/backward program
pair). Instead of three executables per step (forward-with-residuals,
vjp-apply, optimizer) the whole training step — loss, gradients, fused
optimizer update — is traced into a single XLA program with parameter and
optimizer-state buffers DONATED, so XLA updates weights and Adam moments in
place (no ~3x-model-size HBM copy per step) and schedules backward and
update together.

Usage::

    step = paddle.jit.train_step(train_fn, optimizer)   # train_fn -> loss
    for batch in loader:
        loss = step(ids, labels)      # one device dispatch, updated params

`train_fn` must return a scalar loss Tensor (or a tuple whose FIRST element
is the scalar loss). Gradient clipping, weight decay, multi-precision
master weights, and LR schedulers all flow through the optimizer's fused
update as in eager `opt.step()`, with ONE semantic difference: params the
loss does not reach get an all-zeros gradient here (value_and_grad), so
weight decay and moment updates still apply to them — the eager path skips
params whose `.grad is None` entirely. Exclude such params from the
optimizer if they must stay untouched.

Unlike the eager path (which only donates optimizer states), this API also
donates the parameter buffers themselves: do not hold `detach()`/view
aliases of parameter arrays across steps while using it.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from ..framework import random as fr
from ..framework.tensor import Tensor
from .functional import (_collect_state, _guard_key, _rebound_call,
                         _split_tensors, _trace_lock)

__all__ = ["train_step", "TrainStepProgram"]


class TrainStepProgram:
    """Guarded cache of compiled fused-train-step executables."""

    def __init__(self, fn: Callable, optimizer, layers: Sequence = ()):
        self.fn = fn
        self.optimizer = optimizer
        self.layers = list(layers)
        self._compiled: Dict[Any, Any] = {}

    @property
    def program_cache_size(self):
        return len(self._compiled)

    def __call__(self, *args, **kwargs) -> Tensor:
        with _trace_lock:
            return self._call(args, kwargs)

    # -- internals -------------------------------------------------------
    def _call(self, args, kwargs):
        opt = self.optimizer
        all_params, buffers = _collect_state(self.layers)
        opt_params = [p for p in opt._parameter_list()
                      if p is not None and p.trainable]
        opt_ids = {id(p) for p in opt_params}
        # layer params the optimizer does not own (frozen) ride along as
        # non-differentiated state, like buffers
        frozen = [p for p in all_params if id(p) not in opt_ids]
        for p in opt_params:
            opt._ensure_state(p)
        states = [opt._states[id(p)] for p in opt_params]

        template, args_t = _split_tensors(args, kwargs)
        # mesh-placed params + single-device args cannot share a jit
        # computation: promote stragglers to mesh-replicated (writes back)
        from ..ops.dispatch import _harmonize_placements
        _harmonize_placements(list(opt_params) + list(frozen)
                              + list(buffers) + list(args_t))
        arg_arrays = [t._data for t in args_t]

        need_clip = tuple(bool(getattr(p, "need_clip", True))
                          for p in opt_params)
        decay_flags = tuple(not getattr(p, "no_weight_decay", False)
                            for p in opt_params)
        from ..flags import flag_value
        donate = bool(flag_value("donate_optimizer_buffers"))
        key = _guard_key(template, arg_arrays, self.layers) + (
            len(opt_params), need_clip, decay_flags, donate)
        entry = self._compiled.get(key)
        if entry is None:
            entry = self._build(template, opt_params, frozen, buffers,
                                need_clip, decay_flags, donate)
            self._compiled[key] = entry

        opt._step_count += 1
        lr = jnp.asarray(opt.get_lr(), jnp.float32)
        step_no = jnp.asarray(opt._step_count, jnp.int32)
        rng_key = fr.next_key()

        loss, new_params, new_states, post_buffers = entry(
            [p._data for p in opt_params],
            states,
            [p._data for p in frozen],
            [b._data for b in buffers],
            arg_arrays, rng_key, lr, step_no)

        for p, a in zip(opt_params, new_params):
            p._replace_data(a)
        for p, s in zip(opt_params, new_states):
            opt._states[id(p)] = s
        for b, a in zip(buffers, post_buffers):
            b._replace_data(a)
        return Tensor(loss, stop_gradient=True)

    def _build(self, template, opt_params, frozen, buffers, need_clip,
               decay_flags, donate):
        fn = self.fn
        update = self.optimizer._build_update(need_clip, decay_flags)
        state_tensors = list(opt_params) + list(frozen) + list(buffers)

        def run_model(param_arrays, frozen_arrays, buffer_arrays,
                      arg_arrays, rng_key):
            out, post_buffers = _rebound_call(
                fn, state_tensors,
                list(param_arrays) + list(frozen_arrays)
                + list(buffer_arrays),
                template, arg_arrays, rng_key, buffers)
            loss = out[0] if isinstance(out, (tuple, list)) else out
            if isinstance(loss, Tensor):
                loss = loss._data
            if loss.ndim != 0 and loss.size == 1:
                loss = loss.reshape(())
            if loss.ndim != 0:
                raise ValueError(
                    "jit.train_step: train_fn must return a scalar loss "
                    f"(got shape {loss.shape})")
            return loss, post_buffers

        def pure_step(param_arrays, states, frozen_arrays, buffer_arrays,
                      arg_arrays, rng_key, lr, step_no):
            def loss_of(p_arrays):
                loss, post_b = run_model(p_arrays, frozen_arrays,
                                         buffer_arrays, arg_arrays, rng_key)
                return loss.astype(jnp.float32), post_b
            (loss, post_buffers), grads = jax.value_and_grad(
                loss_of, has_aux=True)(list(param_arrays))
            new_params, new_states = update(list(param_arrays), grads,
                                            states, lr, step_no)
            return loss, new_params, new_states, post_buffers

        return jax.jit(pure_step,
                       donate_argnums=(0, 1, 3) if donate else ())


def train_step(fn: Callable, optimizer, layers: Optional[Sequence] = None
               ) -> TrainStepProgram:
    """Compile `fn` (returning a scalar loss) plus `optimizer`'s update
    into one donated XLA executable. Layers are discovered from `fn`'s
    closure/globals like `to_static` when not given explicitly."""
    from ..optimizer.optimizer import Optimizer
    if not isinstance(optimizer, Optimizer):
        # __getattr__-delegating wrappers (dist.shard_optimizer,
        # ShardedOptimizer) apply their policies inside step(), which the
        # fused path bypasses; attribute writes would also land on the
        # wrapper and shadow the inner state. Refuse loudly.
        raise TypeError(
            f"jit.train_step needs a plain paddle Optimizer, got "
            f"{type(optimizer).__name__}; pass the wrapped optimizer's "
            "inner instance, or drive wrapper optimizers through "
            "forward/backward/step")
    if layers is None:
        from .api import _discover_layers
        layers = _discover_layers(fn)
    return TrainStepProgram(fn, optimizer, layers)
