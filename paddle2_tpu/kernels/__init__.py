"""TPU kernels: Pallas flash attention, fused elementwise/optimizer
steps, low-precision matmul paths, and the XLA reference attention.

Submodules (imported lazily by their call sites — importing this
package stays cheap):

* ``attention`` — `scaled_dot_product_attention` + remat policies.
* ``pallas_flash`` — the tiled online-softmax flash kernel.
* ``pallas_fused`` — fused AdamW/momentum STEP kernels (bitwise eager
  twins, in-place aliased), rmsnorm, rope.
* ``pallas_matmul`` — int8 weight-only / int8xint8 / fp8-shaped matmul
  kernels with analytic error bounds (ISSUE 10).
* ``pallas_ln`` — fused LayerNorm (flag-gated).
* ``fused_ce`` — chunked fused head + cross-entropy.
"""
