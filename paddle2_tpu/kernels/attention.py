"""Attention kernels (phi flash_attn_kernel.cu / third_party/flashattn parity).

Two paths:
- `scaled_dot_product_attention`: reference XLA implementation (fused well by
  XLA on small/medium sequence lengths).
- the Pallas TPU flash-attention kernel in pallas_flash.py, used automatically
  on TPU for long sequences (tile-wise online softmax, O(S) memory).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..ops.dispatch import apply_op, ensure_tensor


def _sdpa_xla(q, k, v, bias=None, causal=False, scale=None, dropout_p=0.0,
              dropout_key=None):
    """q,k,v: (B, S, H, D) paddle layout."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    # (B, H, S, D)
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    from ..ops.linalg import _mxu_precision
    prec = _mxu_precision(qh, kh)
    logits = jnp.einsum("bhsd,bhtd->bhst", qh, kh, precision=prec) * scale
    if bias is not None:
        logits = logits + bias
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s, t), bool), k=t - s)
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0).astype(q.dtype)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, vh, precision=prec)
    return jnp.swapaxes(out, 1, 2)


import threading

_flash_tls = threading.local()  # sdp_kernel toggles per-thread


def remat_policy(base: str = "dots"):
    """Rematerialization policy for transformer blocks using this module's
    attention: the base policy ('dots' = dots_with_no_batch_dims_saveable,
    'nothing' = full recompute) EXTENDED to always save the flash kernel's
    named residuals (o, lse), so backward never re-runs the forward pallas
    kernel. The TPU analog of the reference's recompute_granularity
    selective lists (fleet recompute 'core_attn' exclusion)."""
    cp = jax.checkpoint_policies
    names = cp.save_only_these_names("flash_out", "flash_lse")
    if base == "dots":
        return cp.save_from_both_policies(
            cp.dots_with_no_batch_dims_saveable, names)
    if base == "dots_plus":
        # dots + flash residuals + the tagged gelu output: backward
        # recomputes only cheap elementwise (ln/adds), at ~+64MB/layer
        more = cp.save_only_these_names("flash_out", "flash_lse",
                                        "mlp_gelu")
        return cp.save_from_both_policies(
            cp.dots_with_no_batch_dims_saveable, more)
    if base == "dots_plus_ln":
        # also pin the layernorm outputs (tagged "ln_out"): backward skips
        # the LN re-reduction (2 reduce passes over [tokens, H] each), at
        # +2 activation tensors (~32MB/layer at the GPT bench shape)
        more = cp.save_only_these_names("flash_out", "flash_lse",
                                        "mlp_gelu", "ln_out")
        return cp.save_from_both_policies(
            cp.dots_with_no_batch_dims_saveable, more)
    if base == "offload":
        # park the matmul outputs + named residuals in pinned host
        # memory instead of recomputing OR holding them in HBM (the
        # remat searcher's "offload_dots" candidate). Approximation of
        # the modeled candidate: only dot outputs and tagged names
        # offload — cheap elementwise still recomputes, exactly the
        # backward work the search charged it.
        offload_names = cp.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=["flash_out", "flash_lse",
                                          "mlp_gelu", "ln_out"],
            offload_src="device", offload_dst="pinned_host")
        return cp.save_from_both_policies(
            cp.offload_dot_with_no_batch_dims("device", "pinned_host"),
            offload_names)
    return names


def flash_enabled() -> bool:
    return getattr(_flash_tls, "enabled", True)


def set_flash_enabled(flag: bool) -> None:
    _flash_tls.enabled = bool(flag)


def use_pallas(q_shape) -> bool:
    if not flash_enabled():
        return False
    try:
        dev = jax.devices()[0]
    except Exception:
        return False
    if dev.platform.lower() == "cpu":
        return False
    # Pallas wins once the S*S score matrix stops fitting in VMEM-friendly
    # tiles; below that XLA's fusion is already near-roofline.
    return q_shape[1] >= 1024


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, causal=None,
                                 training=True, name=None):
    """paddle.nn.functional.scaled_dot_product_attention parity.

    Inputs are (batch, seq, num_heads, head_dim) like the reference flash-attn
    API (paddle/phi/kernels/gpu/flash_attn_kernel.cu qkv layout).
    """
    causal = causal if causal is not None else is_causal
    query, key, value = (ensure_tensor(query), ensure_tensor(key),
                         ensure_tensor(value))
    tensors = [query, key, value]
    has_mask = attn_mask is not None
    if has_mask:
        tensors.append(ensure_tensor(attn_mask))
    drop_key = None
    if dropout_p > 0.0 and training:
        from ..framework import random as fr
        drop_key = fr.next_key()

    if use_pallas(tuple(query.shape)) and not has_mask and drop_key is None:
        from .pallas_flash import (DEFAULT_BLOCK_K, DEFAULT_BLOCK_Q,
                                   flash_attention_bshd)
        from ..incubate import autotune
        bq, bk = DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K
        if autotune.kernel_tuning_enabled():
            bq, bk = autotune.best_flash_blocks(
                tuple(query.shape), tuple(key.shape), causal, (bq, bk))

        def fn(q, k, v):
            return flash_attention_bshd(q, k, v, causal=causal,
                                        block_q=bq, block_k=bk)
        return apply_op("flash_attention", fn, tuple(tensors), {})

    def fn(q, k, v, *mask):
        bias = mask[0] if mask else None
        return _sdpa_xla(q, k, v, bias=bias, causal=causal,
                         dropout_p=dropout_p if drop_key is not None else 0.0,
                         dropout_key=drop_key)
    return apply_op("sdpa", fn, tuple(tensors), {})
