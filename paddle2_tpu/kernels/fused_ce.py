"""Fused LM-head + softmax cross-entropy, chunked over tokens.

TPU-native replacement for the reference's big-vocab loss pipeline
(paddle/phi/kernels/gpu/cross_entropy_kernel.cu after a separate matmul
head; also c_softmax_with_cross_entropy for the parallel case): instead of
materializing the [N, V] f32 logits tensor twice per step (forward and
d_logits in backward — ~2 x N*V*4 bytes of HBM traffic, 1 GiB each for
GPT-2-medium at batch 8k tokens x 32k vocab), the head matmul and the
softmax reduction are evaluated chunk-by-chunk over tokens inside one
traced loop; backward recomputes each chunk's logits and contracts them
immediately into dx and dW. Peak memory for logits drops from O(N*V) to
O(C*V) (C = chunk rows), the same trick as the public Liger fused
linear-cross-entropy CUDA kernel, done here at the XLA level (lax.scan
keeps one compiled chunk body; the MXU sees the same [C,H]x[H,V] matmuls).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _pick_chunk(n: int, v: int = 32768) -> int:
    """Largest divisor chunk whose f32 logits block stays within ~1 GiB:
    bigger chunks mean fewer scan steps and no dW-carry HBM traffic —
    measured 17.2 -> 12.1 ms fwd+bwd going 2048 -> 8192 at [8192, 32k]
    on v5e — until the logits block pressures HBM."""
    budget = max(256, (1 << 30) // max(4 * v, 1))
    for c in (8192, 4096, 2048, 1024, 512, 256):
        if c <= budget and n % c == 0:
            return c
    return n


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_linear_cross_entropy(x, weight, labels, ignore_index=-100,
                               chunk=None):
    """Per-token CE loss of `softmax(x @ weight)` against `labels`.

    x: [N, H] activations; weight: [H, V]; labels: [N] int. Returns
    (losses [N] f32, valid [N] bool). Tokens equal to `ignore_index`
    contribute zero loss and zero gradient.
    """
    losses, valid = _fwd_chunks(x, weight, labels, ignore_index, chunk)[:2]
    return losses, valid


def _fwd_chunks(x, weight, labels, ignore_index, chunk):
    n, h = x.shape
    c = chunk or _pick_chunk(n, weight.shape[1])
    nchunk = n // c
    xs = x.reshape(nchunk, c, h)
    ls = labels.reshape(nchunk, c)

    def body(carry, xl):
        xc, lc = xl
        logits = jax.lax.dot_general(
            xc, weight, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [C, V] f32
        m = jnp.max(logits, axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
        safe = jnp.where(lc == ignore_index, 0, lc)
        picked = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
        ok = lc != ignore_index
        loss = jnp.where(ok, lse - picked, 0.0)
        return carry, (loss, ok, lse)

    if nchunk == 1:          # no scan machinery for the whole-batch chunk
        _, (loss, ok, lse) = body(0, (xs[0], ls[0]))
        return loss, ok, lse

    _, (losses, valid, lses) = jax.lax.scan(body, 0, (xs, ls))
    return (losses.reshape(n), valid.reshape(n), lses.reshape(n))


def _fle_fwd(x, weight, labels, ignore_index, chunk):
    losses, valid, lses = _fwd_chunks(x, weight, labels, ignore_index, chunk)
    return (losses, valid), (x, weight, labels, lses)


def _fle_bwd(ignore_index, chunk, res, cts):
    x, weight, labels, lses = res
    g, _ = cts                                           # [N] f32 cotangent
    n, h = x.shape
    c = chunk or _pick_chunk(n, weight.shape[1])
    nchunk = n // c
    xs = x.reshape(nchunk, c, h)
    ls = labels.reshape(nchunk, c)
    gs = g.reshape(nchunk, c)
    lse_s = lses.reshape(nchunk, c)
    v = weight.shape[1]

    def body(dw_acc, xlg):
        xc, lc, gc, lsec = xlg
        logits = jax.lax.dot_general(
            xc, weight, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [C, V]
        p = jnp.exp(logits - lsec[:, None])
        ok = lc != ignore_index
        safe = jnp.where(ok, lc, 0)
        onehot = jax.nn.one_hot(safe, v, dtype=p.dtype)
        dlogits = (p - onehot) * (gc * ok)[:, None]      # [C, V] f32
        dlogits = dlogits.astype(x.dtype)
        dx = jax.lax.dot_general(
            dlogits, weight, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32).astype(x.dtype)
        dw_acc = dw_acc + jax.lax.dot_general(
            xc, dlogits, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dw_acc, dx

    if nchunk == 1:
        dw, dx = body(jnp.zeros((h, v), jnp.float32),
                      (xs[0], ls[0], gs[0], lse_s[0]))
        return dx, dw.astype(weight.dtype), None

    dw, dxs = jax.lax.scan(
        body, jnp.zeros((h, v), jnp.float32), (xs, ls, gs, lse_s))
    return dxs.reshape(n, h), dw.astype(weight.dtype), None


fused_linear_cross_entropy.defvjp(_fle_fwd, _fle_bwd)
