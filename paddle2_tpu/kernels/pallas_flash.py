"""Pallas TPU flash attention, forward + backward (FlashAttention-2).

Replaces the reference's CUDA flash kernels
(paddle/phi/kernels/gpu/flash_attn_kernel.cu, third_party/flashattn) with a
TPU-native tiled online-softmax kernel:

- forward: grid (B, H, nq, nk) with the k-axis innermost; a VMEM scratch
  accumulator carries (o_acc, row-max m, row-sum l) across k steps, so HBM
  traffic is O(S*D) not O(S^2). The log-sum-exp is saved for the backward.
- backward: two kernels recompute attention tile-wise (flash-2 split):
  dK/dV with the q-axis innermost, dQ with the k-axis innermost, both
  seeded by delta = rowsum(dO * O).
- causal masking skips fully-masked tiles via pl.when (no wasted MXU work
  on the upper triangle); with Sq != Sk the diagonal is bottom-right
  aligned, matching the XLA fallback and flash-attn v2.1 semantics.
- lse/delta ride in (…, Sq, 128)-lane f32 buffers — the TPU lane-tiling
  minimum, the same layout the official jax flash kernel uses for l/m/di.

Layout contract matches the reference flash API: (batch, seq, heads, dim).
Compute is f32 on the MXU regardless of input dtype (bf16 in, f32 softmax).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# 1024-tiles measured best on v5e for the GPT bench (scores tile of
# 1024x1024 f32 = 4MB sits comfortably in VMEM; fewer grid steps beats
# finer tiling until S is long enough that autotune picks smaller blocks).
# Env-overridable for per-chip tuning (incubate.autotune searches these).
import os as _os
DEFAULT_BLOCK_Q = int(_os.environ.get("FLAGS_flash_block_q", 1024))
DEFAULT_BLOCK_K = int(_os.environ.get("FLAGS_flash_block_k", 1024))
# backward kernels may prefer different tiles than forward
BWD_BLOCK_Q = int(_os.environ.get("FLAGS_flash_bwd_block_q", 0)) or None
BWD_BLOCK_K = int(_os.environ.get("FLAGS_flash_bwd_block_k", 0)) or None
NEG_INF = float("-inf")


def _interpret_default() -> bool:
    try:
        return jax.devices()[0].platform.lower() == "cpu"
    except Exception:
        return True


def _fit_block(s: int, want: int):
    """Largest power-of-two block <= `want` that divides `s`, or None when
    no 8-row-aligned tiling exists. Requested block sizes are preferences,
    never correctness hazards: every divisible S gets a valid grid."""
    b = 1 << (min(want, s).bit_length() - 1)
    while b >= 8:
        if s % b == 0:
            return b
        b //= 2
    return None



def _online_softmax_step(s, v, acc, m_sc, l_sc):
    """Shared flash-fwd tile update: online softmax recurrence over the
    masked score tile `s` (NEG_INF = masked). Mutates acc/m_sc/l_sc."""
    m_prev = m_sc[:, :1]
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    safe_m = jnp.where(m_new == NEG_INF, 0.0, m_new)
    p = jnp.exp(s - safe_m)
    p = jnp.where(s == NEG_INF, 0.0, p)
    alpha = jnp.where(m_prev == NEG_INF, 0.0, jnp.exp(m_prev - safe_m))
    l_new = alpha * l_sc[:, :1] + jnp.sum(p, axis=1, keepdims=True)
    acc[:] = acc[:] * alpha + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT)
    m_sc[:] = jnp.broadcast_to(m_new, m_sc.shape)
    l_sc[:] = jnp.broadcast_to(l_new, l_sc.shape)


def _flash_finalize(o_ref, lse_ref, acc, m_sc, l_sc):
    """Shared flash-fwd epilogue: normalize and emit (o, lse)."""
    l = l_sc[:, :1]
    safe_l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc[:] / safe_l).astype(o_ref.dtype)
    m = m_sc[:, :1]
    lse = jnp.where(l == 0.0, NEG_INF, m + jnp.log(safe_l))
    lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _scores(q, k, scale):
    return jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT) * scale


def _bwd_p_ds(s, lse, delta, do, v, guarded=True):
    """Shared flash-bwd tile math: probabilities p and score cotangent ds
    from the masked tile `s` and saved (lse, delta). `guarded=False`
    skips the fully-masked-row selects (two VPU passes over the tile) —
    valid whenever every row has at least one unmasked column, i.e.
    causal with Sk >= Sq or no mask (the single-block fused path)."""
    if guarded:
        p = jnp.exp(s - jnp.where(lse == NEG_INF, 0.0, lse))
        p = jnp.where((s == NEG_INF) | (lse == NEG_INF), 0.0, p)
    else:
        p = jnp.exp(s - lse)              # masked: exp(-inf - finite) = 0
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT)
    return p, p * (dp - delta)


# ---------------------------------------------------------------- forward

def _fwd_kernel_1blk(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                     offset):
    """Single-block specialization (nq == nk == 1): the whole row fits in
    one tile, so the online-softmax recurrence, VMEM scratch, and init/
    finalize predication all collapse into a direct softmax — measured
    ~30% faster than the general kernel at the GPT bench shape
    (B8 S1024 H16 D64 on v5e). scale folds into the q tile in VMEM (an
    XLA-side pre-scale would cost a full extra HBM pass on q).
    Requires offset >= 0 when causal (every row has a valid column, so
    the row max is finite and no masked-row guards are needed)."""
    q = q_ref[0, 0] * jnp.asarray(scale, q_ref.dtype)
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=jax.lax.Precision.DEFAULT)
    if causal:
        bq, bk = s.shape
        rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + offset
        cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(rows >= cols, s, NEG_INF)
    m = jnp.max(s, axis=1, keepdims=True)
    p = jnp.exp(s - m)                    # masked: exp(-inf - finite) = 0
    l = jnp.sum(p, axis=1, keepdims=True)
    o = jax.lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=jax.lax.Precision.DEFAULT)
    o_ref[0, 0] = (o / l).astype(o_ref.dtype)
    lse_ref[0, 0] = jnp.broadcast_to(m + jnp.log(l), lse_ref.shape[2:])


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_sc, l_sc,
                *, scale, causal, block_q, block_k, nk, offset):
    ik = pl.program_id(3)
    iq = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    q_start = iq * block_q
    k_start = ik * block_k
    run = True
    if causal:
        # skip tiles entirely above the (bottom-right aligned) diagonal
        run = k_start <= q_start + offset + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]                              # (Bq, D) native dtype
        k = k_ref[0, 0]                              # (Bk, D)
        v = v_ref[0, 0]                              # (Bk, D)
        # native-dtype (bf16) MXU matmul with f32 accumulation — casting the
        # operands to f32 would fall off the MXU fast path (~8x slower)
        s = _scores(q, k, scale)                      # (Bq, Bk) f32
        if causal:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + q_start + offset
            cols = jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 1) + k_start
            s = jnp.where(rows >= cols, s, NEG_INF)
        _online_softmax_step(s, v, acc, m_sc, l_sc)

    @pl.when(ik == nk - 1)
    def _finalize():
        _flash_finalize(o_ref, lse_ref, acc, m_sc, l_sc)


def _clamp_blocks_for_dtype(dtype, block_q, block_k):
    """Non-bf16 inputs double the VMEM a tile needs: the 1024x1024
    defaults that fit bf16 blow the scoped-vmem budget for f32 (compile
    fails with a stack OOM). Halve the blocks for >=4-byte dtypes."""
    if jnp.dtype(dtype).itemsize >= 4:
        return min(block_q, 512), min(block_k, 512)
    return block_q, block_k


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    """q,k,v: (B, H, S, D) — returns (o, lse)."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    block_q, block_k = _clamp_blocks_for_dtype(q.dtype, block_q, block_k)
    bq, bk = _fit_block(Sq, block_q), _fit_block(Sk, block_k)
    nq, nk = Sq // bq, Sk // bk

    if nq == 1 and nk == 1 and (not causal or Sk >= Sq):
        o, lse = pl.pallas_call(
            functools.partial(_fwd_kernel_1blk, scale=scale, causal=causal,
                              offset=Sk - Sq),
            grid=(B, H),
            in_specs=[pl.BlockSpec((1, 1, Sq, D),
                                   lambda b, h: (b, h, 0, 0)),
                      pl.BlockSpec((1, 1, Sk, D),
                                   lambda b, h: (b, h, 0, 0)),
                      pl.BlockSpec((1, 1, Sk, D),
                                   lambda b, h: (b, h, 0, 0))],
            out_specs=[pl.BlockSpec((1, 1, Sq, D),
                                    lambda b, h: (b, h, 0, 0)),
                       pl.BlockSpec((1, 1, Sq, 128),
                                    lambda b, h: (b, h, 0, 0))],
            out_shape=[jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
                       jax.ShapeDtypeStruct((B, H, Sq, 128), jnp.float32)],
            interpret=interpret,
        )(q, k, v)
        return o, lse[..., 0]

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=bq, block_k=bk, nk=nk,
                               offset=Sk - Sq)
    grid = (B, H, nq, nk)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, iq, ik: (b, h, ik, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, iq, ik: (b, h, ik, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, bq, 128), lambda b, h, iq, ik: (b, h, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Sq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return o, lse[..., 0]


# --------------------------------------------------------------- backward

def _bwd_fused_1blk_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           dq_ref, dk_ref, dv_ref, *, scale, causal,
                           offset):
    """Single-block fused backward (nq == nk == 1): dQ, dK, dV from ONE
    score/probability computation — the two-kernel flash-2 split exists
    only to order the tile accumulations, which a single tile does not
    need. Saves one QK^T, one dO V^T, and one mask+exp pass vs the split
    (measured 2.45 -> 1.70 ms/layer at the GPT bench shape on v5e).
    Requires offset >= 0 when causal (no fully-masked rows, lse finite)."""
    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0][:, :1]
    delta = delta_ref[0, 0][:, :1]
    qs = q * jnp.asarray(scale, q.dtype)
    s = jax.lax.dot_general(qs, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=jax.lax.Precision.DEFAULT)
    if causal:
        bq, bk = s.shape
        rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + offset
        cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(rows >= cols, s, NEG_INF)
    p, ds_f = _bwd_p_ds(s, lse, delta, do, v, guarded=False)
    ds = ds_f.astype(q.dtype)
    dv_ref[0, 0] = jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT).astype(dv_ref.dtype)
    dk_ref[0, 0] = jax.lax.dot_general(
        ds, qs, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT).astype(dk_ref.dtype)
    dq = jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32,
                             precision=jax.lax.Precision.DEFAULT)
    dq_ref[0, 0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, scale, causal, block_q, block_k, nq, offset):
    iq = pl.program_id(3)
    ik = pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = iq * block_q
    k_start = ik * block_k
    run = True
    if causal:
        run = q_start + offset + block_q - 1 >= k_start

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]                                 # (Bq, D)
        k = k_ref[0, 0]                                 # (Bk, D)
        v = v_ref[0, 0]                                 # (Bk, D)
        do = do_ref[0, 0]                               # (Bq, D)
        lse = lse_ref[0, 0][:, :1]                      # (Bq, 1)
        delta = delta_ref[0, 0][:, :1]                  # (Bq, 1)
        s = _scores(q, k, scale)                       # (Bq, Bk)
        if causal:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + q_start + offset
            cols = jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 1) + k_start
            s = jnp.where(rows >= cols, s, NEG_INF)
        p, ds = _bwd_p_ds(s, lse, delta, do, v)
        # dV += P^T dO ; dK += dS^T Q * scale
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT) * scale

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc,
                   *, scale, causal, block_q, block_k, nk, offset):
    ik = pl.program_id(3)
    iq = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = iq * block_q
    k_start = ik * block_k
    run = True
    if causal:
        run = k_start <= q_start + offset + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = _scores(q, k, scale)
        if causal:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + q_start + offset
            cols = jax.lax.broadcasted_iota(jnp.int32,
                                            (block_q, block_k), 1) + k_start
            s = jnp.where(rows >= cols, s, NEG_INF)
        _p, ds = _bwd_p_ds(s, lse, delta, do, v)
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT) * scale

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, scale, causal, block_q, block_k,
               interpret):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    block_q = BWD_BLOCK_Q or block_q
    block_k = BWD_BLOCK_K or block_k
    block_q, block_k = _clamp_blocks_for_dtype(q.dtype, block_q, block_k)
    bq, bk = _fit_block(Sq, block_q), _fit_block(Sk, block_k)
    nq, nk = Sq // bq, Sk // bk

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                              # (B, H, Sq)
    lse_b = jnp.broadcast_to(lse[..., None], (B, H, Sq, 128))
    delta_b = jnp.broadcast_to(delta[..., None], (B, H, Sq, 128))

    if nq == 1 and nk == 1 and (not causal or Sk >= Sq):
        spec_q = pl.BlockSpec((1, 1, Sq, D), lambda b, h: (b, h, 0, 0))
        spec_k = pl.BlockSpec((1, 1, Sk, D), lambda b, h: (b, h, 0, 0))
        spec_r = pl.BlockSpec((1, 1, Sq, 128), lambda b, h: (b, h, 0, 0))
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_fused_1blk_kernel, scale=scale,
                              causal=causal, offset=Sk - Sq),
            grid=(B, H),
            in_specs=[spec_q, spec_k, spec_k, spec_q, spec_r, spec_r],
            out_specs=[spec_q, spec_k, spec_k],
            out_shape=[jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
                       jax.ShapeDtypeStruct((B, H, Sk, D), k.dtype),
                       jax.ShapeDtypeStruct((B, H, Sk, D), v.dtype)],
            interpret=interpret,
        )(q, k, v, do, lse_b, delta_b)
        return dq, dk, dv

    q_spec_kmaj = pl.BlockSpec((1, 1, bq, D),
                               lambda b, h, ik, iq: (b, h, iq, 0))
    k_spec_kmaj = pl.BlockSpec((1, 1, bk, D),
                               lambda b, h, ik, iq: (b, h, ik, 0))
    r_spec_kmaj = pl.BlockSpec((1, 1, bq, 128),
                               lambda b, h, ik, iq: (b, h, iq, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, nq=nq, offset=Sk - Sq),
        grid=(B, H, nk, nq),
        in_specs=[q_spec_kmaj, k_spec_kmaj, k_spec_kmaj, q_spec_kmaj,
                  r_spec_kmaj, r_spec_kmaj],
        out_specs=[
            pl.BlockSpec((1, 1, bk, D), lambda b, h, ik, iq: (b, h, ik, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, ik, iq: (b, h, ik, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, Sk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse_b, delta_b)

    q_spec_qmaj = pl.BlockSpec((1, 1, bq, D),
                               lambda b, h, iq, ik: (b, h, iq, 0))
    k_spec_qmaj = pl.BlockSpec((1, 1, bk, D),
                               lambda b, h, iq, ik: (b, h, ik, 0))
    r_spec_qmaj = pl.BlockSpec((1, 1, bq, 128),
                               lambda b, h, iq, ik: (b, h, iq, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, nk=nk, offset=Sk - Sq),
        grid=(B, H, nq, nk),
        in_specs=[q_spec_qmaj, k_spec_qmaj, k_spec_qmaj, q_spec_qmaj,
                  r_spec_qmaj, r_spec_qmaj],
        out_specs=pl.BlockSpec((1, 1, bq, D),
                               lambda b, h, iq, ik: (b, h, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse_b, delta_b)
    return dq, dk, dv


# -------------------------------------------------------------- public API

def supported(q_shape, k_shape, block_q=DEFAULT_BLOCK_Q,
              block_k=DEFAULT_BLOCK_K) -> bool:
    """Kernel shape constraints (reference flash_attn has analogous ones).
    Block sizes self-fit to the sequence (largest divisor), so any S with
    an 8-row-aligned tiling is supported regardless of the requested
    blocks — including the backward-block env overrides."""
    B, Sq, H, D = q_shape
    Sk = k_shape[1]
    return (_fit_block(Sq, block_q) is not None
            and _fit_block(Sk, block_k) is not None
            and _fit_block(Sq, BWD_BLOCK_Q or block_q) is not None
            and _fit_block(Sk, BWD_BLOCK_K or block_k) is not None
            and D <= 256 and k_shape[2] == H)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    o, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return o


def _flash_vjp_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    # name the residuals so rematerialization policies can pin them:
    # under jax.checkpoint with kernels.attention.remat_policy() the saved
    # (o, lse) let the backward run WITHOUT re-executing the forward
    # pallas kernel (the usual flash-under-remat trap)
    from jax.ad_checkpoint import checkpoint_name
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, g, scale, causal,
                            block_q, block_k, interpret)
    return dq, dk, dv


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ------------------------------------------------------------- varlen
# Packed (cu_seqlens) attention: the whole ragged batch stays ONE packed
# [T, H, D] sequence (reference flash_attn_unpadded,
# python/paddle/nn/functional/flash_attention.py:593 — no densify). Each
# row carries a segment id and a causal offset; the mask is
#   same-segment AND k_off <= q_off
# where q_off = local_q_pos + (len_k - len_q) (bottom-right alignment per
# sequence) and k_off = local_k_pos. Tiles whose segment ranges cannot
# intersect are SKIPPED dynamically (pl.when on the loaded id blocks) —
# the varlen analog of the causal triangle skip.

def _mk_varlen_mask(sq, oq, sk, ok):
    # sq/oq: (Bq, 1) int32; sk/ok: (1, Bk) int32 -> (Bq, Bk) bool.
    # 2-D operands throughout: 1-D slices would force Mosaic relayouts
    # that blow the scoped-VMEM budget.
    return (sq == sk) & (ok <= oq)


def _fwd_kernel_varlen(q_ref, k_ref, v_ref, sq_ref, oq_ref, sk_ref, ok_ref,
                       o_ref, lse_ref, acc, m_sc, l_sc, *, scale, nk):
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    sq = sq_ref[0, 0][:, :1]          # (Bq, 1)
    sk = sk_ref[0, 0][:1]              # (1, Bk)
    # dynamic tile skip: segments are sorted, so a tile is dead unless
    # [min(sk), max(sk)] intersects [min(sq), max(sq)]
    run = (jnp.min(sk) <= jnp.max(sq)) & (jnp.max(sk) >= jnp.min(sq))

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        oq = oq_ref[0, 0][:, :1]
        ok = ok_ref[0, 0][:1]
        s = _scores(q, k, scale)
        s = jnp.where(_mk_varlen_mask(sq, oq, sk, ok), s, NEG_INF)
        _online_softmax_step(s, v, acc, m_sc, l_sc)

    @pl.when(ik == nk - 1)
    def _finalize():
        _flash_finalize(o_ref, lse_ref, acc, m_sc, l_sc)


def _bwd_dkv_kernel_varlen(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           sq_ref, oq_ref, sk_ref, ok_ref,
                           dk_ref, dv_ref, dk_acc, dv_acc, *, scale, nq):
    iq = pl.program_id(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    sq = sq_ref[0, 0][:, :1]          # (Bq, 1)
    sk = sk_ref[0, 0][:1]              # (1, Bk)
    run = (jnp.min(sk) <= jnp.max(sq)) & (jnp.max(sk) >= jnp.min(sq))

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        oq = oq_ref[0, 0][:, :1]
        ok = ok_ref[0, 0][:1]
        s = _scores(q, k, scale)
        s = jnp.where(_mk_varlen_mask(sq, oq, sk, ok), s, NEG_INF)
        p, ds = _bwd_p_ds(s, lse, delta, do, v)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT) * scale

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel_varlen(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          sq_ref, oq_ref, sk_ref, ok_ref, dq_ref, dq_acc,
                          *, scale, nk):
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    sq = sq_ref[0, 0][:, :1]          # (Bq, 1)
    sk = sk_ref[0, 0][:1]              # (1, Bk)
    run = (jnp.min(sk) <= jnp.max(sq)) & (jnp.max(sk) >= jnp.min(sq))

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        oq = oq_ref[0, 0][:, :1]
        ok = ok_ref[0, 0][:1]
        s = _scores(q, k, scale)
        s = jnp.where(_mk_varlen_mask(sq, oq, sk, ok), s, NEG_INF)
        _p, ds = _bwd_p_ds(s, lse, delta, do, v)
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT) * scale

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _lane(x, T):
    """[T] int32 -> [1, 1, T, 128] lane-tiled q-side metadata."""
    return jnp.broadcast_to(x.astype(jnp.int32)[None, None, :, None],
                            (1, 1, T, 128))


def _lane_k(x, T):
    """[T] int32 -> [1, 1, 8, T] sublane-tiled k-side metadata (read as a
    (1, bk) lane-major block — no transpose in the kernel)."""
    return jnp.broadcast_to(x.astype(jnp.int32)[None, None, None, :],
                            (1, 1, 8, T))


def _varlen_fwd(q, k, v, sq, oq, sk, ok, scale, block_q, block_k,
                interpret):
    """q,k,v: (1, H, T, D). Returns (o, lse)."""
    _, H, Tq, D = q.shape
    Tk = k.shape[2]
    bq, bk = _fit_block(Tq, block_q), _fit_block(Tk, block_k)
    nq, nk = Tq // bq, Tk // bk
    q_meta = pl.BlockSpec((1, 1, bq, 128),
                          lambda b, h, iq, ik: (0, 0, iq, 0))
    k_meta = pl.BlockSpec((1, 1, 8, bk),
                          lambda b, h, iq, ik: (0, 0, 0, ik))
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel_varlen, scale=scale, nk=nk),
        grid=(1, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, iq, ik: (b, h, ik, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, iq, ik: (b, h, ik, 0)),
            q_meta, q_meta, k_meta, k_meta,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, iq, ik: (b, h, iq, 0)),
            pl.BlockSpec((1, 1, bq, 128), lambda b, h, iq, ik: (b, h, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, H, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((1, H, Tq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, _lane(sq, Tq), _lane(oq, Tq), _lane_k(sk, Tk),
      _lane_k(ok, Tk))
    return o, lse[..., 0]


def _varlen_bwd(q, k, v, o, lse, do, sq, oq, sk, ok, scale, block_q,
                block_k, interpret):
    _, H, Tq, D = q.shape
    Tk = k.shape[2]
    block_q = BWD_BLOCK_Q or block_q
    block_k = BWD_BLOCK_K or block_k
    bq, bk = _fit_block(Tq, block_q), _fit_block(Tk, block_k)
    nq, nk = Tq // bq, Tk // bk
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    lse_b = jnp.broadcast_to(lse[..., None], (1, H, Tq, 128))
    delta_b = jnp.broadcast_to(delta[..., None], (1, H, Tq, 128))
    sq_l, oq_l = _lane(sq, Tq), _lane(oq, Tq)
    sk_l, ok_l = _lane_k(sk, Tk), _lane_k(ok, Tk)

    qm = lambda b, h, ik, iq: (b, h, iq, 0)
    km = lambda b, h, ik, iq: (b, h, ik, 0)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_varlen, scale=scale, nq=nq),
        grid=(1, H, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), qm), pl.BlockSpec((1, 1, bk, D), km),
            pl.BlockSpec((1, 1, bk, D), km), pl.BlockSpec((1, 1, bq, D), qm),
            pl.BlockSpec((1, 1, bq, 128), qm),
            pl.BlockSpec((1, 1, bq, 128), qm),
            pl.BlockSpec((1, 1, bq, 128),
                         lambda b, h, ik, iq: (0, 0, iq, 0)),
            pl.BlockSpec((1, 1, bq, 128),
                         lambda b, h, ik, iq: (0, 0, iq, 0)),
            pl.BlockSpec((1, 1, 8, bk),
                         lambda b, h, ik, iq: (0, 0, 0, ik)),
            pl.BlockSpec((1, 1, 8, bk),
                         lambda b, h, ik, iq: (0, 0, 0, ik)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, D), lambda b, h, ik, iq: (b, h, ik, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, ik, iq: (b, h, ik, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, H, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((1, H, Tk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse_b, delta_b, sq_l, oq_l, sk_l, ok_l)

    qn = lambda b, h, iq, ik: (b, h, iq, 0)
    kn = lambda b, h, iq, ik: (b, h, ik, 0)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_varlen, scale=scale, nk=nk),
        grid=(1, H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), qn), pl.BlockSpec((1, 1, bk, D), kn),
            pl.BlockSpec((1, 1, bk, D), kn), pl.BlockSpec((1, 1, bq, D), qn),
            pl.BlockSpec((1, 1, bq, 128), qn),
            pl.BlockSpec((1, 1, bq, 128), qn),
            pl.BlockSpec((1, 1, bq, 128),
                         lambda b, h, iq, ik: (0, 0, iq, 0)),
            pl.BlockSpec((1, 1, bq, 128),
                         lambda b, h, iq, ik: (0, 0, iq, 0)),
            pl.BlockSpec((1, 1, 8, bk),
                         lambda b, h, iq, ik: (0, 0, 0, ik)),
            pl.BlockSpec((1, 1, 8, bk),
                         lambda b, h, iq, ik: (0, 0, 0, ik)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, D),
                               lambda b, h, iq, ik: (b, h, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((1, H, Tq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse_b, delta_b, sq_l, oq_l, sk_l, ok_l)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _flash_varlen(q, k, v, sq, oq, sk, ok, scale, block_q, block_k,
                  interpret):
    o, _ = _varlen_fwd(q, k, v, sq, oq, sk, ok, scale, block_q, block_k,
                       interpret)
    return o


def _flash_varlen_fwd(q, k, v, sq, oq, sk, ok, scale, block_q, block_k,
                      interpret):
    o, lse = _varlen_fwd(q, k, v, sq, oq, sk, ok, scale, block_q, block_k,
                         interpret)
    return o, (q, k, v, o, lse, sq, oq, sk, ok)


def _flash_varlen_bwd(scale, block_q, block_k, interpret, res, g):
    q, k, v, o, lse, sq, oq, sk, ok = res
    dq, dk, dv = _varlen_bwd(q, k, v, o, lse, g, sq, oq, sk, ok, scale,
                             block_q, block_k, interpret)
    return dq, dk, dv, None, None, None, None


_flash_varlen.defvjp(_flash_varlen_fwd, _flash_varlen_bwd)


# eager calls must hit a CACHED jitted entry: rebuilding the pallas_call
# closure per call would re-trace (and re-run the Mosaic compiler) every
# time — jit-per-config gives the C++ dispatch fast path instead
_JIT_CACHE: dict = {}


def _cached_jit(key, builder):
    fn = _JIT_CACHE.get(key)
    if fn is None:
        fn = jax.jit(builder())
        _JIT_CACHE[key] = fn
    return fn


def flash_attention_varlen_packed(q, k, v, seg_q, off_q, seg_k, off_k,
                                  scale=None, block_q=None, block_k=None,
                                  interpret=None):
    """Packed varlen flash attention.

    q: [Tq, H, D], k/v: [Tk, H, D] packed rows (pad T to a multiple of 8
    with seg id -1 / -2 rows). seg_*: int32 [T] per-row segment ids
    (sorted ascending; padding must use ids that never match). off_*:
    int32 [T] causal offsets — mask keeps (seg equal) & (off_k <= off_q);
    pass off_q = local_q_pos + (len_k - len_q), off_k = local_k_pos for
    per-sequence bottom-right-aligned causal, or off_q = +inf-like large
    values for non-causal. Differentiable (pallas fwd+bwd)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = _interpret_default()
    block_q = block_q or DEFAULT_BLOCK_Q
    block_k = block_k or DEFAULT_BLOCK_K
    cfg = (float(scale), int(block_q), int(block_k), bool(interpret))
    fn = _cached_jit(("varlen",) + cfg, lambda: (
        lambda q, k, v, sq, oq, sk, ok: jnp.swapaxes(_flash_varlen(
            jnp.swapaxes(q, 0, 1)[None], jnp.swapaxes(k, 0, 1)[None],
            jnp.swapaxes(v, 0, 1)[None], sq, oq, sk, ok, *cfg)[0], 0, 1)))
    return fn(q, k, v, jnp.asarray(seg_q, jnp.int32),
              jnp.asarray(off_q, jnp.int32),
              jnp.asarray(seg_k, jnp.int32),
              jnp.asarray(off_k, jnp.int32))


def flash_attention_bshd(q, k, v, causal=False, scale=None,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                         interpret=None):
    """Flash attention on (batch, seq, heads, dim) arrays (reference
    flash_attn qkv layout). Differentiable via the Pallas backward kernels;
    falls back to the XLA path when shapes are unsupported."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if not supported(q.shape, k.shape, block_q, block_k):
        from .attention import _sdpa_xla
        return _sdpa_xla(q, k, v, causal=causal, scale=scale)
    if interpret is None:
        interpret = _interpret_default()
    cfg = (float(scale), bool(causal), int(block_q), int(block_k),
           bool(interpret))
    fn = _cached_jit(("bshd",) + cfg, lambda: (
        lambda q, k, v: jnp.swapaxes(
            _flash(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                   jnp.swapaxes(v, 1, 2), *cfg), 1, 2)))
    return fn(q, k, v)
