"""Pallas TPU flash-attention (placeholder wiring; kernel lands with the
kernels milestone). Falls back to the XLA fused path, which is numerically
identical."""

from __future__ import annotations


def flash_attention_bshd(q, k, v, causal=False, scale=None):
    from .attention import _sdpa_xla
    return _sdpa_xla(q, k, v, causal=causal, scale=scale)
