"""Fused elementwise Pallas kernels (reference paddle/phi/kernels/fusion/:
fused_adam_kernel.cu multi-tensor Adam, fused_rope, rms_norm fusions).

On TPU, XLA already fuses elementwise chains aggressively, so each kernel
here ships with a microbench against the XLA-fused baseline
(tests/test_pallas_fused.py asserts parity; .bench notes record measured
wins/losses). The kernels keep ONE HBM pass over every operand with
explicit VMEM tiling — the win over XLA appears when the compiler splits
the chain across fusions (large multi-tensor updates) or when layout
choices force relayouts (rope's interleaved pairs).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret_default() -> bool:
    try:
        return jax.devices()[0].platform.lower() == "cpu"
    except Exception:
        return True


# ------------------------------------------------------------ fused adamw

def _adamw_kernel(p_ref, g_ref, m_ref, v_ref, mst_ref, sc_ref,
                  p_out, m_out, v_out, mst_out):
    """One pass: read (p, g, m, v, master), write (p, m, v, master).
    sc_ref (SMEM) carries [lr, beta1, beta2, eps, wd, bc1, bc2]."""
    lr = sc_ref[0]
    b1 = sc_ref[1]
    b2 = sc_ref[2]
    eps = sc_ref[3]
    wd = sc_ref[4]
    bc1 = sc_ref[5]
    bc2 = sc_ref[6]
    g = g_ref[:].astype(jnp.float32)
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    mhat = m / bc1
    vhat = v / bc2
    mw = mst_ref[:]
    mw = mw - lr * (mhat / (jnp.sqrt(vhat) + eps) + wd * mw)
    p_out[:] = mw.astype(p_out.dtype)
    m_out[:] = m
    v_out[:] = v
    mst_out[:] = mw


def fused_adamw(param, grad, m, v, master, lr, beta1=0.9, beta2=0.999,
                eps=1e-8, weight_decay=0.01, step=1, block=None,
                interpret=None):
    """Decoupled-weight-decay Adam on FLAT arrays in one kernel pass
    (fused_adam_kernel.cu parity): param bf16/f32, master+moments f32.
    Returns (new_param, new_m, new_v, new_master)."""
    if interpret is None:
        interpret = _interpret_default()
    n = param.size
    flat = lambda a: a.reshape(-1)
    p1, g1, m1, v1, w1 = (flat(a) for a in (param, grad, m, v, master))
    blk = block or min(n, 1 << 17)
    # pad to a block multiple (lane-aligned)
    npad = -(-n // blk) * blk
    if npad != n:
        pad = lambda a: jnp.concatenate(
            [a, jnp.zeros(npad - n, a.dtype)])
        p1, g1, m1, v1, w1 = (pad(a) for a in (p1, g1, m1, v1, w1))
    t = jnp.float32(step)
    sc = jnp.stack([jnp.float32(lr), jnp.float32(beta1), jnp.float32(beta2),
                    jnp.float32(eps), jnp.float32(weight_decay),
                    1.0 - jnp.float32(beta1) ** t,
                    1.0 - jnp.float32(beta2) ** t])
    grid = (npad // blk,)
    spec = pl.BlockSpec((blk,), lambda i: (i,))
    po, mo, vo, wo = pl.pallas_call(
        _adamw_kernel,
        grid=grid,
        in_specs=[spec, spec, spec, spec, spec,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[spec, spec, spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct((npad,), param.dtype),
            jax.ShapeDtypeStruct((npad,), jnp.float32),
            jax.ShapeDtypeStruct((npad,), jnp.float32),
            jax.ShapeDtypeStruct((npad,), jnp.float32),
        ],
        interpret=interpret,
    )(p1, g1, m1, v1, w1, sc)
    unflat = lambda a, like: a[:n].reshape(param.shape).astype(like.dtype) \
        if a.dtype != like.dtype else a[:n].reshape(param.shape)
    return (po[:n].reshape(param.shape), mo[:n].reshape(param.shape),
            vo[:n].reshape(param.shape), wo[:n].reshape(param.shape))


# ------------------------------------------------------------ fused rmsnorm

def _rmsnorm_fwd_kernel(x_ref, w_ref, o_ref, r_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    r = jax.lax.rsqrt(ms + eps)
    o_ref[:] = (x * r * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)
    r_ref[:] = jnp.broadcast_to(r, r_ref.shape)


def _rmsnorm_fwd(x, w, eps, block_rows, interpret):
    R, H = x.shape
    br = min(block_rows, R)
    while R % br:
        br //= 2
    grid = (R // br,)
    o, r = pl.pallas_call(
        functools.partial(_rmsnorm_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[pl.BlockSpec((br, H), lambda i: (i, 0)),
                  pl.BlockSpec((1, H), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((br, H), lambda i: (i, 0)),
                   pl.BlockSpec((br, 128), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((R, H), x.dtype),
                   jax.ShapeDtypeStruct((R, 128), jnp.float32)],
        interpret=interpret,
    )(x, w.reshape(1, H))
    return o, r[:, 0]


def _rmsnorm_bwd_kernel(x_ref, w_ref, r_ref, do_ref, dx_ref, dwp_ref):
    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    r = r_ref[:][:, :1]
    do = do_ref[:].astype(jnp.float32)
    xhat = x * r
    dy = do * w
    # d rms: dx = r * (dy - xhat * mean(dy * xhat))
    mean_term = jnp.mean(dy * xhat, axis=-1, keepdims=True)
    dx_ref[:] = (r * (dy - xhat * mean_term)).astype(dx_ref.dtype)
    # per-block dw partial, broadcast over an 8-row sublane tile
    dwp_ref[:] = jnp.broadcast_to(
        jnp.sum(do * xhat, axis=0, keepdims=True), dwp_ref.shape)


def _rmsnorm_bwd(x, w, r, do, block_rows, interpret):
    R, H = x.shape
    br = min(block_rows, R)
    while R % br:
        br //= 2
    grid = (R // br,)
    r2 = jnp.broadcast_to(r[:, None], (R, 128))
    dx, dw_part = pl.pallas_call(
        _rmsnorm_bwd_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((br, H), lambda i: (i, 0)),
                  pl.BlockSpec((1, H), lambda i: (0, 0)),
                  pl.BlockSpec((br, 128), lambda i: (i, 0)),
                  pl.BlockSpec((br, H), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((br, H), lambda i: (i, 0)),
                   pl.BlockSpec((8, H), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((R, H), x.dtype),
                   jax.ShapeDtypeStruct((R // br * 8, H), jnp.float32)],
        interpret=interpret,
    )(x, w.reshape(1, H), r2, do)
    return dx, dw_part[::8].sum(axis=0).astype(w.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _rmsnorm(x, w, eps, block_rows, interpret):
    o, _ = _rmsnorm_fwd(x, w, eps, block_rows, interpret)
    return o


def _rmsnorm_vjp_fwd(x, w, eps, block_rows, interpret):
    o, r = _rmsnorm_fwd(x, w, eps, block_rows, interpret)
    return o, (x, w, r)


def _rmsnorm_vjp_bwd(eps, block_rows, interpret, res, g):
    x, w, r = res
    dx, dw = _rmsnorm_bwd(x, w, r, g, block_rows, interpret)
    return dx, dw


_rmsnorm.defvjp(_rmsnorm_vjp_fwd, _rmsnorm_vjp_bwd)

_JIT_CACHE: dict = {}


def fused_rms_norm(x, weight, epsilon=1e-6, block_rows=512, interpret=None):
    """RMSNorm over the last dim in one pallas pass (fwd + custom bwd);
    any leading shape. Differentiable."""
    if interpret is None:
        interpret = _interpret_default()
    shape = x.shape
    H = shape[-1]
    key = ("rmsnorm", float(epsilon), int(block_rows), bool(interpret))
    fn = _JIT_CACHE.get(key)
    if fn is None:
        fn = jax.jit(lambda x2, w: _rmsnorm(x2, w, float(epsilon),
                                            int(block_rows),
                                            bool(interpret)))
        _JIT_CACHE[key] = fn
    return fn(x.reshape(-1, H), weight).reshape(shape)


# --------------------------------------------------------------- fused rope

def _rope_kernel(x_ref, cos_ref, sin_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)          # (rows, H, D)
    cos = cos_ref[:].astype(jnp.float32)[:, None, :]   # (rows, 1, D)
    sin = sin_ref[:].astype(jnp.float32)[:, None, :]
    D = x.shape[-1]
    x1 = x[..., : D // 2]
    x2 = x[..., D // 2:]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    o_ref[:] = (x * cos + rot * sin).astype(o_ref.dtype)


def fused_rope(x, cos, sin, block_rows=256, interpret=None):
    """Rotary embedding (half-split convention) in one pass over
    [B, S, H, D] (the reference fused_rope layout, fused_rope kernel).
    cos/sin: [S, D] or pre-gathered [B*S, D] (position_ids path). The
    per-(b,s) angle rows broadcast across heads INSIDE the kernel, so the
    HBM traffic for angles is H-fold smaller than the activations.
    Differentiable (linear op; jax transposes the pallas call via its
    jvp/transpose of the underlying computation is not available — use
    the custom vjp below)."""
    if interpret is None:
        interpret = _interpret_default()
    B, S, H, D = x.shape
    rows = B * S
    x2 = x.reshape(rows, H, D)
    if cos.shape[0] != rows:
        cos = jnp.tile(cos.reshape(-1, D), (B, 1))
        sin = jnp.tile(sin.reshape(-1, D), (B, 1))
    br = min(block_rows, rows)
    while rows % br:
        br //= 2
    key = ("rope", rows, H, D, str(x.dtype), int(br), bool(interpret))
    fn = _JIT_CACHE.get(key)
    if fn is None:
        xspec = pl.BlockSpec((br, H, D), lambda i: (i, 0, 0))
        cspec = pl.BlockSpec((br, D), lambda i: (i, 0))

        def call(a, c, s):
            return pl.pallas_call(
                _rope_kernel,
                grid=(rows // br,),
                in_specs=[xspec, cspec, cspec],
                out_specs=xspec,
                out_shape=jax.ShapeDtypeStruct((rows, H, D), a.dtype),
                interpret=interpret,
            )(a, c, s)

        @jax.custom_vjp
        def roped(a, c, s):
            return call(a, c, s)

        def fwd(a, c, s):
            return call(a, c, s), (c, s)

        def bwd(res, g):
            c, s = res
            # transpose of the rotation: rotate by -theta (cos, -sin)
            return call(g, c, -s), None, None

        roped.defvjp(fwd, bwd)
        fn = jax.jit(roped)
        _JIT_CACHE[key] = fn
    return fn(x2, cos, sin).reshape(B, S, H, D)
