"""Fused elementwise Pallas kernels (reference paddle/phi/kernels/fusion/:
fused_adam_kernel.cu multi-tensor Adam, fused_rope, rms_norm fusions).

On TPU, XLA already fuses elementwise chains aggressively, so each kernel
here ships with a microbench against the XLA-fused baseline
(tests/test_pallas_fused.py asserts parity; .bench notes record measured
wins/losses). The kernels keep ONE HBM pass over every operand with
explicit VMEM tiling — the win over XLA appears when the compiler splits
the chain across fusions (large multi-tensor updates) or when layout
choices force relayouts (rope's interleaved pairs).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret_default() -> bool:
    try:
        return jax.devices()[0].platform.lower() == "cpu"
    except Exception:
        return True


# ------------------------------------------------------------ fused adamw

def _adamw_kernel(p_ref, g_ref, m_ref, v_ref, mst_ref, sc_ref,
                  p_out, m_out, v_out, mst_out):
    """One pass: read (p, g, m, v, master), write (p, m, v, master).
    sc_ref (SMEM) carries [lr, beta1, beta2, eps, wd, bc1, bc2]."""
    lr = sc_ref[0]
    b1 = sc_ref[1]
    b2 = sc_ref[2]
    eps = sc_ref[3]
    wd = sc_ref[4]
    bc1 = sc_ref[5]
    bc2 = sc_ref[6]
    g = g_ref[:].astype(jnp.float32)
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    mhat = m / bc1
    vhat = v / bc2
    mw = mst_ref[:]
    mw = mw - lr * (mhat / (jnp.sqrt(vhat) + eps) + wd * mw)
    p_out[:] = mw.astype(p_out.dtype)
    m_out[:] = m
    v_out[:] = v
    mst_out[:] = mw


def fused_adamw(param, grad, m, v, master, lr, beta1=0.9, beta2=0.999,
                eps=1e-8, weight_decay=0.01, step=1, block=None,
                interpret=None):
    """Decoupled-weight-decay Adam on FLAT arrays in one kernel pass
    (fused_adam_kernel.cu parity): param bf16/f32, master+moments f32.
    Returns (new_param, new_m, new_v, new_master)."""
    if interpret is None:
        interpret = _interpret_default()
    n = param.size
    flat = lambda a: a.reshape(-1)
    p1, g1, m1, v1, w1 = (flat(a) for a in (param, grad, m, v, master))
    blk = block or min(n, 1 << 17)
    # pad to a block multiple (lane-aligned)
    npad = -(-n // blk) * blk
    if npad != n:
        pad = lambda a: jnp.concatenate(
            [a, jnp.zeros(npad - n, a.dtype)])
        p1, g1, m1, v1, w1 = (pad(a) for a in (p1, g1, m1, v1, w1))
    t = jnp.float32(step)
    sc = jnp.stack([jnp.float32(lr), jnp.float32(beta1), jnp.float32(beta2),
                    jnp.float32(eps), jnp.float32(weight_decay),
                    1.0 - jnp.float32(beta1) ** t,
                    1.0 - jnp.float32(beta2) ** t])
    grid = (npad // blk,)
    spec = pl.BlockSpec((blk,), lambda i: (i,))
    po, mo, vo, wo = pl.pallas_call(
        _adamw_kernel,
        grid=grid,
        in_specs=[spec, spec, spec, spec, spec,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[spec, spec, spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct((npad,), param.dtype),
            jax.ShapeDtypeStruct((npad,), jnp.float32),
            jax.ShapeDtypeStruct((npad,), jnp.float32),
            jax.ShapeDtypeStruct((npad,), jnp.float32),
        ],
        interpret=interpret,
    )(p1, g1, m1, v1, w1, sc)
    unflat = lambda a, like: a[:n].reshape(param.shape).astype(like.dtype) \
        if a.dtype != like.dtype else a[:n].reshape(param.shape)
    return (po[:n].reshape(param.shape), mo[:n].reshape(param.shape),
            vo[:n].reshape(param.shape), wo[:n].reshape(param.shape))


# ----------------------------------------------- fused optimizer STEP
# Bitwise twins of the eager Optimizer update rules: unlike
# fused_adamw above (which fuses the decay into one multiply-add —
# fast, but a different rounding order), these kernels replicate the
# EXACT op sequence of optimizer/optimizers.py `_update_one` +
# `_apply_one`, so the fused step is provably a pure layout/fusion
# change — the bench gate asserts params AND moments bitwise equal to
# the eager path on f32 state. One kernel pass reads (p, g, m, v) and
# writes (p, m, v) with input_output_aliases pinning the update in
# place — none of the transpose/copy staging XLA inserts around the
# multi-op eager chain.

def _pad_flat(arrs, blk):
    n = arrs[0].size
    npad = -(-n // blk) * blk
    out = []
    for a in arrs:
        f = a.reshape(-1)
        if npad != n:
            f = jnp.concatenate([f, jnp.zeros(npad - n, f.dtype)])
        out.append(f)
    return out, n, npad


def _adamw_step_kernel(p_ref, g_ref, m_ref, v_ref, sc_ref,
                       p_out, m_out, v_out, *, apply_wd):
    """sc: [lr, b1, 1-b1, b2, 1-b2, eps, wd, bc1, bc2]. The 1-b* and
    bc* values are computed OUTSIDE exactly as the eager expressions
    compute them (python-f64 constants, runtime pow) — recomputing
    1-b1 here in f32 would round differently and break bitwise."""
    lr = sc_ref[0]
    b1 = sc_ref[1]
    om1 = sc_ref[2]
    b2 = sc_ref[3]
    om2 = sc_ref[4]
    eps = sc_ref[5]
    wd = sc_ref[6]
    bc1 = sc_ref[7]
    bc2 = sc_ref[8]
    g = g_ref[:]
    p = p_ref[:]
    m = b1 * m_ref[:] + om1 * g
    v = b2 * v_ref[:] + om2 * jnp.square(g)
    mhat = m / bc1
    vhat = v / bc2
    new_p = p - lr * mhat / (jnp.sqrt(vhat) + eps)
    if apply_wd:
        # decoupled decay against the PRE-update param, as a separate
        # subtract — the eager AdamW order
        new_p = new_p - lr * wd * p
    p_out[:] = new_p
    m_out[:] = m
    v_out[:] = v


def adamw_step_supported(work, grad) -> bool:
    """The bitwise-fused path serves f32 math only: f32 working param
    (plain f32, or the multi-precision master) and an f32 grad (the
    master path casts explicitly, matching eager). A bf16 grad without
    a master promotes through bf16 intermediates on the eager path —
    that rounding order is not worth replicating in-kernel, so it
    falls back."""
    return (work.dtype == jnp.float32 and grad.dtype == jnp.float32)


def fused_adamw_step(param, grad, m, v, lr, step, beta1=0.9,
                     beta2=0.999, eps=1e-8, weight_decay=0.0,
                     block=None, interpret=None):
    """One-pass eager-order AdamW: returns (new_param, new_m, new_v)
    BITWISE equal to `Adam._update_one` + decoupled decay on f32
    state. `lr`/`step` are traced scalars; betas/eps/wd python floats.
    `weight_decay=0.0` skips the decay subtract entirely (the eager
    `if wd and decay` branch)."""
    if interpret is None:
        interpret = _interpret_default()
    t = step.astype(jnp.float32)
    # eager-twin scalar staging: 1-b computed in python f64 (the eager
    # closure constant), bias corrections at runtime from the weak-f32
    # pow — identical HLO to `1 - b1 ** t`
    sc = jnp.stack([
        lr.astype(jnp.float32), jnp.float32(beta1),
        jnp.float32(1 - beta1), jnp.float32(beta2),
        jnp.float32(1 - beta2), jnp.float32(eps),
        jnp.float32(weight_decay),
        (1 - beta1 ** t).astype(jnp.float32),
        (1 - beta2 ** t).astype(jnp.float32)])
    blk = block or min(param.size, 1 << 17)
    flats, n, npad = _pad_flat([param, grad, m, v], blk)
    p1, g1, m1, v1 = flats
    grid = (npad // blk,)
    spec = pl.BlockSpec((blk,), lambda i: (i,))
    po, mo, vo = pl.pallas_call(
        functools.partial(_adamw_step_kernel,
                          apply_wd=bool(weight_decay)),
        grid=grid,
        in_specs=[spec, spec, spec, spec,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[spec, spec, spec],
        out_shape=[jax.ShapeDtypeStruct((npad,), jnp.float32)] * 3,
        # layout pinning: update in place — no staging copies
        input_output_aliases={0: 0, 2: 1, 3: 2},
        interpret=interpret,
    )(p1, g1, m1, v1, sc)
    shape = param.shape
    return (po[:n].reshape(shape), mo[:n].reshape(shape),
            vo[:n].reshape(shape))


def _momentum_step_kernel(p_ref, g_ref, v_ref, sc_ref, p_out, v_out,
                          *, nesterov, apply_wd):
    """sc: [lr, momentum, wd]. Eager-order Momentum (l2 decay folded
    into the grad BEFORE the velocity update, like `_apply_one`)."""
    lr = sc_ref[0]
    mom = sc_ref[1]
    wd = sc_ref[2]
    g = g_ref[:]
    p = p_ref[:]
    if apply_wd:
        g = g + wd * p
    v = mom * v_ref[:] + g
    if nesterov:
        new_p = p - lr * (g + mom * v)
    else:
        new_p = p - lr * v
    p_out[:] = new_p
    v_out[:] = v


def fused_momentum_step(param, grad, velocity, lr, momentum=0.9,
                        nesterov=False, weight_decay=0.0, block=None,
                        interpret=None):
    """One-pass eager-order (possibly Nesterov) momentum: bitwise
    equal to `Momentum._update_one` (+ the pre-update l2 fold) on f32
    state."""
    if interpret is None:
        interpret = _interpret_default()
    sc = jnp.stack([lr.astype(jnp.float32), jnp.float32(momentum),
                    jnp.float32(weight_decay)])
    blk = block or min(param.size, 1 << 17)
    flats, n, npad = _pad_flat([param, grad, velocity], blk)
    p1, g1, v1 = flats
    grid = (npad // blk,)
    spec = pl.BlockSpec((blk,), lambda i: (i,))
    po, vo = pl.pallas_call(
        functools.partial(_momentum_step_kernel,
                          nesterov=bool(nesterov),
                          apply_wd=bool(weight_decay)),
        grid=grid,
        in_specs=[spec, spec, spec,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[spec, spec],
        out_shape=[jax.ShapeDtypeStruct((npad,), jnp.float32)] * 2,
        input_output_aliases={0: 0, 2: 1},
        interpret=interpret,
    )(p1, g1, v1, sc)
    shape = param.shape
    return po[:n].reshape(shape), vo[:n].reshape(shape)


# ------------------------------------------------------------ fused rmsnorm

def _rmsnorm_fwd_kernel(x_ref, w_ref, o_ref, r_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    r = jax.lax.rsqrt(ms + eps)
    o_ref[:] = (x * r * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)
    r_ref[:] = jnp.broadcast_to(r, r_ref.shape)


def _rmsnorm_fwd(x, w, eps, block_rows, interpret):
    R, H = x.shape
    br = min(block_rows, R)
    while R % br:
        br //= 2
    grid = (R // br,)
    o, r = pl.pallas_call(
        functools.partial(_rmsnorm_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[pl.BlockSpec((br, H), lambda i: (i, 0)),
                  pl.BlockSpec((1, H), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((br, H), lambda i: (i, 0)),
                   pl.BlockSpec((br, 128), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((R, H), x.dtype),
                   jax.ShapeDtypeStruct((R, 128), jnp.float32)],
        interpret=interpret,
    )(x, w.reshape(1, H))
    return o, r[:, 0]


def _rmsnorm_bwd_kernel(x_ref, w_ref, r_ref, do_ref, dx_ref, dwp_ref):
    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    r = r_ref[:][:, :1]
    do = do_ref[:].astype(jnp.float32)
    xhat = x * r
    dy = do * w
    # d rms: dx = r * (dy - xhat * mean(dy * xhat))
    mean_term = jnp.mean(dy * xhat, axis=-1, keepdims=True)
    dx_ref[:] = (r * (dy - xhat * mean_term)).astype(dx_ref.dtype)
    # per-block dw partial, broadcast over an 8-row sublane tile
    dwp_ref[:] = jnp.broadcast_to(
        jnp.sum(do * xhat, axis=0, keepdims=True), dwp_ref.shape)


def _rmsnorm_bwd(x, w, r, do, block_rows, interpret):
    R, H = x.shape
    br = min(block_rows, R)
    while R % br:
        br //= 2
    grid = (R // br,)
    r2 = jnp.broadcast_to(r[:, None], (R, 128))
    dx, dw_part = pl.pallas_call(
        _rmsnorm_bwd_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((br, H), lambda i: (i, 0)),
                  pl.BlockSpec((1, H), lambda i: (0, 0)),
                  pl.BlockSpec((br, 128), lambda i: (i, 0)),
                  pl.BlockSpec((br, H), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((br, H), lambda i: (i, 0)),
                   pl.BlockSpec((8, H), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((R, H), x.dtype),
                   jax.ShapeDtypeStruct((R // br * 8, H), jnp.float32)],
        interpret=interpret,
    )(x, w.reshape(1, H), r2, do)
    return dx, dw_part[::8].sum(axis=0).astype(w.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _rmsnorm(x, w, eps, block_rows, interpret):
    o, _ = _rmsnorm_fwd(x, w, eps, block_rows, interpret)
    return o


def _rmsnorm_vjp_fwd(x, w, eps, block_rows, interpret):
    o, r = _rmsnorm_fwd(x, w, eps, block_rows, interpret)
    return o, (x, w, r)


def _rmsnorm_vjp_bwd(eps, block_rows, interpret, res, g):
    x, w, r = res
    dx, dw = _rmsnorm_bwd(x, w, r, g, block_rows, interpret)
    return dx, dw


_rmsnorm.defvjp(_rmsnorm_vjp_fwd, _rmsnorm_vjp_bwd)

_JIT_CACHE: dict = {}


def fused_rms_norm(x, weight, epsilon=1e-6, block_rows=512, interpret=None):
    """RMSNorm over the last dim in one pallas pass (fwd + custom bwd);
    any leading shape. Differentiable."""
    if interpret is None:
        interpret = _interpret_default()
    shape = x.shape
    H = shape[-1]
    key = ("rmsnorm", float(epsilon), int(block_rows), bool(interpret))
    fn = _JIT_CACHE.get(key)
    if fn is None:
        fn = jax.jit(lambda x2, w: _rmsnorm(x2, w, float(epsilon),
                                            int(block_rows),
                                            bool(interpret)))
        _JIT_CACHE[key] = fn
    return fn(x.reshape(-1, H), weight).reshape(shape)


# --------------------------------------------------------------- fused rope

def _rope_kernel(x_ref, cos_ref, sin_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)          # (rows, H, D)
    cos = cos_ref[:].astype(jnp.float32)[:, None, :]   # (rows, 1, D)
    sin = sin_ref[:].astype(jnp.float32)[:, None, :]
    D = x.shape[-1]
    x1 = x[..., : D // 2]
    x2 = x[..., D // 2:]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    o_ref[:] = (x * cos + rot * sin).astype(o_ref.dtype)


def fused_rope(x, cos, sin, block_rows=256, interpret=None):
    """Rotary embedding (half-split convention) in one pass over
    [B, S, H, D] (the reference fused_rope layout, fused_rope kernel).
    cos/sin: [S, D] or pre-gathered [B*S, D] (position_ids path). The
    per-(b,s) angle rows broadcast across heads INSIDE the kernel, so the
    HBM traffic for angles is H-fold smaller than the activations.
    Differentiable (linear op; jax transposes the pallas call via its
    jvp/transpose of the underlying computation is not available — use
    the custom vjp below)."""
    if interpret is None:
        interpret = _interpret_default()
    B, S, H, D = x.shape
    rows = B * S
    x2 = x.reshape(rows, H, D)
    if cos.shape[0] != rows:
        cos = jnp.tile(cos.reshape(-1, D), (B, 1))
        sin = jnp.tile(sin.reshape(-1, D), (B, 1))
    br = min(block_rows, rows)
    while rows % br:
        br //= 2
    key = ("rope", rows, H, D, str(x.dtype), int(br), bool(interpret))
    fn = _JIT_CACHE.get(key)
    if fn is None:
        xspec = pl.BlockSpec((br, H, D), lambda i: (i, 0, 0))
        cspec = pl.BlockSpec((br, D), lambda i: (i, 0))

        def call(a, c, s):
            return pl.pallas_call(
                _rope_kernel,
                grid=(rows // br,),
                in_specs=[xspec, cspec, cspec],
                out_specs=xspec,
                out_shape=jax.ShapeDtypeStruct((rows, H, D), a.dtype),
                interpret=interpret,
            )(a, c, s)

        @jax.custom_vjp
        def roped(a, c, s):
            return call(a, c, s)

        def fwd(a, c, s):
            return call(a, c, s), (c, s)

        def bwd(res, g):
            c, s = res
            # transpose of the rotation: rotate by -theta (cos, -sin)
            return call(g, c, -s), None, None

        roped.defvjp(fwd, bwd)
        fn = jax.jit(roped)
        _JIT_CACHE[key] = fn
    return fn(x2, cos, sin).reshape(B, S, H, D)
