"""Pallas fused LayerNorm (forward + backward) for TPU.

The reference fuses LN as a CUDA kernel (paddle/phi/kernels/gpu/
layer_norm_kernel.cu); XLA's lowering of the mean/var/normalize chain at
transformer shapes runs several VPU passes over the tile. This kernel
does the whole forward in ONE pass per row block, and the backward in
one pass that RECOMPUTES the row statistics from the saved input — so
the custom_vjp residuals are just (x, weight, bias): nothing extra to
save, which keeps it remat-policy-neutral. Measured 0.30 vs 0.44
ms/LN for XLA at [8192, 1024] bf16 fwd+bwd on v5e (~6 ms/step on the
GPT bench with 48 LNs + final).

dgamma/dbeta accumulate across row blocks in VMEM scratch (the grid is
sequential on a TensorCore), emitted by the last program — the same
pattern the flash kernels use for their stage accumulators.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


from .pallas_flash import _interpret_default

# keep the backward's working set (x, do, dx blocks in f32 + row stats)
# well under a core's VMEM: blk * H * 4B * 3 <= ~6 MiB
_VMEM_ROW_BUDGET = 512 * 1024


def _pick_block(n: int, h: int) -> int:
    cap = max(8, _VMEM_ROW_BUDGET // max(h, 1))
    for b in (512, 256, 128, 64, 32, 16, 8):
        if b <= cap and n % b == 0:
            return b
    return 0


def supported(shape) -> bool:
    """Last-axis LN over [*, H]: H lane-aligned, rows tileable within
    the VMEM budget."""
    if len(shape) < 2:
        return False
    h = shape[-1]
    n = 1
    for d in shape[:-1]:
        n *= d
    return h % 128 == 0 and h <= 8192 and _pick_block(n, h) >= 8


def _fwd_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    m = jnp.mean(x, axis=1, keepdims=True)
    xc = x - m
    v = jnp.mean(xc * xc, axis=1, keepdims=True)
    r = jax.lax.rsqrt(v + eps)
    o_ref[...] = (xc * r * g_ref[...].astype(jnp.float32)
                  + b_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _bwd_kernel(x_ref, g_ref, do_ref, dx_ref, dg_ref, db_ref,
                dg_acc, db_acc, *, eps, nblk):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dg_acc[...] = jnp.zeros_like(dg_acc)
        db_acc[...] = jnp.zeros_like(db_acc)

    x = x_ref[...].astype(jnp.float32)
    do = do_ref[...].astype(jnp.float32)
    m = jnp.mean(x, axis=1, keepdims=True)          # recompute stats
    xc = x - m
    v = jnp.mean(xc * xc, axis=1, keepdims=True)
    r = jax.lax.rsqrt(v + eps)
    xh = xc * r
    gf = g_ref[...].astype(jnp.float32)
    dg_acc[...] += jnp.sum(do * xh, axis=0)
    db_acc[...] += jnp.sum(do, axis=0)
    dxh = do * gf
    mean_dxh = jnp.mean(dxh, axis=1, keepdims=True)
    mean_dxh_xh = jnp.mean(dxh * xh, axis=1, keepdims=True)
    dx_ref[...] = ((dxh - mean_dxh - xh * mean_dxh_xh) * r
                   ).astype(dx_ref.dtype)

    @pl.when(i == nblk - 1)
    def _emit():
        dg_ref[...] = dg_acc[...]
        db_ref[...] = db_acc[...]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer_norm(x, weight, bias, eps=1e-5):
    """LN over the LAST axis of x [*, H] with affine weight/bias [H].
    Requires supported(x.shape); callers gate on that."""
    return _run_fwd(x, weight, bias, eps)


def _run_fwd(x, weight, bias, eps):
    shape = x.shape
    h = shape[-1]
    x2 = x.reshape(-1, h)
    n = x2.shape[0]
    blk = _pick_block(n, h)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(n // blk,),
        in_specs=[pl.BlockSpec((blk, h), lambda i: (i, 0)),
                  pl.BlockSpec((h,), lambda i: (0,)),
                  pl.BlockSpec((h,), lambda i: (0,))],
        out_specs=pl.BlockSpec((blk, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h), x.dtype),
        interpret=_interpret_default(),
    )(x2, weight, bias)
    return out.reshape(shape)


def _fwd_rule(x, weight, bias, eps):
    return _run_fwd(x, weight, bias, eps), (x, weight)


def _bwd_rule(eps, res, do):
    x, weight = res
    shape = x.shape
    h = shape[-1]
    x2 = x.reshape(-1, h)
    do2 = do.reshape(-1, h)
    n = x2.shape[0]
    blk = _pick_block(n, h)
    nblk = n // blk
    dx, dg, db = pl.pallas_call(
        functools.partial(_bwd_kernel, eps=eps, nblk=nblk),
        grid=(nblk,),
        in_specs=[pl.BlockSpec((blk, h), lambda i: (i, 0)),
                  pl.BlockSpec((h,), lambda i: (0,)),
                  pl.BlockSpec((blk, h), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((blk, h), lambda i: (i, 0)),
                   pl.BlockSpec((h,), lambda i: (0,)),
                   pl.BlockSpec((h,), lambda i: (0,))],
        out_shape=[jax.ShapeDtypeStruct((n, h), x.dtype),
                   jax.ShapeDtypeStruct((h,), jnp.float32),
                   jax.ShapeDtypeStruct((h,), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((h,), jnp.float32),
                        pltpu.VMEM((h,), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret_default(),
    )(x2, weight, do2)
    return (dx.reshape(shape), dg.astype(weight.dtype),
            db.astype(weight.dtype))


fused_layer_norm.defvjp(_fwd_rule, _bwd_rule)
