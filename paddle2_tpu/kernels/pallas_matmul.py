"""Low-precision Pallas matmul paths for the big GPT projections
(fused QKV, out_proj, MLP up/down, lm_head).

Two dtype families:

* **int8 weight-only** — weights ride as int8 with per-OUT-CHANNEL f32
  absmax scales (the ``quantization`` module's channel-wise observer
  convention; :func:`channel_absmax` here is the shared primitive the
  observers reduce with). The kernel streams the int8 weight tile into
  VMEM (HALF the HBM bytes of bf16 — decode and lm_head matmuls are
  weight-bandwidth-bound), dequantizes in-register, and runs the MXU in
  the activation dtype. Error is ANALYTICALLY bounded:
  per-element weight error <= s_j / (2*qmax) (round-to-nearest half
  step), so ``|y_ref - y_q|[i, j] <= ||x_i||_1 * s_j / (2*qmax)`` —
  :func:`weight_quant_error_bound` computes it and the bench gate
  asserts it holds AND is non-vacuous (a mis-scaled payload violates
  it).
* **int4 weight-only** (ISSUE 14 satellite, ROADMAP item 4) — the same
  machinery at ``quant_bits=4``: :func:`pack_int4` stores two weights
  per byte (QUARTER the bf16 HBM bytes — decode is weight-bandwidth
  bound, so this is the aggressive end of the same trade), and
  :func:`weight_quant_error_bound` generalizes unchanged — the bench
  gates the 4-bit bound both HOLDS (f64 reference) and is NON-VACUOUS
  (a 2-bit payload must violate it, and it must beat the trivial
  ``|y|`` bound).
* **int8 x int8** — both operands int8, int32 MXU accumulation (2x the
  bf16 rate on v5e), dequantized at the epilogue: the
  ``QuantedInferenceLinear`` full-int8 path as a Pallas kernel.
* **fp8-shaped** (:func:`fp8_matmul`) — where the jax build exposes
  ``float8_e4m3fn``, the same tiling with fp8 operand casts; gated by
  :func:`fp8_supported` and never chosen implicitly.

Dispatch: :func:`int8_weight_only_matmul` runs the Pallas kernel on TPU
for aligned shapes and falls back to the numerically-equivalent XLA
lowering elsewhere (CPU/CI, ragged shapes) — both produce the same
dequantized product, so the analytic bound gates BOTH lowerings.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_M = 256
DEFAULT_BLOCK_N = 256
DEFAULT_BLOCK_K = 512


def _interpret_default() -> bool:
    try:
        return jax.devices()[0].platform.lower() == "cpu"
    except Exception:
        return True


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform.lower() == "tpu"
    except Exception:
        return False


# ------------------------------------------------------------ primitives
def channel_absmax(arr, axis: int):
    """Per-channel absmax of ``arr`` along ``axis`` (reduced over every
    OTHER axis) — the one reduction the quantization observers, the
    weight-only packers, and the training-time fake-quant head all
    share, so their scales agree bitwise."""
    axis = axis % arr.ndim
    red = tuple(i for i in range(arr.ndim) if i != axis)
    return jnp.max(jnp.abs(arr), axis=red).astype(jnp.float32)


def quantize_channelwise(w, quant_bits: int = 8, axis: int = 1
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(w_int8, scale): symmetric per-channel absmax quantization of a
    weight along ``axis`` (out-channel for ``[in, out]`` Linear
    weights)."""
    qmax = float(2 ** (quant_bits - 1) - 1)
    scale = jnp.maximum(channel_absmax(w, axis), 1e-8)
    shape = [1] * w.ndim
    shape[axis % w.ndim] = -1
    s = scale.reshape(shape)
    w_q = jnp.clip(jnp.round(w.astype(jnp.float32) / s * qmax),
                   -qmax, qmax).astype(jnp.int8)
    return w_q, scale


def weight_quant_error_bound(x, w_scale, quant_bits: int = 8):
    """Analytic per-(row, out-channel) bound on the weight-only
    quantization error of ``x @ W``: each dequantized weight element is
    within ``s_j / (2*qmax)`` of the original (round-to-nearest), so
    the product error is bounded by the l1 norm of the activation row
    times that half-step. Returns ``[..., out]`` f32."""
    qmax = float(2 ** (quant_bits - 1) - 1)
    l1 = jnp.sum(jnp.abs(x.astype(jnp.float32)), axis=-1,
                 keepdims=True)
    return l1 * (w_scale.astype(jnp.float32) / (2.0 * qmax))


# ---------------------------------------------------------- int4 storage
def pack_int4(w_q):
    """Pack a ``[K, N]`` int4-valued int8 array (values in [-7, 7])
    into ``[K, N/2]`` uint8 nibbles (even column in the low nibble) —
    QUARTER the bf16 weight bytes in HBM. N must be even. The compute
    paths consume the unpacked int8 form (the MXU has no int4 lanes on
    this generation; the win is bandwidth, which is what decode and
    lm_head matmuls are bound by)."""
    w_q = jnp.asarray(w_q, jnp.int8)
    if w_q.shape[-1] % 2:
        raise ValueError("pack_int4 needs an even out-channel count")
    lo = (w_q[..., 0::2] & 0xF).astype(jnp.uint8)
    hi = (w_q[..., 1::2] & 0xF).astype(jnp.uint8)
    return lo | (hi << 4)


def unpack_int4(packed, n: int):
    """Inverse of :func:`pack_int4`: ``[K, N/2]`` uint8 -> ``[K, N]``
    sign-extended int8 (values in [-8, 7])."""
    packed = jnp.asarray(packed, jnp.uint8)
    lo = (packed & 0xF).astype(jnp.int8)
    hi = ((packed >> 4) & 0xF).astype(jnp.int8)

    def sext(v):
        return jnp.where(v >= 8, v - 16, v).astype(jnp.int8)

    out = jnp.stack([sext(lo), sext(hi)], axis=-1)
    return out.reshape(packed.shape[:-1] + (2 * packed.shape[-1],))[..., :n]


def int4_weight_only_matmul(x, w_packed, w_scale, bias=None,
                            block_m: int = DEFAULT_BLOCK_M,
                            block_n: int = DEFAULT_BLOCK_N,
                            block_k: int = DEFAULT_BLOCK_K,
                            interpret: Optional[bool] = None):
    """int4 weight-only ``x @ dequant(W)``: unpack the nibble payload
    in-register and run the shared weight-only path at
    ``quant_bits=4`` (the PR 10 error-bound machinery generalizes —
    ``weight_quant_error_bound(x, s, quant_bits=4)`` bounds THIS
    product, and the bench gates it non-vacuous). ``w_packed``:
    ``[K, N/2]`` uint8 from :func:`pack_int4`; ``w_scale``: ``[N]``."""
    n = 2 * w_packed.shape[-1]
    w_q = unpack_int4(w_packed, n)
    return int8_weight_only_matmul(
        x, w_q, w_scale, bias=bias, quant_bits=4, block_m=block_m,
        block_n=block_n, block_k=block_k, interpret=interpret)


# ------------------------------------------------- int8 weight-only kernel
def _wo_kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, qmax, k_steps):
    """Grid (M/bm, N/bn, K/bk): f32 VMEM accumulator, int8 weight tile
    dequantized in-register, per-out-channel scale applied once at the
    epilogue (the matmul is linear in the weight, so scaling the
    accumulated column equals scaling every tile)."""
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    acc_ref[...] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _epilogue():
        o_ref[...] = (acc_ref[...] * (s_ref[:] / qmax)).astype(
            o_ref.dtype)


def _wo_pallas(x2, w_int8, scale, qmax, out_dtype, bm, bn, bk,
               interpret):
    M, K = x2.shape
    N = w_int8.shape[1]
    grid = (M // bm, N // bn, K // bk)
    return pl.pallas_call(
        functools.partial(_wo_kernel, qmax=qmax, k_steps=grid[2]),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(x2, w_int8, scale.reshape(1, N))


def wo_supported(m: int, k: int, n: int, bm: int = DEFAULT_BLOCK_M,
                 bn: int = DEFAULT_BLOCK_N,
                 bk: int = DEFAULT_BLOCK_K) -> bool:
    """Pallas path needs block-aligned operands (the XLA fallback
    serves ragged shapes with identical numerics)."""
    bm, bn, bk = min(bm, m), min(bn, n), min(bk, k)
    return m % bm == 0 and k % bk == 0 and n % bn == 0


def int8_weight_only_matmul(x, w_int8, w_scale, bias=None,
                            quant_bits: int = 8,
                            block_m: int = DEFAULT_BLOCK_M,
                            block_n: int = DEFAULT_BLOCK_N,
                            block_k: int = DEFAULT_BLOCK_K,
                            interpret: Optional[bool] = None):
    """``x @ dequant(w_int8)`` with per-out-channel scales: the Pallas
    weight-only kernel on TPU for aligned shapes, the equivalent XLA
    dequant-matmul elsewhere. ``x``: ``[..., K]`` float; ``w_int8``:
    ``[K, N]``; ``w_scale``: ``[N]``."""
    qmax = float(2 ** (quant_bits - 1) - 1)
    lead = x.shape[:-1]
    K = x.shape[-1]
    N = w_int8.shape[1]
    m = 1
    for d in lead:
        m *= int(d)
    aligned = wo_supported(m, K, N, block_m, block_n, block_k)
    use_pallas = aligned and (interpret is True or _on_tpu())
    if use_pallas:
        x2 = x.reshape(m, K)
        # with a bias the kernel keeps its epilogue in f32 so the bias
        # folds in BEFORE the single output cast — the same rounding
        # order as the XLA fallback below (casting first would make
        # the two lowerings diverge at the last ulp for bf16)
        out_dtype = jnp.float32 if bias is not None else x.dtype
        out = _wo_pallas(x2, w_int8, jnp.asarray(w_scale, jnp.float32),
                         qmax, out_dtype, min(block_m, m),
                         min(block_n, N), min(block_k, K),
                         bool(interpret) if interpret is not None
                         else _interpret_default())
        out = out.reshape(lead + (N,))
        if bias is not None:
            out = (out + bias).astype(x.dtype)
        return out
    w = w_int8.astype(jnp.float32) * (
        jnp.asarray(w_scale, jnp.float32) / qmax)
    out = jax.lax.dot_general(
        x.astype(jnp.float32), w,
        (((x.ndim - 1,), (0,)), ((), ())))
    if bias is not None:
        # bias folds in at f32 BEFORE the output cast — the exact
        # order of the pre-kernel WeightOnlyLinear lowering
        out = out + bias
    return out.astype(x.dtype)


# --------------------------------------------------- int8 x int8 kernel
def _i8i8_kernel(x_ref, w_ref, o_ref, acc_ref, *, k_steps):
    """int8 x int8 -> int32 MXU accumulation (v5e runs this at 2x the
    bf16 rate); dequant happens OUTSIDE (caller owns both scales)."""
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
    acc_ref[...] += jax.lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _epilogue():
        o_ref[...] = acc_ref[...]


def int8_matmul(x_int8, w_int8,
                block_m: int = DEFAULT_BLOCK_M,
                block_n: int = DEFAULT_BLOCK_N,
                block_k: int = DEFAULT_BLOCK_K,
                interpret: Optional[bool] = None):
    """Full-int8 ``[M, K] @ [K, N] -> int32``: the Pallas twin of
    ``QuantedInferenceLinear``'s dot (TPU, aligned), XLA
    ``dot_general`` with int32 accumulation elsewhere."""
    M, K = x_int8.shape
    N = w_int8.shape[1]
    aligned = wo_supported(M, K, N, block_m, block_n, block_k)
    use_pallas = aligned and (interpret is True or _on_tpu())
    if not use_pallas:
        return jax.lax.dot_general(
            x_int8, w_int8, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
    bm, bn, bk = min(block_m, M), min(block_n, N), min(block_k, K)
    grid = (M // bm, N // bn, K // bk)
    return pl.pallas_call(
        functools.partial(_i8i8_kernel, k_steps=grid[2]),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), jnp.int32),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.int32)],
        interpret=bool(interpret) if interpret is not None
        else _interpret_default(),
    )(x_int8, w_int8)


# ------------------------------------------------------- collective matmul
# Tensor-parallel projections spend their ICI time on the tp all-gather
# that feeds (or follows) the matmul. A collective matmul decomposes the
# gather into CHUNK-granular transfers interleaved with chunk-granular
# MXU work, so each transfer rides under a dot it is independent of —
# the latency-hiding scheduler (flags.apply_multichip_xla_env) then
# hides the ICI time inside the MXU time instead of serializing
# gather -> matmul. Two standard forms, both PURE SCHEDULE SHAPES
# (bitwise identical to the unfused gather-then-matmul, gated on the
# virtual mesh):
#
# * :func:`allgather_matmul` — input form (sequence-parallel Megatron):
#   ``all_gather(x, tp) @ w`` as a ring; each step runs the chunk it
#   holds through the dot while ``ppermute`` brings the next chunk in.
# * :func:`matmul_allgather` — EPILOGUE form (column-parallel output
#   re-replication): ``all_gather(x @ w_shard, tp)`` with the gather
#   issued per OUTPUT TILE from the epilogue, so tile t's wire time
#   overlaps tile t+1's dot.
#
# The per-chunk dot is pluggable (``matmul_fn``): the int8/int4
# weight-only Pallas kernels above slot straight in, composing the
# PR 10 quantized paths with the collective schedule. Cost accounting
# goes through :func:`collective_matmul_traffic`: the gather's wire
# bytes enter the model marked OVERLAPPABLE, which is exactly what the
# cost model's exposed-vs-hidden overlap split prices.


def _resolve_axis_size(axis_name, axis_size: Optional[int]) -> int:
    if axis_size is not None:
        return int(axis_size)
    from ..distributed import mesh as _mesh  # lazy: avoid import cycle
    return _mesh.traced_axis_size(axis_name)


def allgather_matmul(x_shard, w, axis_name: str,
                     axis_size: Optional[int] = None,
                     matmul_fn=None):
    """Ring collective matmul of the INPUT all-gather (shard_map
    context): computes ``all_gather(x_shard, axis) @ w`` — ``x_shard``
    is this rank's ``[rows/tp, K]`` slice — as ``tp`` chunk dots, each
    independent of the in-flight ``ppermute`` bringing the next chunk,
    so the gather's ICI time hides inside MXU time. Bitwise identical
    to the unfused path: every output row block is produced by the
    same-shaped dot on the same values, and the ring only moves data.
    ``matmul_fn(chunk, w) -> [rows/tp, N]`` swaps the per-chunk dot
    (e.g. a weight-only Pallas kernel); default is a plain ``@``."""
    n = _resolve_axis_size(axis_name, axis_size)
    dot = matmul_fn if matmul_fn is not None else (lambda c, ww: c @ ww)
    if n == 1:
        return dot(x_shard, w)
    r = jax.lax.axis_index(axis_name)
    rows = x_shard.shape[0]
    first = dot(x_shard, w)
    out = jnp.zeros((n * rows,) + first.shape[1:], first.dtype)
    out = jax.lax.dynamic_update_slice_in_dim(out, first, r * rows, 0)
    # descending ring: after k hops this rank holds rank (r + k) % n's
    # original shard
    perm = [(i, (i - 1) % n) for i in range(n)]
    cur = x_shard
    for step in range(1, n):
        cur = jax.lax.ppermute(cur, axis_name, perm)
        src = (r + step) % n
        y = dot(cur, w)
        out = jax.lax.dynamic_update_slice_in_dim(out, y, src * rows, 0)
    return out


def matmul_allgather(x, w_shard, axis_name: str,
                     axis_size: Optional[int] = None,
                     tiles: int = 1, matmul_fn=None):
    """Column-parallel matmul with the tp all-gather of the OUTPUT
    fused into the epilogue: computes
    ``all_gather(x @ w_shard, axis)`` (rank-major column blocks,
    ``[..., tp * N_shard]``) but issues the gather per output TILE —
    ``tiles`` column tiles per rank, each gathered as soon as its dot
    finishes, so tile t's wire time overlaps tile t+1's MXU work.
    Bitwise identical to the unfused gather: column tiles of a dot are
    independent K-reductions and the gather only places blocks. (Keep
    tiles MODERATE — a degenerate 1-wide column tile can change the
    XLA CPU dot's reduction grouping by ~1 ulp, the same effect PR 9
    pinned for gemm row counts; the acceptance tests run 1/2/4 tiles.)
    ``matmul_fn(x, w_tile) -> [..., tile]`` swaps the per-tile dot."""
    n = _resolve_axis_size(axis_name, axis_size)
    dot = matmul_fn if matmul_fn is not None else (lambda xx, ww: xx @ ww)
    nl = w_shard.shape[-1]
    t = max(1, min(int(tiles), nl))
    if nl % t:
        raise ValueError(
            f"matmul_allgather: {t} tiles must divide the local "
            f"out-channel count {nl}")
    bn = nl // t
    y0 = dot(x, w_shard[..., :bn])
    out = jnp.zeros(y0.shape[:-1] + (n * nl,), y0.dtype)
    for ti in range(t):
        y_t = y0 if ti == 0 else dot(
            x, w_shard[..., ti * bn:(ti + 1) * bn])
        if n == 1:
            g = y_t[None]
        else:
            # leading rank dim [n, ..., bn]: rank r's tile block
            g = jax.lax.all_gather(y_t, axis_name)
        for rank in range(n):
            out = jax.lax.dynamic_update_slice_in_dim(
                out, g[rank], rank * nl + ti * bn, out.ndim - 1)
    return out


def collective_matmul_traffic(payload_bytes: float, tp: int,
                              axes, traffic=None):
    """Price one collective matmul's gather into a
    :class:`~paddle2_tpu.observability.cost_model.CollectiveTraffic`
    (created if not given): the all-gather's wire bytes enter the model
    marked OVERLAPPABLE — hidden under the step's MXU time up to the
    compute budget by the cost model's exposed-vs-hidden overlap split,
    which is the whole point of fusing the gather into the matmul. The
    unfused comparison prices the same bytes non-overlappable."""
    from ..observability.cost_model import CollectiveTraffic
    t = traffic if traffic is not None else CollectiveTraffic()
    t.add("all_gather", float(payload_bytes), axes=tuple(axes),
          group_size=int(tp), overlappable=True)
    return t


# ------------------------------------------------------------- fp8-shaped
def fp8_supported() -> bool:
    """True when this jax build carries the fp8 dtypes (the kernels are
    SHAPE-compatible with fp8 — actual fp8 MXU rate needs v5p+)."""
    return hasattr(jnp, "float8_e4m3fn")


def fp8_matmul(x, w, interpret: Optional[bool] = None):
    """fp8-shaped matmul: both operands cast to ``float8_e4m3fn``,
    accumulated in f32. Opt-in only (caller owns the accuracy story);
    raises where the dtype does not exist."""
    if not fp8_supported():
        raise NotImplementedError(
            "fp8_matmul: this jax build has no float8_e4m3fn dtype")
    f8 = jnp.float8_e4m3fn
    out = jax.lax.dot_general(
        x.astype(f8), w.astype(f8),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return out.astype(x.dtype)


__all__ = ["channel_absmax", "quantize_channelwise",
           "weight_quant_error_bound", "int8_weight_only_matmul",
           "int4_weight_only_matmul", "pack_int4", "unpack_int4",
           "int8_matmul", "fp8_matmul", "fp8_supported", "wo_supported",
           "allgather_matmul", "matmul_allgather",
           "collective_matmul_traffic",
           "DEFAULT_BLOCK_M", "DEFAULT_BLOCK_N", "DEFAULT_BLOCK_K"]
