"""paddle.metric (reference python/paddle/metric/metrics.py; independent
numpy-accumulator implementation — metrics are host-side bookkeeping, so
they live in numpy and never trace into XLA programs)."""

from .metrics import Metric, Accuracy, Precision, Recall, Auc, accuracy

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]
