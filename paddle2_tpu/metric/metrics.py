"""Metric classes (reference python/paddle/metric/metrics.py:79 Metric,
:194 Accuracy, :371 Precision, :476 Recall, :576 Auc)."""

from __future__ import annotations

import abc
from typing import List, Sequence, Union

import numpy as np


def _np(x):
    from ..framework.tensor import Tensor
    if isinstance(x, Tensor):
        return np.asarray(x.numpy())
    return np.asarray(x)


class Metric(abc.ABC):
    """metrics.py:79 contract: reset / update / accumulate / name /
    compute (optional preprocessing that runs with the network outputs)."""

    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self):
        ...

    @abc.abstractmethod
    def update(self, *args):
        ...

    @abc.abstractmethod
    def accumulate(self):
        ...

    @abc.abstractmethod
    def name(self):
        ...

    def compute(self, *args):
        return args


class Accuracy(Metric):
    """Top-k accuracy (metrics.py:194)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = _np(pred)
        label = _np(label)
        idx = np.argsort(-pred, axis=-1)[..., :self.maxk]
        if label.ndim == pred.ndim:
            if label.shape[-1] == pred.shape[-1] and label.shape[-1] != 1:
                label = label.argmax(axis=-1)  # one-hot / soft labels
            else:  # [N, 1] index labels (metrics.py:285 guard)
                label = label[..., 0]
        correct = (idx == label[..., None]).astype("float32")
        return correct

    def update(self, correct, *args):
        correct = _np(correct)
        num = correct.shape[0]
        accs = []
        for k in self.topk:
            c = correct[..., :k].sum(-1).mean()
            accs.append(float(c))
        self.total = [t + float(correct[..., :k].sum()) for t, k in
                      zip(self.total, self.topk)]
        self.count += num
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = 0

    def accumulate(self):
        res = [t / max(self.count, 1) for t in self.total]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision over 0/1 predictions (metrics.py:371)."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype("int32").reshape(-1)
        labels = _np(labels).astype("int32").reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall (metrics.py:476)."""

    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype("int32").reshape(-1)
        labels = _np(labels).astype("int32").reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via threshold-bucketed confusion counts (metrics.py:576)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        if preds.ndim == 2:  # [N, 2] class probabilities -> P(class 1)
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        labels = _np(labels).reshape(-1)
        buckets = np.clip((preds * self.num_thresholds).astype("int64"), 0,
                          self.num_thresholds)
        pos = buckets[labels > 0.5]
        neg = buckets[labels <= 0.5]
        np.add.at(self._stat_pos, pos, 1)
        np.add.at(self._stat_neg, neg, 1)

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, "int64")
        self._stat_neg = np.zeros(self.num_thresholds + 1, "int64")

    def accumulate(self):
        # integrate TPR over FPR from the highest threshold down
        tot_pos = tot_neg = 0.0
        area = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            area += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return float(area / (tot_pos * tot_neg))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (metrics.py:859)."""
    from ..framework.tensor import Tensor
    import jax.numpy as jnp
    pred = _np(input)
    lab = _np(label)
    idx = np.argsort(-pred, axis=-1)[..., :k]
    if lab.ndim == pred.ndim:
        lab = lab.argmax(axis=-1) if lab.shape[-1] == pred.shape[-1] \
            else lab.reshape(lab.shape[:-1])
    acc = (idx == lab.reshape(lab.shape[0], -1)[:, :1]).any(-1).mean()
    return Tensor(jnp.asarray(np.float32(acc)))
