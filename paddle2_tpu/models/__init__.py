"""Model zoo: flagship architectures matching BASELINE.json configs."""

from .gpt import (GPTConfig, GPTModel, GPTForCausalLM, gpt3_1p3b, gpt_small,
                  gpt_tiny)

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "gpt3_1p3b",
           "gpt_small", "gpt_tiny"]
