"""Model zoo: flagship architectures matching BASELINE.json configs."""

from .gpt import (GPTConfig, GPTModel, GPTForCausalLM, gpt3_1p3b, gpt_small,
                  gpt_tiny)
from .ernie import (ErnieConfig, ErnieModel, ErnieForSequenceClassification,
                    ernie3_base, ernie_tiny)

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "gpt3_1p3b",
           "gpt_small", "gpt_tiny", "ErnieConfig", "ErnieModel",
           "ErnieForSequenceClassification", "ernie3_base", "ernie_tiny"]
