"""Shared scan-over-homogeneous-layers machinery (gpt/ernie model zoo).

XLA compiles ONE layer body instead of num_layers copies — HLO size and
compile time stop growing with depth (a 24-layer GPT-2-medium compile
dropped from >25 min to under a minute on v5e). Per-layer weights stack
into a leading layer axis at trace time; the runtime pays one stack copy
per step for a depth-independent compile.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor


def scan_layer_stack(layers: Sequence, x: Tensor,
                     wrap_body: Optional[Callable] = None):
    """Run a homogeneous layer stack as one lax.scan.

    `wrap_body` optionally transforms the scan body (e.g. jax.checkpoint
    with a remat policy). Returns the output Tensor, or None when the
    stack is not homogeneous (caller falls back to the Python loop).
    """
    tmpl = layers[0]
    tmpl_params = dict(tmpl.named_parameters())
    names = sorted(tmpl_params)
    for layer in layers:
        if sorted(n for n, _ in layer.named_parameters()) != names:
            return None
    stacked = {n: jnp.stack([dict(layer.named_parameters())[n]._data
                             for layer in layers]) for n in names}

    def body(carry, layer_params):
        originals = {n: tmpl_params[n]._data for n in names}
        for n in names:
            tmpl_params[n]._data = layer_params[n]
        try:
            out = tmpl(Tensor(carry))
        finally:
            for n in names:
                tmpl_params[n]._data = originals[n]
        return out._data, None

    if wrap_body is not None:
        body = wrap_body(body)
    final, _ = jax.lax.scan(body, x._data, stacked)
    return Tensor(final, stop_gradient=x.stop_gradient)
