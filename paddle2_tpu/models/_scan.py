"""Shared scan-over-homogeneous-layers machinery (gpt/ernie model zoo).

XLA compiles ONE layer body instead of num_layers copies — HLO size and
compile time stop growing with depth (a 24-layer GPT-2-medium compile
dropped from >25 min to under a minute on v5e). Per-layer weights stack
into a leading layer axis at trace time; the runtime pays one stack copy
per step for a depth-independent compile.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor


def _layer_base():
    from ..nn.layer.layers import Layer
    return (Layer,)


def _poison_for_grad(out):
    """Mark eager slice-path outputs so a backward that reaches them
    RAISES: grads through the rebound template cannot reach the stacked
    leaves, and a plain detach would let downstream trainable params
    (e.g. a tied LM head) re-attach and train on silently-partial
    grads. Pure inference (no backward) pays nothing."""
    from ..framework import core
    if not core.is_grad_enabled():
        return out

    def wrap(t):
        if not isinstance(t, Tensor):
            return t
        from ..autograd.tape import GradNode

        def boom(_cts):
            raise RuntimeError(
                "stacked_blocks: a backward pass reached the output of "
                "the eager slice path — gradients cannot flow to the "
                "stacked leaves here; run the forward under "
                "jit.to_static / jit.train_step (or no_grad if you did "
                "not want gradients)")
        nt = Tensor(t._data, stop_gradient=False)
        nt._grad_node = GradNode(
            "stacked_poison", boom, [],
            [(tuple(t._data.shape), t._data.dtype)])
        nt._output_index = 0
        return nt

    if isinstance(out, tuple):
        return tuple(wrap(t) for t in out)
    if isinstance(out, list):
        return [wrap(t) for t in out]
    return wrap(out)


class StackedLayerStack(*_layer_base()):
    """Homogeneous block stack whose parameters LIVE stacked: one
    ``[L, ...]`` Parameter per template leaf, consumed by ``lax.scan``
    directly.

    Why: ``scan_layer_stack`` stacks L separate per-block Parameters at
    trace time, which the compiled step pays for EVERY step — a chain of
    dynamic-update-slice fusions assembling the [L, ...] operands (and
    the transpose slicing the stacked grads back apart). At
    GPT-2-medium scale that is ~GBs of pure HBM traffic per step,
    measured as the bulk of the in-framework vs bare-JAX layer-time gap
    on v5e (r5). Storing the stack as the canonical Parameter removes
    it: the optimizer updates the stacked leaves in place and the scan
    reads them with zero data movement.
    """

    def __init__(self, blocks: Sequence):
        super().__init__()
        import jax.numpy as jnp
        from ..framework.tensor import Parameter
        tmpl = blocks[0]
        self._template = tmpl            # registered sublayer: its own
        # per-block params are REPLACED below by the stacked leaves
        names = sorted(n for n, _ in tmpl.named_parameters())
        self.n_layers = len(blocks)
        self._names = names
        per = [dict(b.named_parameters()) for b in blocks]
        for n in names:
            stackedv = jnp.stack([per[i][n]._data
                                  for i in range(len(blocks))])
            src = per[0][n]._data
            src_sharding = getattr(src, "sharding", None)
            if src_sharding is not None \
                    and getattr(src_sharding, "spec", None) is not None \
                    and len(getattr(src_sharding, "device_set", ())) > 1:
                # TP-sharded source params (mp_layers): keep the shard
                # spec on the stacked leaf (layer axis replicated) —
                # jnp.stack would otherwise silently re-place
                import jax
                from jax.sharding import NamedSharding, PartitionSpec
                spec = tuple(src_sharding.spec)
                spec = spec + (None,) * (src.ndim - len(spec))
                stackedv = jax.device_put(
                    stackedv, NamedSharding(src_sharding.mesh,
                                            PartitionSpec(None, *spec)))
            p = Parameter(stackedv,
                          name="stacked_" + n.replace(".", "__"))
            # carry regularization/clip attrs from the template leaf
            # (homogeneous per name across blocks, so the template's
            # attrs are the right ones — e.g. apply_decay_param_fun
            # name-matching sees the stacked_<name> leaf name)
            for attr in ("need_clip", "no_weight_decay"):
                if hasattr(per[0][n], attr):
                    setattr(p, attr, getattr(per[0][n], attr))
            self.add_parameter("stacked_" + n.replace(".", "__"), p)
        # the template's own per-block Parameters must NOT appear in
        # named_parameters (they would double-count / double-train):
        # drop them from its registry; forward rebinds their _data from
        # the stacked leaves each call.
        self._tmpl_params = {n: per[0][n] for n in names}
        self._detached = {}
        self._detach_template()

    def _detach_template(self):
        # remove template params from its (and sublayers') registries so
        # _collect_state / optimizers see ONLY the stacked leaves —
        # rebound as PLAIN instance attributes so `self.weight` etc.
        # still resolve inside the template's forward
        stack = [self._template]
        while stack:
            layer = stack.pop()
            for k in list(layer._parameters):
                p = layer._parameters.pop(k)
                self._detached[(id(layer), k)] = p
                object.__setattr__(layer, k, p)
            stack.extend(layer._sub_layers.values())

    def stacked_leaf(self, name: str):
        return getattr(self, "stacked_" + name.replace(".", "__"))

    def _rebind(self, leaf_arrays):
        originals = {n: self._tmpl_params[n]._data for n in self._names}
        for n, a in zip(self._names, leaf_arrays):
            self._tmpl_params[n]._data = a
        return originals

    def _restore(self, originals):
        for n, a in originals.items():
            self._tmpl_params[n]._data = a

    def forward(self, x: Tensor, wrap_body: Optional[Callable] = None,
                allow_scan: bool = True):
        import jax
        from ..framework import core
        tracing = isinstance(x._data, jax.core.Tracer)
        stacked = [self.stacked_leaf(n)._data for n in self._names]
        if tracing and allow_scan:
            def body(carry, leaf_arrays):
                originals = self._rebind(leaf_arrays)
                try:
                    out = self._template(Tensor(carry))
                finally:
                    self._restore(originals)
                return out._data, None
            if wrap_body is not None:
                body = wrap_body(body)
            final, _ = jax.lax.scan(body, x._data, stacked)
            return Tensor(final, stop_gradient=x.stop_gradient)
        if tracing:
            # traced but scan disallowed (e.g. dropout needs a DISTINCT
            # rng stream per layer — a scan body's trace-time key would
            # reuse ONE mask for all L layers): unrolled loop over
            # slices; grads still flow to the stacked leaves
            out = x
            for i in range(self.n_layers):
                originals = self._rebind([s[i] for s in stacked])
                try:
                    out = self._template(out)
                finally:
                    self._restore(originals)
            return out
        # eager: python loop over layer slices. Reads are device views;
        # grads cannot route back to the stacked leaves through the
        # rebound template. Training mode rejects up front; otherwise
        # the loop runs under no_grad and the output is POISONED: a
        # later backward that reaches it raises instead of silently
        # producing partial grads (e.g. head-only paths re-attaching
        # after a plain detach).
        if self._template.training and core.is_grad_enabled():
            raise RuntimeError(
                "stacked_blocks: eager differentiable execution is not "
                "supported — run under jit.to_static / jit.train_step, "
                "or use no_grad for inference (set stacked_blocks=False "
                "for eager training)")
        out = x
        with core.no_grad():
            for i in range(self.n_layers):
                originals = self._rebind([s[i] for s in stacked])
                try:
                    out = self._template(out)
                finally:
                    self._restore(originals)
        return _poison_for_grad(out)

    def layer_slice_call(self, i: int, x, **kwargs):
        """Run block i on x (decode/cache/attn-bias paths). Traced
        execution differentiates through the slices; EAGER execution
        runs under no_grad with a poisoned output — grads cannot route
        back to the stacked leaves through the rebound template, and a
        backward that reaches the output must fail loudly rather than
        silently dropping them."""
        import jax
        from ..framework import core
        data = getattr(x, "_data", x)
        tracing = isinstance(data, jax.core.Tracer)
        if not tracing and self._template.training \
                and core.is_grad_enabled():
            raise RuntimeError(
                "stacked_blocks: eager differentiable execution is not "
                "supported — run under jit.to_static / jit.train_step, "
                "or use no_grad for inference")
        stacked = [self.stacked_leaf(n)._data for n in self._names]
        originals = self._rebind([s[i] for s in stacked])
        try:
            if tracing:
                return self._template(x, **kwargs)
            with core.no_grad():
                out = self._template(x, **kwargs)
            return _poison_for_grad(out)
        finally:
            self._restore(originals)


def scan_layer_stack(layers: Sequence, x: Tensor,
                     wrap_body: Optional[Callable] = None):
    """Run a homogeneous layer stack as one lax.scan.

    `wrap_body` optionally transforms the scan body (e.g. jax.checkpoint
    with a remat policy). Returns the output Tensor, or None when the
    stack is not homogeneous (caller falls back to the Python loop).
    """
    tmpl = layers[0]
    tmpl_params = dict(tmpl.named_parameters())
    names = sorted(tmpl_params)
    for layer in layers:
        if sorted(n for n, _ in layer.named_parameters()) != names:
            return None
    stacked = {n: jnp.stack([dict(layer.named_parameters())[n]._data
                             for layer in layers]) for n in names}

    def body(carry, layer_params):
        originals = {n: tmpl_params[n]._data for n in names}
        for n in names:
            tmpl_params[n]._data = layer_params[n]
        try:
            out = tmpl(Tensor(carry))
        finally:
            for n in names:
                tmpl_params[n]._data = originals[n]
        return out._data, None

    if wrap_body is not None:
        body = wrap_body(body)
    final, _ = jax.lax.scan(body, x._data, stacked)
    return Tensor(final, stop_gradient=x.stop_gradient)
