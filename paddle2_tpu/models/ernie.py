"""ERNIE/BERT-style encoder family (BASELINE config 2: "ERNIE-3.0-base
SST-2 fine-tune"; the reference zoo lives in PaddleNLP — structure follows
ernie/modeling.py: word+position+token-type embeddings, post-LN encoder,
pooler, task heads).

TPU-first like models/gpt.py: the homogeneous encoder stack compiles as
ONE lax.scan body (depth-independent compile), attention routes through
the kernel selector (pallas flash on TPU), and the whole fine-tune step
runs under jit.train_step.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from .. import nn
from ..nn import functional as F
from ..kernels.attention import scaled_dot_product_attention


@dataclass
class ErnieConfig:
    vocab_size: int = 40000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None      # default 4*hidden
    max_position_embeddings: int = 2048
    type_vocab_size: int = 4
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-12
    num_classes: int = 2
    use_scan: bool = True
    # [L, ...] stacked parameter storage for the encoder stack (see
    # GPTConfig.stacked_blocks / models/_scan.py StackedLayerStack):
    # removes the per-step restack of the scan operands. Per-layer
    # sublayers stop being addressable; eager training requires jit.
    stacked_blocks: bool = False

    @property
    def ffn_size(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def _attr(std):
    return nn.ParamAttr(initializer=nn.initializer.Normal(mean=0.0, std=std))


class ErnieSelfAttention(nn.Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        h = cfg.hidden_size
        self.cfg = cfg
        self.qkv = nn.Linear(h, 3 * h, weight_attr=_attr(cfg.initializer_range))
        self.out = nn.Linear(h, h, weight_attr=_attr(cfg.initializer_range))

    def forward(self, x, attn_bias=None):
        cfg = self.cfg
        b, s, h = x.shape
        qkv = self.qkv(x).reshape([b, s, 3, cfg.num_heads, cfg.head_dim])
        q, k, v = qkv.unbind(axis=2)
        o = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_bias, is_causal=False,
            dropout_p=cfg.attention_dropout_prob, training=self.training)
        return self.out(o.reshape([b, s, h]))


class ErnieLayer(nn.Layer):
    """Post-LN encoder block (BERT/ERNIE convention)."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        eps = cfg.layer_norm_epsilon
        self.attn = ErnieSelfAttention(cfg)
        self.ln_1 = nn.LayerNorm(cfg.hidden_size, epsilon=eps)
        self.up = nn.Linear(cfg.hidden_size, cfg.ffn_size,
                            weight_attr=_attr(cfg.initializer_range))
        self.down = nn.Linear(cfg.ffn_size, cfg.hidden_size,
                              weight_attr=_attr(cfg.initializer_range))
        self.ln_2 = nn.LayerNorm(cfg.hidden_size, epsilon=eps)
        self.drop = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, x, attn_bias=None):
        x = self.ln_1(x + self.drop(self.attn(x, attn_bias)))
        x = self.ln_2(x + self.drop(self.down(F.gelu(self.up(x)))))
        return x


class ErnieModel(nn.Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        std = cfg.initializer_range
        h = cfg.hidden_size
        self.word_emb = nn.Embedding(cfg.vocab_size, h, weight_attr=_attr(std))
        self.pos_emb = nn.Embedding(cfg.max_position_embeddings, h,
                                    weight_attr=_attr(std))
        self.type_emb = nn.Embedding(cfg.type_vocab_size, h,
                                     weight_attr=_attr(std))
        self.emb_ln = nn.LayerNorm(h, epsilon=cfg.layer_norm_epsilon)
        self.drop = nn.Dropout(cfg.hidden_dropout_prob)
        blocks = [ErnieLayer(cfg) for _ in range(cfg.num_layers)]
        if cfg.stacked_blocks:
            from ._scan import StackedLayerStack
            self.layers = StackedLayerStack(blocks)
        else:
            self.layers = nn.LayerList(blocks)
        self.pooler = nn.Linear(h, h, weight_attr=_attr(std))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        b, s = input_ids.shape
        pos = Tensor(jnp.arange(s, dtype=jnp.int32)[None, :])
        x = self.word_emb(input_ids) + self.pos_emb(pos)
        if token_type_ids is not None:
            x = x + self.type_emb(token_type_ids)
        x = self.drop(self.emb_ln(x))
        attn_bias = None
        if attention_mask is not None:
            m = attention_mask._data if isinstance(attention_mask, Tensor) \
                else jnp.asarray(attention_mask)
            # finite min in the ACTIVATION dtype: f32-min cast to bf16
            # overflows to -inf, which NaNs fully-masked softmax rows
            neg = jnp.finfo(jnp.result_type(x._data.dtype,
                                            jnp.float32)
                            if not jnp.issubdtype(x._data.dtype,
                                                  jnp.inexact)
                            else x._data.dtype).min
            attn_bias = Tensor(
                jnp.where(m[:, None, None, :].astype(bool), 0.0,
                          neg).astype(x._data.dtype))
        if self._can_scan(x, attn_bias):
            x = self._scan_layers(x)
        elif self.cfg.stacked_blocks:
            if attn_bias is None:
                x = self.layers(x, allow_scan=False)
            else:
                for i in range(self.cfg.num_layers):
                    x = self.layers.layer_slice_call(i, x,
                                                     attn_bias=attn_bias)
        else:
            for layer in self.layers:
                x = layer(x, attn_bias)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled

    def _can_scan(self, x, attn_bias) -> bool:
        cfg = self.cfg
        return (cfg.use_scan and cfg.num_layers > 1 and attn_bias is None
                and isinstance(x._data, jax.core.Tracer)
                and (not self.training
                     or (cfg.hidden_dropout_prob == 0.0
                         and cfg.attention_dropout_prob == 0.0)))

    def _scan_layers(self, x: Tensor) -> Tensor:
        """Depth-independent compile: one scanned block body (shared
        machinery in models/_scan.py)."""
        if self.cfg.stacked_blocks:
            return self.layers(x)
        from ._scan import scan_layer_stack
        out = scan_layer_stack(list(self.layers), x)
        if out is not None:
            return out
        for layer in self.layers:
            x = layer(x)
        return x


class ErnieForSequenceClassification(nn.Layer):
    """SST-2-style fine-tune head (BASELINE config 2 task)."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.ernie = ErnieModel(cfg)
        self.drop = nn.Dropout(cfg.hidden_dropout_prob)
        self.classifier = nn.Linear(cfg.hidden_size, cfg.num_classes,
                                    weight_attr=_attr(cfg.initializer_range))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        _, pooled = self.ernie(input_ids, token_type_ids, attention_mask)
        logits = self.classifier(self.drop(pooled))
        if labels is None:
            return logits
        loss = F.cross_entropy(logits.astype("float32"),
                               labels.reshape([-1]))
        return logits, loss

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())


def ernie3_base(**overrides) -> ErnieConfig:
    """ERNIE-3.0-base geometry (BASELINE config 2)."""
    cfg = dict(vocab_size=40000, hidden_size=768, num_layers=12,
               num_heads=12, max_position_embeddings=2048)
    cfg.update(overrides)
    return ErnieConfig(**cfg)


def ernie_tiny(**overrides) -> ErnieConfig:
    cfg = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
               max_position_embeddings=64, type_vocab_size=2)
    cfg.update(overrides)
    return ErnieConfig(**cfg)
