"""GPT-style decoder LM — the flagship model family (BASELINE config 4:
"GPT-3 1.3B Fleet hybrid-parallel"; reference model zoo lives in PaddleNLP,
structure mirrored from fleet mp examples: fused qkv, pre-LN blocks,
Column/Row-parallel MLP like fleet/layers/mpu/mp_layers.py usage).

TPU-first design: one logical module works at every parallelism degree —
  * tensor_parallel=True swaps Linear for GSPMD-sharded Column/Row layers
    (mp mesh axis), including the vocab-parallel embedding + tied head.
  * sequence_parallel=True keeps inter-block activations sharded over the
    'sep' axis on the sequence dim (Megatron-SP; attention re-gathers).
  * the flash-attention kernel (kernels/) serves the sdpa hot path.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from .. import nn
from ..nn import functional as F
from ..kernels.attention import scaled_dot_product_attention


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_position_embeddings: int = 1024
    intermediate_size: Optional[int] = None  # default 4*hidden
    hidden_dropout_prob: float = 0.0
    attention_dropout_prob: float = 0.0
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-5
    tie_word_embeddings: bool = True
    tensor_parallel: bool = False
    sequence_parallel: bool = False
    # long-context attention over the 'sep' mesh ring: "none" | "ring"
    # (KV rotation via collective-permute) | "ulysses" (all-to-all head
    # resharding). See distributed/sep.py.
    context_parallel: str = "none"
    use_recompute: bool = False
    # remat selectivity: "full" recomputes everything (min memory);
    # "dots" saves matmul outputs and recomputes elementwise only
    # (jax checkpoint_policies.dots_with_no_batch_dims_saveable) — the
    # usual best speed/memory point on TPU; "dots_plus"/"dots_plus_ln"
    # additionally pin the gelu / LN outputs; "offload" parks the
    # heavies in pinned host memory; "search" runs the deterministic
    # cost-model policy search (incubate.autotune.search_remat_policy)
    # once per (batch, seq) and wires the minimal-recompute policy
    # that fits remat_budget_gb
    recompute_granularity: str = "full"
    # HBM budget the "search" granularity must fit (params + grads +
    # optimizer state + saved activations, cost-model accounting).
    # None: $PADDLE_REMAT_BUDGET_GB, else the v5e 16 GB default.
    remat_budget_gb: Optional[float] = None
    # compile the block stack as ONE lax.scan body under to_static —
    # compile time (and HLO size) become depth-independent, the standard
    # TPU recipe for deep transformers. Falls back to the Python loop in
    # eager mode or when dropout makes per-layer RNG streams necessary.
    use_scan: bool = True
    # store the block stack's parameters PRE-STACKED as [L, ...] leaves
    # (models/_scan.py StackedLayerStack): the scan consumes them with
    # zero per-step restacking. Measured on v5e (r5): the per-step
    # dynamic-update-slice stack of 24 layers' weights (+ the matching
    # grad unstack) is ~GBs of pure HBM traffic — the bulk of the
    # "framework tax" vs a bare-JAX probe. Trade-off: per-block
    # sub-layers (model.gpt.h[i]) are not addressable and eager
    # *training* must run under jit (to_static / train_step); eager
    # inference works.
    stacked_blocks: bool = False
    # compute the LM loss through the chunked fused head+CE kernel
    # (incubate.nn.functional.fused_linear_cross_entropy): the [tokens,
    # vocab] f32 logits are never materialized. forward(labels=...) then
    # returns (None, loss). Single-device / non-TP path only.
    fused_head_loss: bool = False
    # opt-in TRAINING-TIME int8 weight-only path for the lm_head /
    # logits matmul: the head weight is per-vocab-channel absmax
    # fake-quantized (straight-through gradients back to the fp
    # weight), so the forward logits equal the int8 weight-only
    # serving matmul within its analytic error bound while training
    # stays differentiable. Shared-embedding aware: with tied
    # embeddings only the HEAD read of wte is quantized, never the
    # embedding lookup. Mutually exclusive with fused_head_loss
    # (whose chunked kernel owns the head matmul).
    quantized_lm_head: bool = False

    @property
    def ffn_size(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def _init_attr(std):
    return nn.ParamAttr(initializer=nn.initializer.Normal(mean=0.0, std=std))


def convert_pre_r5_qkv_weight(w, num_heads: int, head_dim: int):
    """Permute a fused qkv weight/bias from the pre-r5 column layout
    ``[.., (q|k|v), heads, d]`` to the current HEAD-MAJOR layout
    ``[.., heads, (q|k|v), d]`` (see GPTAttention.forward — the change
    makes mp shards split at head boundaries). Apply to ``qkv.weight``
    ([in, 3h]) and ``qkv.bias`` ([3h]) when loading a checkpoint saved
    before the layout change; shapes are unchanged, so the load itself
    cannot detect the mismatch."""
    arr = w._data if isinstance(w, Tensor) else jnp.asarray(w)
    lead = arr.shape[:-1]
    out = arr.reshape(lead + (3, num_heads, head_dim))
    out = jnp.swapaxes(out, -3, -2).reshape(arr.shape)
    return Tensor(out) if isinstance(w, Tensor) else out


def _linear_pair(cfg: GPTConfig, d_in, d_mid, std):
    """(up, down) projections: parallel Column/Row when tensor_parallel."""
    if cfg.tensor_parallel:
        from ..distributed.fleet import (ColumnParallelLinear,
                                         RowParallelLinear)
        up = ColumnParallelLinear(d_in, d_mid, weight_attr=_init_attr(std),
                                  gather_output=False)
        down = RowParallelLinear(d_mid, d_in, weight_attr=_init_attr(std),
                                 input_is_parallel=True)
    else:
        up = nn.Linear(d_in, d_mid, weight_attr=_init_attr(std))
        down = nn.Linear(d_mid, d_in, weight_attr=_init_attr(std))
    return up, down


def _seq_constrain(x: Tensor, cfg: GPTConfig) -> Tensor:
    """Keep activations sharded [dp(batch), sep(seq), -] between blocks."""
    if not cfg.sequence_parallel:
        return x
    from ..distributed import get_mesh
    from ..distributed.fleet.mp_layers import _constrain_tensor
    from jax.sharding import PartitionSpec as P
    mesh = get_mesh()
    if mesh is None or "sep" not in mesh.axis_names:
        return x
    batch_axis = "dp" if "dp" in mesh.axis_names else None
    return _constrain_tensor(x, P(batch_axis, "sep",
                                  *([None] * (x.ndim - 2))))


class GPTAttention(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        h = cfg.hidden_size
        std = cfg.initializer_range
        proj_std = std / math.sqrt(2 * cfg.num_layers)
        if cfg.tensor_parallel:
            from ..distributed.fleet import (ColumnParallelLinear,
                                             RowParallelLinear)
            self.qkv = ColumnParallelLinear(h, 3 * h,
                                            weight_attr=_init_attr(std),
                                            gather_output=False)
            self.out_proj = RowParallelLinear(h, h,
                                              weight_attr=_init_attr(std),
                                              input_is_parallel=True)
        else:
            self.qkv = nn.Linear(h, 3 * h, weight_attr=_init_attr(std))
            self.out_proj = nn.Linear(h, h, weight_attr=_init_attr(std))
        # GPT-2 init: residual-out projections scaled by 1/sqrt(2*layers)
        w = self.out_proj.weight
        data = nn.initializer.Normal(mean=0.0, std=proj_std)(w.shape, w.dtype)
        data = data._data if isinstance(data, Tensor) else jnp.asarray(data)
        w._replace_data(jax.device_put(data, w._data.sharding))

    def forward(self, x, cache=None):
        """cache: optional (k, v) of past tokens [b, s_past, H, D] —
        autoregressive decode appends this step's k/v and attends over the
        full prefix (causal stays correct: our sdpa is bottom-right
        aligned for s_q < s_k). Returns out, or (out, new_cache) when a
        cache (possibly empty tuple) is passed."""
        cfg = self.cfg
        b, s, h = x.shape
        qkv = self.qkv(x)  # [b, s, 3h] (mp-sharded when TP)
        # HEAD-MAJOR fused layout [heads, (q|k|v), head_dim]: an mp shard
        # of the output dim then splits at head boundaries, so the
        # manual-mp local block reshapes to whole heads (num_heads/mp of
        # them — hence -1) and GSPMD avoids a reshard on this reshape.
        # A (3, heads, d) layout would hand rank 0 "all of q + half of
        # k" under TP.
        qkv = qkv.reshape([b, s, -1, 3, cfg.head_dim])
        q, k, v = qkv.unbind(axis=3)
        new_cache = None
        if cache is not None:
            if len(cache) == 2:
                from ..ops.manipulation import concat
                k = concat([cache[0], k], axis=1)
                v = concat([cache[1], v], axis=1)
            new_cache = (k, v)
        if cfg.context_parallel != "none":
            if cfg.attention_dropout_prob > 0.0 and self.training:
                raise ValueError(
                    "attention_dropout_prob > 0 is not supported with "
                    "context_parallel (the ring/ulysses paths have no "
                    "dropout); set it to 0 or use hidden dropout")
            from ..distributed.sep import ring_attention, ulysses_attention
            attn = (ring_attention if cfg.context_parallel == "ring"
                    else ulysses_attention)
            out = attn(q, k, v, causal=True)
        else:
            out = scaled_dot_product_attention(
                q, k, v, is_causal=True,
                dropout_p=cfg.attention_dropout_prob, training=self.training)
        out = out.reshape([b, s, -1])   # h, or h/mp under manual-mp
        out = self.out_proj(out)
        return (out, new_cache) if cache is not None else out


class GPTMLP(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.up, self.down = _linear_pair(cfg, cfg.hidden_size, cfg.ffn_size,
                                          cfg.initializer_range)
        # the gelu residual tag only matters when the dots_plus remat
        # policy will consume it; other configs skip the extra dispatch.
        # "search"/"offload" tag unconditionally: the resolved policy
        # may pin the name, and an unconsumed checkpoint_name is a
        # bitwise-neutral identity
        self._tag_gelu = (cfg.use_recompute
                          and cfg.recompute_granularity in
                          ("dots_plus", "dots_plus_ln", "search",
                           "offload"))

    def forward(self, x):
        h = F.gelu(self.up(x))
        if self._tag_gelu and self.training:
            # named residual for the "dots_plus" policy (saves the gelu
            # output so backward skips its recompute). Routed through
            # apply_op: the tag must not sever the eager tape (it is a
            # recorded identity with identity VJP).
            from jax.ad_checkpoint import checkpoint_name
            from ..ops.dispatch import apply_op
            h = apply_op("mlp_gelu_tag",
                         lambda a: checkpoint_name(a, "mlp_gelu"),
                         (h,), {})
        return self.down(h)


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        eps = cfg.layer_norm_epsilon
        self.ln_1 = nn.LayerNorm(cfg.hidden_size, epsilon=eps)
        self.attn = GPTAttention(cfg)
        self.ln_2 = nn.LayerNorm(cfg.hidden_size, epsilon=eps)
        self.mlp = GPTMLP(cfg)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)
        self._tag_ln = (cfg.use_recompute
                        and cfg.recompute_granularity in
                        ("dots_plus_ln", "search", "offload"))

    def _ln(self, norm, x):
        out = norm(x)
        if self._tag_ln and self.training:
            # named residual for the "dots_plus_ln" policy (saves the LN
            # output so backward skips its re-reduction)
            from jax.ad_checkpoint import checkpoint_name
            from ..ops.dispatch import apply_op
            out = apply_op("ln_out_tag",
                           lambda a: checkpoint_name(a, "ln_out"),
                           (out,), {})
        return out

    def forward(self, x, cache=None):
        if cache is not None:
            a, new_cache = self.attn(self._ln(self.ln_1, x), cache=cache)
            x = x + self.dropout(a)
            x = x + self.dropout(self.mlp(self._ln(self.ln_2, x)))
            return _seq_constrain(x, self.cfg), new_cache
        x = x + self.dropout(self.attn(self._ln(self.ln_1, x)))
        x = x + self.dropout(self.mlp(self._ln(self.ln_2, x)))
        return _seq_constrain(x, self.cfg)


class GPTModel(nn.Layer):
    """Transformer trunk: embeddings -> blocks -> final LN."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        std = cfg.initializer_range
        if cfg.tensor_parallel:
            from ..distributed.fleet import VocabParallelEmbedding
            self.wte = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size,
                                              weight_attr=_init_attr(std))
        else:
            self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                    weight_attr=_init_attr(std))
        self.wpe = nn.Embedding(cfg.max_position_embeddings, cfg.hidden_size,
                                weight_attr=_init_attr(std))
        self.drop = nn.Dropout(cfg.hidden_dropout_prob)
        blocks = [GPTBlock(cfg) for _ in range(cfg.num_layers)]
        if cfg.stacked_blocks:
            from ._scan import StackedLayerStack
            self.h = StackedLayerStack(blocks)
        else:
            self.h = nn.LayerList(blocks)
        self.ln_f = nn.LayerNorm(cfg.hidden_size,
                                 epsilon=cfg.layer_norm_epsilon)
        # "search" granularity: plans resolved per (batch, seq) by the
        # deterministic cost-model searcher; the per-shape cache token
        # keys the jit.train_step program cache so two models differing
        # only in resolved policy never share a compiled entry
        self._remat_plans: dict = {}
        # untied-head models register their extra head params here
        # (GPTForCausalLM ctor): the budget's fixed-bytes accounting
        # must see EVERY trained parameter, not just the trunk's
        self._remat_fixed_params_extra = 0

    # -- remat policy resolution ----------------------------------------
    def _resolved_remat(self, batch: int, seq: int):
        """(use_recompute, granularity) for this forward. Non-"search"
        configs pass through; "search" resolves (and caches) a
        :class:`~paddle2_tpu.incubate.autotune.RematPlan` for the
        (batch, seq) shape — a pure function of config + rate model,
        so every host resolves the same policy."""
        cfg = self.cfg
        if not cfg.use_recompute:
            return False, cfg.recompute_granularity
        if cfg.recompute_granularity != "search":
            return True, cfg.recompute_granularity
        key = (int(batch), int(seq))
        plan = self._remat_plans.get(key)
        if plan is None:
            import os as _os
            from ..incubate import autotune
            budget_gb = cfg.remat_budget_gb
            if budget_gb is None:
                budget_gb = float(_os.environ.get(
                    "PADDLE_REMAT_BUDGET_GB", 16.0))
            # fixed footprint: bf16 params + bf16 grads + f32 master +
            # two f32 Adam moments (the multi-precision AdamW worst
            # case the BENCH config trains with); the extra term covers
            # params owned OUTSIDE the trunk (an untied lm_head)
            n_params = (sum(int(p.size) for p in self.parameters())
                        + int(self._remat_fixed_params_extra))
            fixed = float(n_params) * (2.0 + 2.0 + 3 * 4.0)
            plan = autotune.search_remat_policy(
                hidden=cfg.hidden_size, num_layers=cfg.num_layers,
                num_heads=cfg.num_heads, seq=seq, batch=batch,
                ffn=cfg.ffn_size, budget_bytes=budget_gb * 1e9,
                fixed_bytes=fixed)
            self._remat_plans[key] = plan
        return plan.use_recompute, plan.granularity

    def _remat_token_for(self, batch: int, seq: int):
        """The program-cache token of THIS shape's resolved plan —
        per shape, never the last-resolved one (a stale global token
        would force a duplicate compile every time shapes alternate)."""
        plan = self._remat_plans.get((int(batch), int(seq)))
        if plan is None:
            return None
        return plan.cache_token() + (int(batch), int(seq))

    def _prepare_remat(self, arg_arrays):
        """jit.train_step protocol: resolve the searched policy from
        the call's batch shape BEFORE the program-cache key is
        computed, and return THIS SHAPE's cache token (None when
        nothing is searched). Keeps the first compiled entry and every
        later same-shape call under the SAME key — no wasted duplicate
        compile, even when batch shapes alternate."""
        cfg = self.cfg
        if not (cfg.use_recompute
                and cfg.recompute_granularity == "search"
                and self.training):
            return None
        for a in arg_arrays:
            shp = getattr(a, "shape", None)
            if shp is not None and len(shp) == 2:
                self._resolved_remat(int(shp[0]), int(shp[1]))
                return self._remat_token_for(int(shp[0]), int(shp[1]))
        return None

    def remat_plan(self, batch: int, seq: int):
        """The resolved searched plan for a shape (resolving it if
        needed) — None unless granularity is "search"."""
        self._resolved_remat(batch, seq)
        return self._remat_plans.get((int(batch), int(seq)))

    def forward(self, input_ids):
        b, s = input_ids.shape
        pos = Tensor(jnp.arange(s, dtype=jnp.int32)[None, :])
        x = self.wte(input_ids) + self.wpe(pos)
        x = _seq_constrain(self.drop(x), self.cfg)
        use_rc, gran = (self._resolved_remat(b, s) if self.training
                        else (False, None))
        if self._can_scan(x):
            x = self._scan_blocks(x, use_rc, gran)
        else:
            x = self._fallback_loop(x, use_rc, gran)
        return self.ln_f(x)

    def _can_scan(self, x) -> bool:
        cfg = self.cfg
        return (cfg.use_scan and cfg.num_layers > 1
                and isinstance(x._data, jax.core.Tracer)
                and (cfg.hidden_dropout_prob == 0.0
                     and cfg.attention_dropout_prob == 0.0
                     or not self.training))

    def _scan_blocks(self, x: Tensor, use_rc: bool, gran) -> Tensor:
        """Run the homogeneous block stack as one lax.scan (shared
        machinery in models/_scan.py). With use_recompute the body is
        jax.checkpoint-ed with kernels.attention.remat_policy: 'dots' +
        pinned flash residuals means backward reuses the saved flash
        (o, lse) instead of re-running the kernel."""
        from ._scan import scan_layer_stack

        wrap = None
        if use_rc and self.training:
            from ..kernels.attention import remat_policy
            policy = remat_policy(
                gran if gran in ("dots", "dots_plus", "dots_plus_ln",
                                 "offload")
                else "nothing")
            wrap = lambda body: jax.checkpoint(body, policy=policy)
        if self.cfg.stacked_blocks:
            return self.h(x, wrap_body=wrap)
        out = scan_layer_stack(list(self.h), x, wrap_body=wrap)
        return out if out is not None else \
            self._fallback_loop(x, use_rc, gran)

    def _fallback_loop(self, x: Tensor, use_rc: bool = None,
                       gran=None) -> Tensor:
        if use_rc is None:
            use_rc, gran = (self._resolved_remat(*x.shape[:2])
                            if self.training else (False, None))
        if self.cfg.stacked_blocks:
            # allow_scan=False: this path is taken exactly when _can_scan
            # said no (eager, or dropout needs per-layer rng streams)
            return self.h(x, allow_scan=False)
        for block in self.h:
            if use_rc and self.training:
                from ..distributed.recompute import recompute
                x = recompute(block, x, policy=gran)
            else:
                x = block(x)
        return x

    def decode_step(self, input_ids, caches, position_offset: int):
        """KV-cached decode: run only the NEW tokens through the trunk,
        appending to per-layer (k, v) caches. caches: list of per-block
        tuples (() on the first/prefill call)."""
        b, s = input_ids.shape
        pos = Tensor(jnp.arange(position_offset, position_offset + s,
                                dtype=jnp.int32)[None, :])
        x = self.wte(input_ids) + self.wpe(pos)
        x = _seq_constrain(self.drop(x), self.cfg)
        new_caches = []
        if self.cfg.stacked_blocks:
            for i, cache in enumerate(caches):
                x, c = self.h.layer_slice_call(i, x, cache=cache)
                new_caches.append(c)
        else:
            for block, cache in zip(self.h, caches):
                x, c = block(x, cache=cache)
                new_caches.append(c)
        return self.ln_f(x), new_caches


class GPTForCausalLM(nn.Layer):
    """Trunk + LM head (tied to wte by default, like the reference zoo)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        if cfg.quantized_lm_head and cfg.fused_head_loss:
            raise ValueError(
                "quantized_lm_head and fused_head_loss are mutually "
                "exclusive: the chunked fused-CE kernel owns the head "
                "matmul, so there is no logits matmul to quantize")
        self.gpt = GPTModel(cfg)
        if not cfg.tie_word_embeddings:
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                     weight_attr=_init_attr(
                                         cfg.initializer_range),
                                     bias_attr=False)
            # the remat searcher's fixed-bytes budget must count the
            # head params the trunk cannot see
            self.gpt._remat_fixed_params_extra = int(
                self.lm_head.weight.size)

    def _prepare_remat(self, arg_arrays):
        """jit.train_step cache-key protocol — delegate to the trunk."""
        return self.gpt._prepare_remat(arg_arrays)

    def _head(self, hidden):
        # serving-time int8 payload installed by
        # quantization.quantize_lm_head (shared-embedding aware: the
        # embedding LOOKUP stays fp — only this head read is int8)
        wo = getattr(self, "_wo_head", None)
        if wo is not None:
            return wo(hidden)
        if self.cfg.quantized_lm_head:
            # training-time int8 weight-only path: per-vocab-channel
            # absmax fake quantization (STE) — forward logits equal
            # the int8 serving matmul's dequantized product within its
            # analytic bound, gradients flow straight through to the
            # fp weight (and the tied embedding)
            from ..quantization import channel_absmax, fake_quant
            w = (self.gpt.wte.weight.T if self.cfg.tie_word_embeddings
                 else self.lm_head.weight)
            scale = channel_absmax(w, axis=1)
            w = fake_quant(w, scale, bits=8, quant_axis=1)
            return F.linear(hidden, w)
        if self.cfg.tie_word_embeddings:
            return F.linear(hidden, self.gpt.wte.weight.T)
        return self.lm_head(hidden)

    def forward(self, input_ids, labels=None):
        hidden = self.gpt(input_ids)
        if (labels is not None and self.cfg.fused_head_loss
                and not self.cfg.tensor_parallel):
            from ..incubate.nn.functional import fused_linear_cross_entropy
            w = (self.gpt.wte.weight.T if self.cfg.tie_word_embeddings
                 else self.lm_head.weight)
            loss = fused_linear_cross_entropy(hidden, w, labels)
            return None, loss
        logits = self._head(hidden)
        if labels is None:
            return logits
        loss = F.cross_entropy(
            logits.reshape([-1, self.cfg.vocab_size]).astype("float32"),
            labels.reshape([-1]))
        return logits, loss

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 1.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 eos_token_id: Optional[int] = None):
        """Autoregressive decoding (PaddleNLP generate() capability).

        Greedy when temperature == 0, otherwise temperature/top-k/top-p
        sampling through the framework RNG (seeded by paddle.seed).
        Decoding runs through per-layer KV caches (prefill once, then one
        new token per step); past max_position_embeddings — or when
        context_parallel attention is active, whose ring/ulysses paths
        need full equal-length sequences — it falls back to windowed full
        forwards.
        """
        from ..framework import core
        from ..framework import random as fr
        ids = input_ids if isinstance(input_ids, Tensor) \
            else Tensor(jnp.asarray(input_ids, jnp.int32))
        arr = ids._data.astype(jnp.int32)
        if arr.ndim == 1:
            arr = arr[None]
        max_pos = self.cfg.max_position_embeddings
        finished = jnp.zeros((arr.shape[0],), bool)
        caches = ([() for _ in range(self.cfg.num_layers)]
                  if self.cfg.context_parallel == "none" else None)
        pos = 0
        with core.no_grad():
            for _ in range(max_new_tokens):
                if arr.shape[1] > max_pos:
                    # context overflow: fall back to windowed full forward
                    caches = None
                if caches is not None:
                    new_tok = arr[:, pos:]        # prefill, then 1/step
                    hidden, caches = self.gpt.decode_step(
                        Tensor(new_tok), caches, pos)
                    pos = arr.shape[1]
                    # only the LAST position feeds sampling: skip the
                    # [s, vocab] prefill logits entirely
                    logits = self._head(hidden[:, -1:])
                else:
                    logits = self._head(self.gpt(Tensor(arr[:, -max_pos:])))
                step = logits._data[:, -1].astype(jnp.float32)  # [B, V]
                if temperature == 0.0:
                    nxt = jnp.argmax(step, axis=-1)
                else:
                    step = step / max(temperature, 1e-6)
                    if top_k is not None:
                        kth = jnp.sort(step, axis=-1)[:, -int(top_k)]
                        step = jnp.where(step < kth[:, None], -jnp.inf,
                                         step)
                    if top_p is not None:
                        from ..ops.extra import nucleus_filter_logits
                        step = nucleus_filter_logits(
                            step, jnp.full((step.shape[0],), top_p,
                                           jnp.float32))
                    nxt = jax.random.categorical(fr.next_key(), step)
                if eos_token_id is not None:
                    # finished rows pad with eos (PaddleNLP semantics)
                    nxt = jnp.where(finished, eos_token_id, nxt)
                    finished = finished | (nxt == eos_token_id)
                arr = jnp.concatenate(
                    [arr, nxt[:, None].astype(jnp.int32)], axis=1)
                if eos_token_id is not None and bool(jnp.all(finished)):
                    break
        return Tensor(arr, stop_gradient=True)


def gpt3_1p3b(**overrides) -> GPTConfig:
    """BASELINE config 4 geometry (GPT-3 1.3B)."""
    cfg = dict(vocab_size=50304, hidden_size=2048, num_layers=24,
               num_heads=16, max_position_embeddings=2048)
    cfg.update(overrides)
    return GPTConfig(**cfg)


def gpt_small(**overrides) -> GPTConfig:
    cfg = dict(vocab_size=50304, hidden_size=768, num_layers=12,
               num_heads=12, max_position_embeddings=1024)
    cfg.update(overrides)
    return GPTConfig(**cfg)


def gpt_tiny(**overrides) -> GPTConfig:
    """Test/dryrun geometry."""
    cfg = dict(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
               max_position_embeddings=64)
    cfg.update(overrides)
    return GPTConfig(**cfg)
