"""paddle.nn namespace (python/paddle/nn/__init__.py parity)."""

from . import initializer  # noqa: F401  (must import before layers)
from .layer.layers import Layer, ParamAttr  # noqa: F401
from . import functional  # noqa: F401
from .layer.common import *       # noqa: F401,F403
from .layer.conv import *         # noqa: F401,F403
from .layer.norm import *         # noqa: F401,F403
from .layer.pooling import *      # noqa: F401,F403
from .layer.activation import *   # noqa: F401,F403
from .layer.container import *    # noqa: F401,F403
from .layer.transformer import *  # noqa: F401,F403
from .layer.loss import *         # noqa: F401,F403
from .layer.rnn import *          # noqa: F401,F403
from .decode import BeamSearchDecoder, Decoder, dynamic_decode  # noqa: F401

from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .utils_mod import clip_grad_norm_, clip_grad_value_  # noqa: F401
from . import utils  # noqa: F401
