"""Gradient clipping (python/paddle/nn/clip.py parity).

Clip objects are attached to optimizers; inside a jitted train step they
become pure pytree transforms, fusing into the update kernel.
"""

from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm"]


class ClipGradBase:
    def __call__(self, params_grads: List[Tuple[Tensor, Tensor]]):
        raise NotImplementedError

    def apply_arrays(self, grads):
        """Pure-array form used by the jitted optimizer path."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max))))
        return out

    def apply_arrays(self, grads):
        import jax
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, self.min, self.max), grads)


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip_one(self, g):
        norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
        return g * scale

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(self._clip_one(g._data))))
        return out

    def apply_arrays(self, grads):
        import jax
        return jax.tree_util.tree_map(self._clip_one, grads)


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        sq = [jnp.sum(jnp.square(g._data.astype(jnp.float32)))
              for p, g in params_grads
              if g is not None and getattr(p, "need_clip", True)]
        if not sq:
            return params_grads
        gnorm = jnp.sqrt(sum(sq))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(gnorm, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor((g._data.astype(jnp.float32) * scale)
                                  .astype(g._data.dtype))))
        return out

    def apply_arrays(self, grads):
        import jax
        leaves = jax.tree_util.tree_leaves(grads)
        gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                             for g in leaves))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(gnorm, 1e-12), 1.0)
        return jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads)
