"""Seq2seq decoding infrastructure (reference python/paddle/nn/decode.py:
Decoder :39, BeamSearchDecoder :161, dynamic_decode :~1200).

The step loop runs eagerly on host (decode lengths are data-dependent);
each step's tensor work — cell forward, log-softmax, top-k over
beam x vocab, beam/state gathers — is XLA-compiled via the op layer, and
the final backtrace reuses ``F.gather_tree``'s compiled scan.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..ops.dispatch import ensure_tensor

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]


class Decoder:
    """decode.py:39 — the initialize/step/finalize protocol."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


def _map_structure(fn, tree):
    return jax.tree_util.tree_map(fn, tree)


class BeamSearchDecoder(Decoder):
    """decode.py:161 — beam search over an RNN cell.

    ``embedding_fn`` maps token ids to the cell's input; ``output_fn``
    maps cell output to vocab logits.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """decode.py:256 — [B, ...] -> [B*beam, ...] by tiling."""
        x = ensure_tensor(x)
        a = x._data
        tiled = jnp.repeat(a[:, None], beam_size, axis=1)
        return Tensor(tiled.reshape((-1,) + a.shape[1:]))

    def _merge(self, a):
        return a.reshape((-1,) + a.shape[2:])

    def _split(self, a):
        return a.reshape((-1, self.beam_size) + a.shape[1:])

    def initialize(self, inits):
        """Tile initial states across beams; beam 0 starts live (log-prob
        0), the rest dead (-inf), so step 1 expands a single beam."""
        states = _map_structure(
            lambda t: self._merge(jnp.repeat(
                ensure_tensor(t)._data[:, None], self.beam_size, axis=1)),
            inits)
        batch = jax.tree_util.tree_leaves(states)[0].shape[0] \
            // self.beam_size
        tokens = jnp.full((batch * self.beam_size,), self.start_token,
                          jnp.int32)
        log_probs = jnp.tile(
            jnp.asarray([0.0] + [-1e9] * (self.beam_size - 1),
                        jnp.float32)[None], (batch, 1))
        finished = jnp.zeros((batch, self.beam_size), bool)
        return tokens, states, log_probs, finished

    def _embed(self, tokens):
        t = Tensor(tokens)
        if self.embedding_fn is not None:
            return self.embedding_fn(t)
        return t

    def step(self, time, tokens, states, log_probs, finished):
        inputs = self._embed(tokens)
        cell_out, next_states = self.cell(inputs, _map_structure(
            lambda a: Tensor(a), states))
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        logits = ensure_tensor(cell_out)._data
        V = logits.shape[-1]
        B = logits.shape[0] // self.beam_size
        step_lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        step_lp = step_lp.reshape(B, self.beam_size, V)
        # finished beams extend only with end_token, at zero cost
        fin_mask = jnp.full((V,), -1e9,
                            jnp.float32).at[self.end_token].set(0.0)
        step_lp = jnp.where(finished[..., None], fin_mask[None, None],
                            step_lp)
        scores = (log_probs[..., None] + step_lp).reshape(B, -1)
        top_scores, top_idx = jax.lax.top_k(scores, self.beam_size)
        parent = (top_idx // V).astype(jnp.int32)      # [B, beam]
        token = (top_idx % V).astype(jnp.int32)
        next_states = _map_structure(
            lambda t: self._merge(jnp.take_along_axis(
                self._split(ensure_tensor(t)._data), parent.reshape(
                    (B, self.beam_size)
                    + (1,) * (ensure_tensor(t)._data.ndim - 1)),
                axis=1)), next_states)
        prev_fin = jnp.take_along_axis(finished, parent, axis=1)
        next_finished = prev_fin | (token == self.end_token)
        return (token.reshape(-1), next_states, top_scores,
                next_finished, parent)

    def finalize(self, step_tokens, step_parents, sequence_lengths):
        """Backtrace beams through the parent pointers (gather_tree)."""
        from .functional import gather_tree
        ids = Tensor(jnp.stack(step_tokens))        # [T, B, beam]
        parents = Tensor(jnp.stack(step_parents))
        return gather_tree(ids, parents)


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """decode.py dynamic_decode: run decoder.initialize, then step until
    every beam is finished or ``max_step_num``; finalize with the
    backtrace."""
    tokens, states, log_probs, finished = decoder.initialize(inits)
    B, beam = finished.shape
    step_tokens, step_parents = [], []
    lengths = jnp.zeros((B, beam), jnp.int32)
    limit = int(max_step_num) if max_step_num is not None else 256
    for t in range(limit):
        (tokens, states, log_probs, next_finished,
         parent) = decoder.step(t, tokens, states, log_probs, finished)
        step_tokens.append(tokens.reshape(B, beam))
        step_parents.append(parent)
        lengths = lengths + (~next_finished).astype(jnp.int32)
        finished = next_finished
        if bool(jnp.all(finished)):
            break
    ids = decoder.finalize(step_tokens, step_parents, lengths)
    if not output_time_major:
        ids = Tensor(jnp.transpose(ids._data, (1, 0, 2)))
    # count end_token emission in the length (reference semantics)
    lengths = Tensor(jnp.minimum(lengths + 1, len(step_tokens)))
    if return_length:
        return ids, lengths
    return ids
