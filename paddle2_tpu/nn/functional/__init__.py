"""paddle.nn.functional namespace (python/paddle/nn/functional/ parity)."""

from .activation import *   # noqa: F401,F403
from .common import *       # noqa: F401,F403
from .conv import *         # noqa: F401,F403
from .pooling import *      # noqa: F401,F403
from .norm import *         # noqa: F401,F403
from .loss import *         # noqa: F401,F403

from ...kernels.attention import scaled_dot_product_attention  # noqa: F401
# NOTE: like the reference, `paddle.nn.functional.flash_attention` is the
# SUBMODULE (PaddleNLP does `paddle.nn.functional.flash_attention
# .flash_attention(...)`); only the helper names are lifted here
from .flash_attention import (flashmask_attention,  # noqa: F401
                              sparse_attention,
                              flash_attn_qkvpacked,
                              flash_attn_unpadded,
                              flash_attn_varlen_qkvpacked, sdp_kernel)
from . import flash_attention  # noqa: F401  (module binding wins)
from .extra_losses import *   # noqa: F401,F403
from .vision_ops import *     # noqa: F401,F403

# in-place activation variants (reference elu_/tanh_/... surface):
# out-of-place op + rebind keeps the autograd edge
from ...ops.dispatch import rebind_inplace as _rebind
from ...ops.dispatch import ensure_tensor as _ensure


def _mk_act_inplace(_base, _nm):
    def f(x, *a, **k):
        x = _ensure(x)
        return _rebind(x, _base(x, *a, **k))
    f.__name__ = _nm
    return f


import sys as _sys
_self = _sys.modules[__name__]
for _b in ("elu", "hardtanh", "leaky_relu", "tanh", "thresholded_relu",
           "relu", "relu6", "softmax", "sigmoid"):
    _fn = getattr(_self, _b, None)
    if _fn is not None and not hasattr(_self, _b + "_"):
        setattr(_self, _b + "_", _mk_act_inplace(_fn, _b + "_"))

# sequence mask helper used widely in NLP codebases
import jax.numpy as _jnp
from ...framework.tensor import Tensor as _Tensor
from ...ops.dispatch import apply_op as _apply_op, ensure_tensor as _ensure


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    x = _ensure(x)
    m = maxlen if maxlen is not None else int(x.numpy().max())
    from ...framework import core as _core
    dt = _core.convert_dtype(dtype)
    return _apply_op(
        "sequence_mask",
        lambda a: (_jnp.arange(m)[None, :] < a[..., None]).astype(dt),
        (x,), {}, differentiable=False)


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    from ...ops.creation import diag_embed as _de
    return _de(x, offset, dim1, dim2)
