"""Activation functionals (python/paddle/nn/functional/activation.py parity).

All are single fused XLA elementwise graphs — on TPU these fuse into the
surrounding matmul's epilogue, so there is no per-activation kernel to write.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops.dispatch import apply_op, ensure_tensor
from ...framework.tensor import Tensor

__all__ = ["relu", "relu_", "relu6", "elu", "selu", "celu", "gelu", "silu",
           "swish", "mish", "softplus", "softshrink", "hardshrink",
           "tanhshrink", "hardtanh", "hardsigmoid", "hardswish", "leaky_relu",
           "log_sigmoid", "log_softmax", "softmax", "softmax_", "softsign",
           "sigmoid", "tanh", "maxout", "prelu", "rrelu", "glu",
           "gumbel_softmax", "thresholded_relu"]


def _unary(name, jfn):
    def op(x, *args, name=None, **kwargs):
        return apply_op(op.__name__,
                        (lambda a: jfn(a, *args, **kwargs)),
                        (ensure_tensor(x),), {})
    op.__name__ = name
    op.__qualname__ = name
    return op


relu = _unary("relu", jax.nn.relu)
relu6 = _unary("relu6", jax.nn.relu6)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
tanh = _unary("tanh", jnp.tanh)
softsign = _unary("softsign", jax.nn.soft_sign)
silu = _unary("silu", jax.nn.silu)
log_sigmoid = _unary("log_sigmoid", jax.nn.log_sigmoid)
mish = _unary("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)))


def relu_(x, name=None):
    from ...ops.dispatch import rebind_inplace
    return rebind_inplace(x, relu(x))


def elu(x, alpha=1.0, name=None):
    return apply_op("elu", lambda a: jax.nn.elu(a, alpha),
                    (ensure_tensor(x),), {})


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply_op(
        "selu",
        lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)),
        (ensure_tensor(x),), {})


def celu(x, alpha=1.0, name=None):
    return apply_op("celu", lambda a: jax.nn.celu(a, alpha),
                    (ensure_tensor(x),), {})


def gelu(x, approximate=False, name=None):
    return apply_op("gelu", lambda a: jax.nn.gelu(a, approximate=approximate),
                    (ensure_tensor(x),), {})


def swish(x, name=None):
    return silu(x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    def fn(a):
        scaled = beta * a
        return jnp.where(scaled > threshold, a,
                         jnp.log1p(jnp.exp(jnp.minimum(scaled, threshold))) / beta)
    return apply_op("softplus", fn, (ensure_tensor(x),), {})


def softshrink(x, threshold=0.5, name=None):
    return apply_op(
        "softshrink",
        lambda a: jnp.where(a > threshold, a - threshold,
                            jnp.where(a < -threshold, a + threshold, 0.0)),
        (ensure_tensor(x),), {})


def hardshrink(x, threshold=0.5, name=None):
    return apply_op(
        "hardshrink",
        lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0),
        (ensure_tensor(x),), {})


def tanhshrink(x, name=None):
    return apply_op("tanhshrink", lambda a: a - jnp.tanh(a),
                    (ensure_tensor(x),), {})


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_op("hardtanh", lambda a: jnp.clip(a, min, max),
                    (ensure_tensor(x),), {})


def hardsigmoid(x, slope=1.0 / 6, offset=0.5, name=None):
    return apply_op("hardsigmoid",
                    lambda a: jnp.clip(slope * a + offset, 0.0, 1.0),
                    (ensure_tensor(x),), {})


def hardswish(x, name=None):
    return apply_op("hardswish",
                    lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0,
                    (ensure_tensor(x),), {})


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op("leaky_relu",
                    lambda a: jax.nn.leaky_relu(a, negative_slope),
                    (ensure_tensor(x),), {})


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply_op("thresholded_relu",
                    lambda a: jnp.where(a > threshold, a, value),
                    (ensure_tensor(x),), {})


def softmax(x, axis=-1, dtype=None, name=None):
    from ...framework import core
    dt = core.convert_dtype(dtype)
    def fn(a):
        if dt is not None:
            a = a.astype(dt)
        return jax.nn.softmax(a, axis=axis)
    return apply_op("softmax", fn, (ensure_tensor(x),), {})


def softmax_(x, axis=-1, dtype=None, name=None):
    from ...ops.dispatch import rebind_inplace
    return rebind_inplace(x, softmax(x, axis, dtype))


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...framework import core
    dt = core.convert_dtype(dtype)
    def fn(a):
        if dt is not None:
            a = a.astype(dt)
        return jax.nn.log_softmax(a, axis=axis)
    return apply_op("log_softmax", fn, (ensure_tensor(x),), {})


def maxout(x, groups, axis=1, name=None):
    def fn(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)
    return apply_op("maxout", fn, (ensure_tensor(x),), {})


def prelu(x, weight, data_format="NCHW", name=None):
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    def fn(a, w):
        if w.size > 1:
            ch_axis = 1 if data_format == "NCHW" else a.ndim - 1
            shape = [1] * a.ndim
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(a > 0, a, w * a)
    return apply_op("prelu", fn, (x, weight), {})


def rrelu(x, lower=0.125, upper=1.0 / 3, training=True, name=None):
    from ...framework import random as fr
    x = ensure_tensor(x)
    if training:
        slope = jax.random.uniform(fr.next_key(), tuple(x.shape),
                                   minval=lower, maxval=upper)
        return apply_op("rrelu", lambda a: jnp.where(a >= 0, a, slope * a),
                        (x,), {})
    mid = (lower + upper) / 2.0
    return apply_op("rrelu", lambda a: jnp.where(a >= 0, a, mid * a), (x,), {})


def glu(x, axis=-1, name=None):
    return apply_op("glu", lambda a: jax.nn.glu(a, axis=axis),
                    (ensure_tensor(x),), {})


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework import random as fr
    x = ensure_tensor(x)
    g = -jnp.log(-jnp.log(
        jax.random.uniform(fr.next_key(), tuple(x.shape), minval=1e-20,
                           maxval=1.0)))
    def fn(a):
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            onehot = jax.nn.one_hot(jnp.argmax(y, axis=axis), y.shape[axis],
                                    axis=axis, dtype=y.dtype)

            # straight-through estimator with a BITWISE-exact one-hot forward
            # (onehot + y - stop_grad(y) leaves float dust like 0.9999999)
            @jax.custom_vjp
            def st(soft):
                return onehot
            st.defvjp(lambda soft: (onehot, None), lambda _, ct: (ct,))
            return st(y)
        return y
    return apply_op("gumbel_softmax", fn, (x,), {})
