"""Common functionals: linear, dropout, embedding, pad, one_hot, interpolate
(python/paddle/nn/functional/common.py + input.py parity)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import core
from ...framework import random as fr
from ...framework.tensor import Tensor
from ...ops.dispatch import apply_op, ensure_tensor

__all__ = ["feature_alpha_dropout", "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
           "embedding", "one_hot", "pad", "zeropad2d", "unfold", "fold",
           "interpolate", "upsample", "pixel_shuffle", "pixel_unshuffle",
           "channel_shuffle", "cosine_similarity", "bilinear", "label_smooth",
           "class_center_sample", "normalize"]


def linear(x, weight, bias=None, name=None) -> Tensor:
    """y = x @ W + b; W is (in_features, out_features) like the reference
    (python/paddle/nn/functional/common.py linear)."""
    from ...ops.linalg import _mxu_precision
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    if bias is not None:
        bias = ensure_tensor(bias)
        return apply_op(
            "linear",
            lambda a, w, b: jnp.matmul(
                a, w, precision=_mxu_precision(a, w)) + b,
            (x, weight, bias), {})
    return apply_op(
        "linear",
        lambda a, w: jnp.matmul(a, w, precision=_mxu_precision(a, w)),
        (x, weight), {})


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None) -> Tensor:
    x = ensure_tensor(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply_op("dropout_infer", lambda a: a * (1 - p), (x,), {})
        return x.clone()
    shape = list(x.shape)
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        shape = [s if i in axes else 1 for i, s in enumerate(shape)]
    keep = jax.random.bernoulli(fr.next_key(), 1.0 - p, tuple(shape))
    def fn(a):
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)
    return apply_op("dropout", fn, (x,), {})


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None) -> Tensor:
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None) -> Tensor:
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None) -> Tensor:
    x = ensure_tensor(x)
    if not training or p == 0.0:
        return x.clone()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    keep = jax.random.bernoulli(fr.next_key(), 1.0 - p, tuple(x.shape))
    a_coef = (1.0 - p + p * alpha_p ** 2) ** -0.5
    b_coef = -a_coef * p * alpha_p
    return apply_op(
        "alpha_dropout",
        lambda a: (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(a.dtype),
        (x,), {})


def embedding(x, weight, padding_idx=None, sparse=False, name=None) -> Tensor:
    """Lookup rows of `weight` — on TPU a gather that XLA turns into a
    one-hot matmul or dynamic-gather depending on vocab size."""
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    pad_idx = padding_idx
    if pad_idx is not None and pad_idx < 0:
        pad_idx = weight.shape[0] + pad_idx
    def fn(ids, w):
        out = jnp.take(w, ids, axis=0)
        if pad_idx is not None:
            mask = (ids == pad_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return apply_op("embedding", fn, (x, weight), {})


def one_hot(x, num_classes, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("one_hot",
                    lambda a: jax.nn.one_hot(a, num_classes,
                                             dtype=core.get_default_dtype()),
                    (x,), {}, differentiable=False)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None) -> Tensor:
    x = ensure_tensor(x)
    if isinstance(pad, Tensor):
        pad = [int(p) for p in pad.numpy().reshape(-1)]
    pad = [int(p) for p in pad]
    nd = x.ndim

    if len(pad) == 2 * nd:
        cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle semantics: pad applies to the spatial dims per data_format,
        # listed innermost-first (W, H, D)
        n_spatial = len(pad) // 2
        cfg = [(0, 0)] * nd
        if data_format.startswith("NC"):
            spatial_axes = list(range(2, 2 + n_spatial))
        else:
            spatial_axes = list(range(1, 1 + n_spatial))
        for i, ax in enumerate(reversed(spatial_axes)):
            cfg[ax] = (pad[2 * i], pad[2 * i + 1])

    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
    def fn(a):
        if jmode == "constant":
            return jnp.pad(a, cfg, mode="constant", constant_values=value)
        return jnp.pad(a, cfg, mode=jmode)
    return apply_op("pad", fn, (x,), {})


def zeropad2d(x, padding, data_format="NCHW", name=None) -> Tensor:
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None) -> Tensor:
    """im2col (N,C,H,W) -> (N, C*kh*kw, L)."""
    x = ensure_tensor(x)
    kh, kw = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) \
        else kernel_sizes
    sh, sw = (strides, strides) if isinstance(strides, int) else strides
    dh, dw = (dilations, dilations) if isinstance(dilations, int) else dilations
    if isinstance(paddings, int):
        pt = pb = pl = pr = paddings
    elif len(paddings) == 2:
        pt = pb = paddings[0]; pl = pr = paddings[1]
    else:
        pt, pl, pb, pr = paddings

    def fn(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, ((0, 0), (0, 0), (pt, pb), (pl, pr)))
        hh, ww = a.shape[2], a.shape[3]
        oh = (hh - (dh * (kh - 1) + 1)) // sh + 1
        ow = (ww - (dw * (kw - 1) + 1)) // sw + 1
        patches = jax.lax.conv_general_dilated_patches(
            a, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # patches: (N, C*kh*kw, oh, ow)
        return patches.reshape(n, c * kh * kw, oh * ow)
    return apply_op("unfold", fn, (x,), {})


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None) -> Tensor:
    """col2im — adjoint of unfold."""
    x = ensure_tensor(x)
    oh, ow = (output_sizes, output_sizes) if isinstance(output_sizes, int) \
        else output_sizes
    def fwd(cols):
        n = cols.shape[0]
        c_kk = cols.shape[1]
        kh, kw = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) \
            else kernel_sizes
        c = c_kk // (kh * kw)
        zeros = jnp.zeros((n, c, oh, ow), cols.dtype)
        _, vjp = jax.vjp(
            lambda img: unfold_raw(img, kernel_sizes, strides, paddings,
                                   dilations), zeros)
        (out,) = vjp(cols)
        return out
    def unfold_raw(a, ks, st, pd, dl):
        t = Tensor(a)
        return unfold(t, ks, st, pd, dl)._data
    return apply_op("fold", fwd, (x,), {})


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None) -> Tensor:
    x = ensure_tensor(x)
    if data_format not in ("NCHW", "NHWC", "NCW", "NWC", "NCDHW", "NDHWC"):
        raise ValueError(f"bad data_format {data_format}")
    channel_last = not data_format.startswith("NC")
    nd = x.ndim
    n_spatial = nd - 2
    spatial_axes = (list(range(1, 1 + n_spatial)) if channel_last
                    else list(range(2, 2 + n_spatial)))
    in_sizes = [x.shape[a] for a in spatial_axes]
    if size is not None:
        if isinstance(size, Tensor):
            size = [int(s) for s in size.numpy().reshape(-1)]
        out_sizes = [int(s.item()) if isinstance(s, Tensor) else int(s)
                     for s in (size if isinstance(size, (list, tuple)) else [size])]
    else:
        sf = (scale_factor if isinstance(scale_factor, (list, tuple))
              else [scale_factor] * n_spatial)
        out_sizes = [int(i * float(s)) for i, s in zip(in_sizes, sf)]

    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def fn(a):
        out_shape = list(a.shape)
        for ax, s in zip(spatial_axes, out_sizes):
            out_shape[ax] = s
        if jmode == "nearest":
            idx = [jnp.floor(jnp.arange(s) * (in_sizes[i] / s)).astype(jnp.int32)
                   for i, s in enumerate(out_sizes)]
            out = a
            for i, ax in enumerate(spatial_axes):
                out = jnp.take(out, idx[i], axis=ax)
            return out
        return jax.image.resize(a, out_shape, method=jmode)
    return apply_op("interpolate", fn, (x,), {})


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None) -> Tensor:
    x = ensure_tensor(x)
    r = upscale_factor
    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, r, r, c // (r * r))
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, c // (r * r))
    return apply_op("pixel_shuffle", fn, (x,), {})


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None) -> Tensor:
    x = ensure_tensor(x)
    r = downscale_factor
    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            a = a.transpose(0, 1, 3, 5, 2, 4)
            return a.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h // r, w // r, c * r * r)
    return apply_op("pixel_unshuffle", fn, (x,), {})


def channel_shuffle(x, groups, data_format="NCHW", name=None) -> Tensor:
    x = ensure_tensor(x)
    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, groups, c // groups, h, w)
            return a.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, groups, c // groups)
        return a.transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)
    return apply_op("channel_shuffle", fn, (x,), {})


def cosine_similarity(x1, x2, axis=1, eps=1e-8) -> Tensor:
    x1, x2 = ensure_tensor(x1), ensure_tensor(x2)
    def fn(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return apply_op("cosine_similarity", fn, (x1, x2), {})


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None) -> Tensor:
    x = ensure_tensor(x)
    def fn(a):
        norm = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(norm, epsilon)
    return apply_op("normalize", fn, (x,), {})


def bilinear(x1, x2, weight, bias=None, name=None) -> Tensor:
    x1, x2, weight = ensure_tensor(x1), ensure_tensor(x2), ensure_tensor(weight)
    if bias is not None:
        bias = ensure_tensor(bias)
        return apply_op("bilinear",
                        lambda a, b, w, bi: jnp.einsum("bi,oij,bj->bo", a, w, b) + bi,
                        (x1, x2, weight, bias), {})
    return apply_op("bilinear",
                    lambda a, b, w: jnp.einsum("bi,oij,bj->bo", a, w, b),
                    (x1, x2, weight), {})


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None) -> Tensor:
    label = ensure_tensor(label)
    if prior_dist is not None:
        prior_dist = ensure_tensor(prior_dist)
        return apply_op("label_smooth",
                        lambda l, p: (1 - epsilon) * l + epsilon * p,
                        (label, prior_dist), {})
    k = label.shape[-1]
    return apply_op("label_smooth",
                    lambda l: (1 - epsilon) * l + epsilon / k, (label,), {})


def class_center_sample(label, num_classes, num_samples, group=None):
    label = ensure_tensor(label)
    pos = np.unique(np.asarray(label._data))
    n_extra = max(0, num_samples - pos.size)
    rest = np.setdiff1d(np.arange(num_classes), pos)
    extra = np.random.choice(rest, size=min(n_extra, rest.size), replace=False) \
        if n_extra else np.array([], np.int64)
    sampled = np.sort(np.concatenate([pos, extra])).astype(np.int32)
    remap = -np.ones(num_classes, np.int32)
    remap[sampled] = np.arange(sampled.size)
    remapped = remap[np.asarray(label._data)]
    return (Tensor(jnp.asarray(remapped)), Tensor(jnp.asarray(sampled)))


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """Alpha dropout over whole feature maps (functional parity): drops
    entire channels to the SELU saturation value, preserving mean/var."""
    if not 0.0 <= p <= 1.0:
        raise ValueError(f"dropout probability must be in [0, 1], got {p}")
    x = ensure_tensor(x)
    if not training or p == 0.0:
        return x
    from ...framework import random as fr
    import jax as _jax
    alpha_p = -1.7580993408473766  # -scale * alpha of SELU
    if p == 1.0:  # everything dropped: the affine of the constant
        return apply_op("feature_alpha_dropout",
                        lambda a: jnp.full_like(a, 0.0), (x,), {})
    key = fr.next_key()
    mask_shape = tuple(x.shape[:2]) + (1,) * (x.ndim - 2)
    keep = _jax.random.bernoulli(key, 1.0 - p, mask_shape)

    def f(a):
        a_ = (1.0 / ((1 - p) * (1 + p * alpha_p ** 2)) ** 0.5)
        b_ = -a_ * alpha_p * p
        out = jnp.where(keep, a, alpha_p)
        return out * a_ + b_
    return apply_op("feature_alpha_dropout", f, (x,), {})
