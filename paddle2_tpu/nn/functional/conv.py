"""Convolution functionals (python/paddle/nn/functional/conv.py parity).

Lowered to lax.conv_general_dilated — THE conv path onto the TPU MXU; XLA
picks the layout, so the NCHW-default paddle API costs nothing vs NHWC.
"""

from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp

from ...framework.tensor import Tensor
from ...ops.dispatch import apply_op, ensure_tensor

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose"]


def _tuplize(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


def _padding(padding, n, stride, dilation, kernel):
    """Resolve paddle padding spec → lax padding list or string."""
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1]))
                for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, n_spatial,
          data_format, op_name):
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    stride = _tuplize(stride, n_spatial)
    dilation = _tuplize(dilation, n_spatial)
    channel_last = not data_format.startswith("NC")
    spatial = "DHW"[-n_spatial:]
    if channel_last:
        lhs_spec = "N" + spatial + "C"
    else:
        lhs_spec = "NC" + spatial
    rhs_spec = "OI" + spatial
    out_spec = lhs_spec
    pad = _padding(padding, n_spatial, stride, dilation, None)
    dn = jax.lax.conv_dimension_numbers(
        tuple(x.shape), tuple(weight.shape), (lhs_spec, rhs_spec, out_spec))

    from ...flags import flag_value
    # internal channels-last: the TPU conv path is measurably faster in
    # NHWC (1.26x on v5e for a ResNet 3x3 block); the API stays NCHW and
    # XLA cancels the paired transposes between consecutive convs
    to_nhwc = (not channel_last and n_spatial == 2 and groups == 1
               and flag_value("conv_prefer_channels_last"))
    if to_nhwc:
        lhs2 = "N" + spatial + "C"
        dn_nhwc = jax.lax.conv_dimension_numbers(
            (x.shape[0],) + tuple(x.shape[2:]) + (x.shape[1],),
            tuple(weight.shape), (lhs2, rhs_spec, lhs2))

    def fn(a, w, *maybe_b):
        from ...ops.linalg import _mxu_precision
        if to_nhwc:
            a2 = jnp.transpose(a, (0, 2, 3, 1))
            out = jax.lax.conv_general_dilated(
                a2, w, window_strides=stride, padding=pad,
                rhs_dilation=dilation, dimension_numbers=dn_nhwc,
                feature_group_count=groups,
                precision=_mxu_precision(a, w),
                preferred_element_type=None)
            if maybe_b:
                out = out + maybe_b[0].reshape((1, 1, 1, -1))
            return jnp.transpose(out, (0, 3, 1, 2))
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups,
            precision=_mxu_precision(a, w),
            preferred_element_type=None)
        if maybe_b:
            b = maybe_b[0]
            shape = [1] * out.ndim
            shape[1 if not channel_last else out.ndim - 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    args = (x, weight) if bias is None else (x, weight, ensure_tensor(bias))
    return apply_op(op_name, fn, args, {})


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None) -> Tensor:
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 "NCW" if data_format == "NCL" else "NWC", "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None) -> Tensor:
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None) -> Tensor:
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, "conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, n_spatial, data_format, op_name, output_size=None):
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    stride = _tuplize(stride, n_spatial)
    dilation = _tuplize(dilation, n_spatial)
    out_pad = _tuplize(output_padding, n_spatial)
    channel_last = not data_format.startswith("NC")
    spatial = "DHW"[-n_spatial:]
    lhs_spec = ("N" + spatial + "C") if channel_last else ("NC" + spatial)
    # paddle conv_transpose weight layout: (in_channels, out_channels/groups, *k)
    rhs_spec = "IO" + spatial
    dn = jax.lax.conv_dimension_numbers(
        tuple(x.shape), tuple(weight.shape), (lhs_spec, rhs_spec, lhs_spec))

    if isinstance(padding, str):
        pad_cfg = padding.upper()
    else:
        base = _padding(padding, n_spatial, stride, dilation, None)
        kernel = weight.shape[2:]
        # gradient-of-conv padding: lo = dilation*(k-1) - pad_lo
        pad_cfg = []
        for i in range(n_spatial):
            k_eff = dilation[i] * (kernel[i] - 1)
            lo, hi = base[i]
            pad_cfg.append((k_eff - lo, k_eff - hi + out_pad[i]))

    def fn(a, w, *maybe_b):
        w_flipped = jnp.flip(w, axis=tuple(range(2, 2 + n_spatial)))
        if groups > 1:
            # lax grouped conv wants rhs I = C_in/groups with O blocked by
            # group; regroup (C_in, C_out/g, k) -> (C_in/g, C_out, k) so
            # output block i consumes input block i.
            cin, cog = w_flipped.shape[0], w_flipped.shape[1]
            k = w_flipped.shape[2:]
            w_flipped = (w_flipped
                         .reshape((groups, cin // groups, cog) + k)
                         .transpose((1, 0, 2) + tuple(range(3, 3 + n_spatial)))
                         .reshape((cin // groups, groups * cog) + k))
        from ...ops.linalg import _mxu_precision
        out = jax.lax.conv_general_dilated(
            a, w_flipped, window_strides=(1,) * n_spatial,
            padding=pad_cfg if not isinstance(pad_cfg, str) else pad_cfg,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups,
            precision=_mxu_precision(a, w_flipped))
        if maybe_b:
            b = maybe_b[0]
            shape = [1] * out.ndim
            shape[1 if not channel_last else out.ndim - 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    args = (x, weight) if bias is None else (x, weight, ensure_tensor(bias))
    return apply_op(op_name, fn, args, {})


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None) -> Tensor:
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1,
                           "NCW" if data_format == "NCL" else "NWC",
                           "conv1d_transpose", output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCHW", name=None) -> Tensor:
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format,
                           "conv2d_transpose", output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None) -> Tensor:
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format,
                           "conv3d_transpose", output_size)
