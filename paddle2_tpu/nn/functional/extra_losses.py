"""Additional loss/distance functionals (reference
python/paddle/nn/functional/loss.py + distance.py surface widening)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...framework.tensor import Tensor
from ...ops.dispatch import apply_op, ensure_tensor

__all__ = ["pairwise_distance", "soft_margin_loss",
           "multi_label_soft_margin_loss", "multi_margin_loss",
           "gaussian_nll_loss", "triplet_margin_with_distance_loss",
           "dice_loss", "npair_loss", "gather_tree", "temporal_shift"]


def _reduce(val, reduction):
    if reduction == "mean":
        return jnp.mean(val)
    if reduction == "sum":
        return jnp.sum(val)
    return val


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """distance.py pairwise_distance."""
    def f(a, b):
        d = a - b + epsilon
        return jnp.linalg.norm(d, ord=p, axis=-1, keepdims=keepdim)
    return apply_op("pairwise_distance", f,
                    (ensure_tensor(x), ensure_tensor(y)), {})


def soft_margin_loss(input, label, reduction="mean", name=None):
    def f(x, y):
        return _reduce(jnp.log1p(jnp.exp(-y * x)), reduction)
    return apply_op("soft_margin_loss", f,
                    (ensure_tensor(input), ensure_tensor(label)), {})


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    ts = [ensure_tensor(input), ensure_tensor(label)]
    if weight is not None:
        ts.append(ensure_tensor(weight))

    def f(x, y, *w):
        loss = -(y * jax.nn.log_sigmoid(x)
                 + (1 - y) * jax.nn.log_sigmoid(-x))
        if w:
            loss = loss * w[0]
        return _reduce(jnp.mean(loss, axis=-1), reduction)
    return apply_op("multi_label_soft_margin_loss", f, tuple(ts), {})


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    ts = [ensure_tensor(input), ensure_tensor(label)]
    if weight is not None:
        ts.append(ensure_tensor(weight))

    def f(x, y, *w):
        n, c = x.shape
        correct = jnp.take_along_axis(x, y[:, None].astype(jnp.int32), 1)
        m = jnp.maximum(0.0, margin - correct + x) ** p
        if w:
            m = m * w[0][y.astype(jnp.int32)][:, None]
        mask = jax.nn.one_hot(y.astype(jnp.int32), c) == 0
        return _reduce(jnp.sum(m * mask, axis=1) / c, reduction)
    return apply_op("multi_margin_loss", f, tuple(ts), {})


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def f(mu, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + jnp.square(y - mu) / var)
        if full:
            loss = loss + 0.5 * jnp.log(2 * jnp.pi)
        return _reduce(loss, reduction)
    return apply_op("gaussian_nll_loss", f,
                    (ensure_tensor(input), ensure_tensor(label),
                     ensure_tensor(variance)), {})


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    a = ensure_tensor(input)
    p_ = ensure_tensor(positive)
    n_ = ensure_tensor(negative)
    dist = distance_function or (lambda u, v: pairwise_distance(u, v))
    dp = ensure_tensor(dist(a, p_))
    dn = ensure_tensor(dist(a, n_))
    if swap:
        dpn = ensure_tensor(dist(p_, n_))
        dn = apply_op("min", lambda u, v: jnp.minimum(u, v), (dn, dpn), {})

    def f(u, v):
        return _reduce(jnp.maximum(0.0, u - v + margin), reduction)
    return apply_op("triplet_margin_with_distance_loss", f, (dp, dn), {})


def dice_loss(input, label, epsilon=1e-5, name=None):
    """loss.py dice_loss: input [N, ..., C] probs, label [N, ..., 1]."""
    def f(x, y):
        c = x.shape[-1]
        oh = jax.nn.one_hot(y[..., 0].astype(jnp.int32), c, dtype=x.dtype)
        inter = jnp.sum(x * oh, axis=tuple(range(1, x.ndim)))
        union = jnp.sum(x + oh, axis=tuple(range(1, x.ndim)))
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return apply_op("dice_loss", f,
                    (ensure_tensor(input), ensure_tensor(label)), {})


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """loss.py npair_loss (improved deep metric learning)."""
    def f(a, p, y):
        sim = a @ p.T                              # [N, N]
        same = (y[:, None] == y[None, :]).astype(a.dtype)
        tgt = same / jnp.maximum(same.sum(1, keepdims=True), 1.0)
        logp = jax.nn.log_softmax(sim, axis=1)
        xent = -jnp.mean(jnp.sum(tgt * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1))
                        + jnp.mean(jnp.sum(p * p, 1))) * 0.25
        return xent + reg
    return apply_op("npair_loss", f,
                    (ensure_tensor(anchor), ensure_tensor(positive),
                     ensure_tensor(labels)), {})


def gather_tree(ids, parents):
    """functional/extension.py gather_tree: beam-search backtrace.
    ids/parents: [T, B, beam]."""
    def f(i, par):
        T = i.shape[0]

        def step(carry, t):
            beams = carry                       # [B, beam] current beam idx
            tok = jnp.take_along_axis(i[t], beams, axis=1)
            beams = jnp.take_along_axis(par[t], beams, axis=1)
            return beams, tok
        init = jnp.broadcast_to(jnp.arange(i.shape[2])[None, :],
                                i.shape[1:])
        _, toks = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return jnp.flip(toks, axis=0)
    return apply_op("gather_tree", f,
                    (ensure_tensor(ids), ensure_tensor(parents)), {},
                    differentiable=False)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    """functional/extension.py temporal_shift (TSM video models)."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"unknown data_format {data_format!r}")

    def f(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        nt, c, h, w = a.shape
        n = nt // seg_num
        v = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        back = jnp.concatenate([v[:, 1:, :fold],
                                jnp.zeros_like(v[:, :1, :fold])], axis=1)
        fwd = jnp.concatenate([jnp.zeros_like(v[:, :1, fold:2 * fold]),
                               v[:, :-1, fold:2 * fold]], axis=1)
        keep = v[:, :, 2 * fold:]
        out = jnp.concatenate([back, fwd, keep],
                              axis=2).reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out
    return apply_op("temporal_shift", f, (ensure_tensor(x),), {})
