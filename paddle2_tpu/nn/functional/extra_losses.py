"""Additional loss/distance functionals (reference
python/paddle/nn/functional/loss.py + distance.py surface widening)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.tensor import Tensor
from ...ops.dispatch import apply_op, ensure_tensor

__all__ = ["pairwise_distance", "soft_margin_loss",
           "multi_label_soft_margin_loss", "multi_margin_loss",
           "gaussian_nll_loss", "triplet_margin_with_distance_loss",
           "dice_loss", "npair_loss", "gather_tree", "temporal_shift",
           "hsigmoid_loss", "adaptive_log_softmax_with_loss", "rnnt_loss"]


def _reduce(val, reduction):
    if reduction == "mean":
        return jnp.mean(val)
    if reduction == "sum":
        return jnp.sum(val)
    return val


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """distance.py pairwise_distance."""
    def f(a, b):
        d = a - b + epsilon
        return jnp.linalg.norm(d, ord=p, axis=-1, keepdims=keepdim)
    return apply_op("pairwise_distance", f,
                    (ensure_tensor(x), ensure_tensor(y)), {})


def soft_margin_loss(input, label, reduction="mean", name=None):
    def f(x, y):
        return _reduce(jnp.log1p(jnp.exp(-y * x)), reduction)
    return apply_op("soft_margin_loss", f,
                    (ensure_tensor(input), ensure_tensor(label)), {})


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    ts = [ensure_tensor(input), ensure_tensor(label)]
    if weight is not None:
        ts.append(ensure_tensor(weight))

    def f(x, y, *w):
        loss = -(y * jax.nn.log_sigmoid(x)
                 + (1 - y) * jax.nn.log_sigmoid(-x))
        if w:
            loss = loss * w[0]
        return _reduce(jnp.mean(loss, axis=-1), reduction)
    return apply_op("multi_label_soft_margin_loss", f, tuple(ts), {})


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    ts = [ensure_tensor(input), ensure_tensor(label)]
    if weight is not None:
        ts.append(ensure_tensor(weight))

    def f(x, y, *w):
        n, c = x.shape
        correct = jnp.take_along_axis(x, y[:, None].astype(jnp.int32), 1)
        m = jnp.maximum(0.0, margin - correct + x) ** p
        if w:
            m = m * w[0][y.astype(jnp.int32)][:, None]
        mask = jax.nn.one_hot(y.astype(jnp.int32), c) == 0
        return _reduce(jnp.sum(m * mask, axis=1) / c, reduction)
    return apply_op("multi_margin_loss", f, tuple(ts), {})


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def f(mu, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + jnp.square(y - mu) / var)
        if full:
            loss = loss + 0.5 * jnp.log(2 * jnp.pi)
        return _reduce(loss, reduction)
    return apply_op("gaussian_nll_loss", f,
                    (ensure_tensor(input), ensure_tensor(label),
                     ensure_tensor(variance)), {})


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    a = ensure_tensor(input)
    p_ = ensure_tensor(positive)
    n_ = ensure_tensor(negative)
    dist = distance_function or (lambda u, v: pairwise_distance(u, v))
    dp = ensure_tensor(dist(a, p_))
    dn = ensure_tensor(dist(a, n_))
    if swap:
        dpn = ensure_tensor(dist(p_, n_))
        dn = apply_op("min", lambda u, v: jnp.minimum(u, v), (dn, dpn), {})

    def f(u, v):
        return _reduce(jnp.maximum(0.0, u - v + margin), reduction)
    return apply_op("triplet_margin_with_distance_loss", f, (dp, dn), {})


def dice_loss(input, label, epsilon=1e-5, name=None):
    """loss.py dice_loss: input [N, ..., C] probs, label [N, ..., 1]."""
    def f(x, y):
        c = x.shape[-1]
        oh = jax.nn.one_hot(y[..., 0].astype(jnp.int32), c, dtype=x.dtype)
        inter = jnp.sum(x * oh, axis=tuple(range(1, x.ndim)))
        union = jnp.sum(x + oh, axis=tuple(range(1, x.ndim)))
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return apply_op("dice_loss", f,
                    (ensure_tensor(input), ensure_tensor(label)), {})


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """loss.py npair_loss (improved deep metric learning)."""
    def f(a, p, y):
        sim = a @ p.T                              # [N, N]
        same = (y[:, None] == y[None, :]).astype(a.dtype)
        tgt = same / jnp.maximum(same.sum(1, keepdims=True), 1.0)
        logp = jax.nn.log_softmax(sim, axis=1)
        xent = -jnp.mean(jnp.sum(tgt * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1))
                        + jnp.mean(jnp.sum(p * p, 1))) * 0.25
        return xent + reg
    return apply_op("npair_loss", f,
                    (ensure_tensor(anchor), ensure_tensor(positive),
                     ensure_tensor(labels)), {})


def gather_tree(ids, parents):
    """functional/extension.py gather_tree: beam-search backtrace.
    ids/parents: [T, B, beam]."""
    def f(i, par):
        T = i.shape[0]

        def step(carry, t):
            beams = carry                       # [B, beam] current beam idx
            tok = jnp.take_along_axis(i[t], beams, axis=1)
            beams = jnp.take_along_axis(par[t], beams, axis=1)
            return beams, tok
        init = jnp.broadcast_to(jnp.arange(i.shape[2])[None, :],
                                i.shape[1:])
        _, toks = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return jnp.flip(toks, axis=0)
    return apply_op("gather_tree", f,
                    (ensure_tensor(ids), ensure_tensor(parents)), {},
                    differentiable=False)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    """functional/extension.py temporal_shift (TSM video models)."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"unknown data_format {data_format!r}")

    def f(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        nt, c, h, w = a.shape
        n = nt // seg_num
        v = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        back = jnp.concatenate([v[:, 1:, :fold],
                                jnp.zeros_like(v[:, :1, :fold])], axis=1)
        fwd = jnp.concatenate([jnp.zeros_like(v[:, :1, fold:2 * fold]),
                               v[:, :-1, fold:2 * fold]], axis=1)
        keep = v[:, :, 2 * fold:]
        out = jnp.concatenate([back, fwd, keep],
                              axis=2).reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out
    return apply_op("temporal_shift", f, (ensure_tensor(x),), {})


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference loss.py hsigmoid_loss;
    kernel hsigmoid_loss_kernel.cc + matrix_bit_code.h SimpleCode).

    Complete-tree mode: class c encodes as ``c + num_classes``; walking
    the bits of the code from LSB gives, per level j, the internal-node
    row ``(code >> (j+1)) - 1`` and the binary target ``(code >> j) & 1``.
    Loss per sample = sum over path of BCE-with-logits(w_row . x + b_row,
    bit), logits clipped to [-40, 40] like the kernel.  Custom-tree mode
    takes ``path_table``/``path_code`` (-1-padded) directly."""
    tensors = [ensure_tensor(input), ensure_tensor(label),
               ensure_tensor(weight)]
    has_bias = bias is not None
    if has_bias:
        tensors.append(ensure_tensor(bias))
    custom = path_table is not None
    if custom:
        tensors.append(ensure_tensor(path_table))
        tensors.append(ensure_tensor(path_code))

    def fn(x, lab, w, *rest):
        b = rest[0] if has_bias else None
        if custom:
            ptab = rest[-2].astype(jnp.int32)   # [N, L] rows, -1 pad
            pcode = rest[-1].astype(jnp.int32)  # [N, L] bits
            valid = ptab >= 0
            rows = jnp.clip(ptab, 0)
            bits = pcode.astype(jnp.float32)
        else:
            code = (lab.astype(jnp.int32).reshape(-1)
                    + jnp.int32(num_classes))   # [N]
            L = int(np.ceil(np.log2(2 * num_classes)))
            j = jnp.arange(L)
            shifted = code[:, None] >> (j[None, :] + 1)
            valid = shifted > 0                  # bit within path length
            rows = jnp.clip(shifted - 1, 0)
            bits = ((code[:, None] >> j[None, :]) & 1).astype(jnp.float32)
        wr = jnp.take(w, rows, axis=0)           # [N, L, F]
        z = jnp.einsum("nlf,nf->nl", wr, x)
        if b is not None:
            z = z + jnp.take(b.reshape(-1), rows)
        z = jnp.clip(z, -40.0, 40.0)
        # BCE with logits: softplus(z) - bit * z
        per = jnp.logaddexp(0.0, z) - bits * z
        per = jnp.where(valid, per, 0.0)
        return jnp.sum(per, axis=1, keepdims=True)

    return apply_op("hsigmoid_loss", fn, tuple(tensors), {})


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Adaptive softmax (reference loss.py:4461): frequent classes score
    in the head shortlist; rare classes live in clusters reached through
    a cluster logit and a low-rank tail projection. Returns (per-sample
    target log-prob, nll loss = -mean)."""
    cutoffs = [int(c) for c in cutoffs]
    n_clusters = len(cutoffs)
    shortlist = cutoffs[0] if cutoffs else 0
    cutoff_ends = [0] + cutoffs
    tensors = [ensure_tensor(input), ensure_tensor(label),
               ensure_tensor(head_weight)]
    has_bias = head_bias is not None
    if has_bias:
        tensors.append(ensure_tensor(head_bias))
    flat_tails = []
    for pair in tail_weights:
        flat_tails.extend([ensure_tensor(pair[0]), ensure_tensor(pair[1])])
    tensors.extend(flat_tails)

    def fn(x, lab, hw, *rest):
        hb = rest[0] if has_bias else None
        tails = rest[1 if has_bias else 0:]
        lab_i = lab.astype(jnp.int32).reshape(-1)
        head = x @ hw                           # [N, shortlist+K]
        if hb is not None:
            head = head + hb
        head_lp = jax.nn.log_softmax(head, axis=-1)
        # shortlist targets read head directly; clamp for gather safety
        out = jnp.take_along_axis(
            head_lp, jnp.clip(lab_i, 0, shortlist - 1)[:, None],
            axis=1)[:, 0]
        for k in range(n_clusters):
            lo = cutoffs[k]
            hi = cutoffs[k + 1] if k + 1 < n_clusters else None
            proj, ow = tails[2 * k], tails[2 * k + 1]
            csize = ow.shape[1]
            tail_lp = jax.nn.log_softmax((x @ proj) @ ow, axis=-1)
            in_k = (lab_i >= lo) & ((lab_i < hi) if hi is not None
                                    else jnp.full_like(lab_i, True,
                                                       dtype=bool))
            local = jnp.clip(lab_i - lo, 0, csize - 1)
            cluster_lp = head_lp[:, shortlist + k]
            cand = cluster_lp + jnp.take_along_axis(
                tail_lp, local[:, None], axis=1)[:, 0]
            out = jnp.where(in_k, cand, out)
        return out, -jnp.mean(out)

    return apply_op("adaptive_log_softmax", fn, tuple(tensors), {})


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-T transducer loss (reference loss.py:2055, warp-transducer).

    Forward-variable DP as lax.scan over T with the U axis vectorized:
      alpha[t, u] = logaddexp(alpha[t-1, u] + blank(t-1, u),
                              alpha[t, u-1] + emit(t, u-1))
    run entirely in log space; -(alpha[T-1, U] + blank(T-1, U)) is the
    NLL. FastEmit scales the emission terms' GRADIENT by (1+lambda)
    with the loss value unchanged (warp_transducer's formulation),
    expressed as y*(1+l) - stop_gradient(y*l)."""
    tensors = [ensure_tensor(input), ensure_tensor(label),
               ensure_tensor(input_lengths), ensure_tensor(label_lengths)]

    def fn(logits, labels, t_lens, u_lens):
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        B, T, U1, V = lp.shape
        U = U1 - 1
        labels_i = labels.astype(jnp.int32)
        blank_lp = lp[..., blank]               # [B, T, U+1]
        emit_lp = jnp.take_along_axis(
            lp[:, :, :U, :],
            jnp.broadcast_to(labels_i[:, None, :, None], (B, T, U, 1)),
            axis=3)[..., 0]                      # [B, T, U]
        if fastemit_lambda:
            lam = float(fastemit_lambda)
            emit_lp = (emit_lp * (1.0 + lam)
                       - jax.lax.stop_gradient(emit_lp * lam))
        neg = jnp.float32(-1e30)
        u_idx = jnp.arange(U1)

        def step(alpha_prev, t):
            # horizontal move: blank at (t-1, u) keeps u
            from_blank = jnp.where(
                t > 0, alpha_prev + blank_lp[:, jnp.maximum(t - 1, 0), :],
                jnp.where(u_idx[None, :] == 0, 0.0, neg))
            # init: alpha[0, 0] = 0; alpha[0, u>0] via vertical scan below
            def vert(carry, u):
                # vertical move: emit label u-1 at (t, u-1)
                val = jnp.where(
                    u > 0,
                    jnp.logaddexp(
                        from_blank[:, u],
                        carry + emit_lp[:, t, jnp.maximum(u - 1, 0)]),
                    from_blank[:, u])
                return val, val
            _, cols = jax.lax.scan(vert, jnp.full((B,), neg), u_idx)
            alpha = jnp.transpose(cols)          # [B, U+1]
            return alpha, alpha

        init = jnp.full((B, U1), neg)
        _, alphas = jax.lax.scan(step, init, jnp.arange(T))
        alphas = jnp.transpose(alphas, (1, 0, 2))   # [B, T, U+1]
        t_last = jnp.clip(t_lens.astype(jnp.int32) - 1, 0)
        u_last = jnp.clip(u_lens.astype(jnp.int32), 0)
        a_fin = alphas[jnp.arange(B), t_last, u_last]
        b_fin = blank_lp[jnp.arange(B), t_last, u_last]
        nll = -(a_fin + b_fin)
        if reduction == "mean":
            # warp-transducer averages over batch
            return jnp.mean(nll)
        if reduction == "sum":
            return jnp.sum(nll)
        return nll

    return apply_op("rnnt_loss", fn, tuple(tensors), {})
