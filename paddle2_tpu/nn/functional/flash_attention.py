"""paddle.nn.functional.flash_attention (reference
python/paddle/nn/functional/flash_attention.py:195 flash_attention,
:593 flash_attn_unpadded, plus scaled_dot_product_attention re-export).

All paths route through the kernel selector in kernels/attention.py: the
Pallas flash kernel on TPU for long sequences, the XLA fused path
otherwise. Layout is the reference's (batch, seq, heads, head_dim).

``flash_attn_unpadded`` (varlen, cu_seqlens) is served by densifying into
a padded batch with a length mask — static shapes for jit; the packed
CUDA layout has no XLA analog, and padded+masked is the TPU-idiomatic
equivalent.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.tensor import Tensor
from ...kernels.attention import (_sdpa_xla,
                                  scaled_dot_product_attention)
from ...ops.dispatch import apply_op, ensure_tensor

__all__ = ["flash_attention", "flash_attn_unpadded", "flash_attn_qkvpacked",
           "flash_attn_varlen_qkvpacked",
           "scaled_dot_product_attention", "sdp_kernel", "flashmask_attention", "sparse_attention"]


def flash_attention(query, key, value, dropout: float = 0.0,
                    causal: bool = False, return_softmax: bool = False,
                    *, fixed_seed_offset=None, rng_name: str = "",
                    training: bool = True, name=None):
    """flash_attention.py:195 parity: returns (out, softmax) — softmax is
    None unless return_softmax (which forces the XLA path: the flash
    kernel never materializes probabilities, that is its point)."""
    out = scaled_dot_product_attention(query, key, value,
                                       dropout_p=dropout, is_causal=causal,
                                       training=training)
    softmax = None
    if return_softmax:
        q, k = ensure_tensor(query), ensure_tensor(key)

        def probs(qa, ka):
            import math
            qh = jnp.swapaxes(qa, 1, 2).astype(jnp.float32)
            kh = jnp.swapaxes(ka, 1, 2).astype(jnp.float32)
            s = jnp.einsum("bhsd,bhtd->bhst", qh, kh) \
                / math.sqrt(qa.shape[-1])
            if causal:
                t_q, t_k = s.shape[-2], s.shape[-1]
                mask = jnp.tril(jnp.ones((t_q, t_k), bool), k=t_k - t_q)
                s = jnp.where(mask, s, -jnp.inf)
            return jax.nn.softmax(s, axis=-1)
        softmax = apply_op("flash_softmax", probs, (q, k), {},
                           differentiable=False)
    return out, softmax


def flash_attn_qkvpacked(qkv, dropout: float = 0.0, causal: bool = False,
                         return_softmax: bool = False, **kwargs):
    """Packed [b, s, 3, h, d] variant (flash_attention.py qkvpacked)."""
    t = ensure_tensor(qkv)
    q, k, v = t[:, :, 0], t[:, :, 1], t[:, :, 2]
    return flash_attention(q, k, v, dropout=dropout, causal=causal,
                           return_softmax=return_softmax, **kwargs)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q: int, max_seqlen_k: int, scale: float,
                        dropout: float = 0.0, causal: bool = False,
                        return_softmax: bool = False, *,
                        fixed_seed_offset=None, rng_name: str = "",
                        training: bool = True, name=None):
    """Varlen attention over packed sequences (flash_attention.py:593).

    query/key/value: [total_tokens, heads, dim] packed rows;
    cu_seqlens_*: [batch+1] cumulative offsets. Densified to a padded
    [b, max_seqlen, h, d] batch; padding keys are masked out of the
    softmax and padded query rows are zeroed on output re-packing.
    """
    import numpy as np
    if return_softmax:
        raise NotImplementedError(
            "flash_attn_unpadded(return_softmax=True): the varlen path "
            "never materializes probabilities; use flash_attention")
    q = ensure_tensor(query)
    k = ensure_tensor(key)
    v = ensure_tensor(value)
    cu_q = np.asarray(ensure_tensor(cu_seqlens_q).numpy()).astype(np.int64)
    cu_k = np.asarray(ensure_tensor(cu_seqlens_k).numpy()).astype(np.int64)
    B = len(cu_q) - 1
    Sq, Sk = int(max_seqlen_q), int(max_seqlen_k)
    len_q = cu_q[1:] - cu_q[:-1]
    len_k = cu_k[1:] - cu_k[:-1]
    drop_key = None
    if dropout > 0.0 and training:
        from ...framework import random as fr
        drop_key = fr.next_key()

    # packed pallas path: the ragged batch stays ONE [T, H, D] packed
    # sequence with per-row segment ids — no O(B*Smax^2) densify
    from ...kernels.attention import flash_enabled
    try:
        on_accel = jax.devices()[0].platform.lower() != "cpu"
    except Exception:
        on_accel = False
    head_dim = int(q.shape[-1])
    if (on_accel and flash_enabled() and drop_key is None
            and head_dim <= 256):   # pallas kernel range (supported())
        return _unpadded_packed(q, k, v, cu_q, cu_k, len_q, len_k,
                                scale, causal), None

    def _row_index(cu, lens, S):
        # [B, S] gather map into the packed rows; out-of-range positions
        # point at a sentinel zero row appended to the source
        idx = np.zeros((B, S), np.int64)
        for i in range(B):
            L = int(lens[i])
            idx[i, :L] = np.arange(int(cu[i]), int(cu[i]) + L)
            idx[i, L:] = -1  # sentinel (last row after the append below)
        return jnp.asarray(idx)

    iq_map = _row_index(cu_q, len_q, Sq)
    ik_map = _row_index(cu_k, len_k, Sk)

    def run(qa, ka, va):
        # one gather per tensor (sentinel row = zeros) instead of B
        # sequential full-buffer scatter copies
        def pad_one(arr, idx):
            with_sentinel = jnp.concatenate(
                [arr, jnp.zeros((1,) + arr.shape[1:], arr.dtype)], axis=0)
            return with_sentinel[idx]
        qp = pad_one(qa, iq_map)
        kp = pad_one(ka, ik_map)
        vp = pad_one(va, ik_map)
        # per-sequence mask: key must be real, and under causal each
        # query position may only see keys up to its own bottom-right
        # aligned diagonal len_k[i] - len_q[i] + qpos (PER ROW — the
        # padded maxes differ from each sequence's true lengths)
        lk = jnp.asarray(len_k)[:, None, None]            # [B,1,1]
        lq = jnp.asarray(len_q)[:, None, None]
        qpos = jnp.arange(Sq)[None, :, None]              # [1,Sq,1]
        kpos = jnp.arange(Sk)[None, None, :]              # [1,1,Sk]
        allowed = kpos < lk
        if causal:
            allowed = allowed & (kpos <= qpos + (lk - lq))
        bias = jnp.where(allowed, 0.0, -jnp.inf)[:, None]  # [B,1,Sq,Sk]
        out = _sdpa_xla(qp, kp, vp, bias=bias, causal=False,
                        scale=scale,
                        dropout_p=dropout if drop_key is not None else 0.0,
                        dropout_key=drop_key)
        # re-pack valid query rows with ONE gather (a per-sequence slice
        # loop would emit B dynamic-slices + concatenate)
        seq_of_row = np.repeat(np.arange(B), len_q.astype(np.int64))
        pos_of_row = (np.arange(int(cu_q[-1]))
                      - np.repeat(cu_q[:-1], len_q.astype(np.int64)))
        return out[jnp.asarray(seq_of_row), jnp.asarray(pos_of_row)]
    out = apply_op("flash_attn_unpadded", run, (q, k, v), {})
    return out, None


_SEG_CACHE: dict = {}


def _seg_off_device(cu_q, cu_k, len_q, len_k, causal):
    """Per-row (segment, causal-offset) metadata as DEVICE arrays, memoized
    on the cu_seqlens bytes — a bucketed training loop pays the host loop
    and the four uploads once per bucket, not once per step."""
    import numpy as np
    key = (cu_q.tobytes(), cu_k.tobytes(), bool(causal))
    hit = _SEG_CACHE.get(key)
    if hit is not None:
        return hit

    def seg_off(cu, lens, pad_id):
        T = int(cu[-1])
        seg = np.full(T, 0, np.int32)
        off = np.zeros(T, np.int32)
        for i in range(len(lens)):
            a, b = int(cu[i]), int(cu[i + 1])
            seg[a:b] = i
            off[a:b] = np.arange(b - a)
        Tp = -(-max(T, 8) // 8) * 8
        if Tp != T:
            seg = np.concatenate([seg, np.full(Tp - T, pad_id, np.int32)])
            off = np.concatenate([off, np.zeros(Tp - T, np.int32)])
        return seg, off, T, Tp

    seg_q, off_q, Tq, Tqp = seg_off(cu_q, len_q, -1)
    seg_k, off_k, Tk, Tkp = seg_off(cu_k, len_k, -2)
    if causal:
        # bottom-right alignment per sequence: q row allowance shifts by
        # (len_k - len_q) of its sequence
        for i in range(len(len_q)):
            a, b = int(cu_q[i]), int(cu_q[i + 1])
            off_q[a:b] = off_q[a:b] + int(len_k[i] - len_q[i])
    else:
        off_q = np.full_like(off_q, 2 ** 30)
    out = (jnp.asarray(seg_q), jnp.asarray(off_q), jnp.asarray(seg_k),
           jnp.asarray(off_k), Tq, Tqp, Tk, Tkp)
    if len(_SEG_CACHE) > 512:
        _SEG_CACHE.clear()
    _SEG_CACHE[key] = out
    return out


def _unpadded_packed(q, k, v, cu_q, cu_k, len_q, len_k, scale, causal):
    """Packed varlen kernel dispatch (no densify): per-row metadata from
    the host cu_seqlens (memoized), pallas kernel on the packed rows."""
    from ...kernels.pallas_flash import flash_attention_varlen_packed
    sq, oq, sk, ok, Tq, Tqp, Tk, Tkp = _seg_off_device(
        cu_q, cu_k, len_q, len_k, causal)

    def run(qa, ka, va):
        def pad_rows(a, Tp):
            T = a.shape[0]
            if Tp == T:
                return a
            return jnp.concatenate(
                [a, jnp.zeros((Tp - T,) + a.shape[1:], a.dtype)], axis=0)
        o = flash_attention_varlen_packed(
            pad_rows(qa, Tqp), pad_rows(ka, Tkp), pad_rows(va, Tkp),
            sq, oq, sk, ok, scale=scale)
        return o[:Tq]

    return apply_op("flash_attn_unpadded_packed", run, (q, k, v), {})


class sdp_kernel:
    """Kernel-selection context (reference sdp_kernel): toggles the
    Pallas flash path — enable_flash=False forces the XLA/math backend
    inside the block (thread-local, like the selection itself). The math
    backend cannot be disabled: it is the guaranteed-shape fallback, so
    enable_math=False raises instead of silently not applying."""

    def __init__(self, enable_math: bool = True, enable_flash: bool = True,
                 enable_mem_efficient: bool = True):
        if not enable_math:
            raise ValueError(
                "sdp_kernel(enable_math=False): the XLA math path is the "
                "guaranteed fallback on TPU and cannot be disabled")
        self.enable_flash = enable_flash
        self._prev = None

    def __enter__(self):
        from ...kernels import attention as _att
        self._prev = _att.flash_enabled()
        _att.set_flash_enabled(bool(self.enable_flash))
        return self

    def __exit__(self, *exc):
        from ...kernels import attention as _att
        _att.set_flash_enabled(self._prev)
        return False



def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q, max_seqlen_k, scale,
                                dropout: float = 0.0, causal: bool = False,
                                return_softmax: bool = False, **kwargs):
    """Varlen packed-QKV variant (flash_attention.py
    flash_attn_varlen_qkvpacked): qkv [total_tokens, 3, h, d]."""
    t = ensure_tensor(qkv)
    q, k, v = t[:, 0], t[:, 1], t[:, 2]
    return flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k,
                               max_seqlen_q, max_seqlen_k, scale,
                               dropout=dropout, causal=causal,
                               return_softmax=return_softmax, **kwargs)


def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout: float = 0.0, causal: bool = False,
                        window_size=None, return_softmax_lse: bool = False,
                        return_seed_offset: bool = False,
                        fixed_seed_offset=None, rng_name: str = "",
                        training: bool = True, name=None):
    """FlashMask attention (reference flash_attention.py:1098): the mask
    is a column-wise sparse description — per KEY position, row ranges
    of the score matrix to mask:

      causal, last dim 1:  mask rows i >= s0[j]            (+ causal)
      causal, last dim 2:  mask s0[j] <= i < s1[j]         (+ causal)
      bidir,  last dim 2:  mask i >= s0[j]  and  i < s1[j]
      bidir,  last dim 4:  mask s0<=i<s1    and  s2<=i<s3

    The reference's CUDA kernel skips masked tiles; here the ranges
    materialize as a boolean mask inside one fused XLA attention — the
    tile-skipping Pallas variant follows the same contract.
    """
    tensors = [ensure_tensor(query), ensure_tensor(key),
               ensure_tensor(value)]
    has_idx = startend_row_indices is not None
    if has_idx:
        tensors.append(ensure_tensor(startend_row_indices))

    def fn(q, k, v, *rest):
        B, Sq, H, D = q.shape
        Sk = k.shape[1]
        scale = 1.0 / np.sqrt(D)
        # [B, H, Sq, Sk]
        scores = jnp.einsum("bqhd,bkhd->bhqk",
                            q.astype(jnp.float32),
                            k.astype(jnp.float32)) * scale
        rows = jnp.arange(Sq)[:, None]             # i
        cols = jnp.arange(Sk)[None, :]             # j
        masked = jnp.zeros((1, 1, Sq, Sk), bool)
        if causal:
            masked = masked | (rows < cols)[None, None]
        if window_size is not None:
            w = ((window_size, window_size)
                 if isinstance(window_size, int) else tuple(window_size))
            masked = masked | (rows - cols > w[0])[None, None]
            if not causal:
                masked = masked | (cols - rows > w[1])[None, None]
        if has_idx:
            idx = rest[0].astype(jnp.int32)        # [B, Hk, Sk, {1,2,4}]
            if idx.shape[1] == 1:
                idx = jnp.broadcast_to(idx, (B, H) + idx.shape[2:])
            n = idx.shape[-1]
            i = rows[None, None]                   # [1, 1, Sq, 1]
            s = jnp.swapaxes(idx, 2, 3)            # [B, H, n, Sk]
            if causal and n == 1:
                band = i >= s[:, :, 0][:, :, None, :]
            elif causal and n == 2:
                band = ((i >= s[:, :, 0][:, :, None, :])
                        & (i < s[:, :, 1][:, :, None, :]))
            elif not causal and n == 2:
                band = ((i >= s[:, :, 0][:, :, None, :])
                        | (i < s[:, :, 1][:, :, None, :]))
            elif not causal and n == 4:
                band = (((i >= s[:, :, 0][:, :, None, :])
                         & (i < s[:, :, 1][:, :, None, :]))
                        | ((i >= s[:, :, 2][:, :, None, :])
                           & (i < s[:, :, 3][:, :, None, :])))
            else:
                raise ValueError(
                    f"startend_row_indices last dim {n} invalid for "
                    f"causal={causal}")
            masked = masked | band
        scores = jnp.where(masked, -jnp.inf, scores)
        lse = jax.scipy.special.logsumexp(scores, axis=-1)
        probs = jnp.exp(scores - lse[..., None])
        # fully-masked rows: zero output, not NaN
        probs = jnp.where(jnp.isfinite(lse)[..., None], probs, 0.0)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs,
                         v.astype(jnp.float32)).astype(q.dtype)
        if return_softmax_lse:
            return out, lse
        return out

    res = apply_op("flashmask_attention", fn, tuple(tensors), {})
    if return_seed_offset:
        extra = Tensor(jnp.zeros((2,), jnp.int32))
        return (res + (extra,)) if isinstance(res, tuple) else (res, extra)
    return res


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Block-sparse attention with a CSR pattern (reference
    sparse_attention op): q/k/v are [B, H, S, D]; per query row r, only
    the keys listed in columns[offset[r]:offset[r+1]] participate in the
    softmax. Dense-equivalent lowering: the CSR pattern scatters into a
    boolean mask consumed by one fused masked softmax."""
    tensors = [ensure_tensor(query), ensure_tensor(key),
               ensure_tensor(value), ensure_tensor(sparse_csr_offset),
               ensure_tensor(sparse_csr_columns)]
    extra = []
    if key_padding_mask is not None:
        extra.append(ensure_tensor(key_padding_mask))
    if attn_mask is not None:
        extra.append(ensure_tensor(attn_mask))
    tensors.extend(extra)
    has_kpm = key_padding_mask is not None
    has_am = attn_mask is not None

    def fn(q, k, v, offset, columns, *rest):
        B, H, S, D = q.shape
        nnz = columns.shape[-1]
        offset = offset.astype(jnp.int32)
        columns = columns.astype(jnp.int32)

        def one(off, cols):
            # nnz element e belongs to row searchsorted(off, e, 'right')-1
            rows = jnp.searchsorted(off, jnp.arange(nnz), side="right") - 1
            rows = jnp.clip(rows, 0, S - 1)
            valid = jnp.arange(nnz) < off[-1]
            m = jnp.zeros((S, S), bool)
            # max-scatter: padded tail elements (valid=False) collide at
            # clipped positions and must not clear real True entries
            return m.at[rows, jnp.clip(cols, 0, S - 1)].max(valid)

        allow = jax.vmap(jax.vmap(one))(offset, columns)   # [B, H, S, S]
        scale = 1.0 / np.sqrt(D)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                            k.astype(jnp.float32)) * scale
        if has_kpm:
            kpm = rest[0]
            allow = allow & (kpm[:, None, None, :] > -1.0)
        if has_am:
            am = rest[-1]
            scores = scores + am.astype(jnp.float32)
        scores = jnp.where(allow, scores, -jnp.inf)
        lse = jax.scipy.special.logsumexp(scores, axis=-1)
        probs = jnp.where(jnp.isfinite(lse)[..., None],
                          jnp.exp(scores - lse[..., None]), 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", probs,
                          v.astype(jnp.float32)).astype(q.dtype)

    return apply_op("sparse_attention", fn, tuple(tensors), {})
