"""Loss functionals (python/paddle/nn/functional/loss.py parity)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.tensor import Tensor
from ...ops.dispatch import apply_op, ensure_tensor

__all__ = ["margin_cross_entropy", "cross_entropy", "softmax_with_cross_entropy", "nll_loss",
           "binary_cross_entropy", "binary_cross_entropy_with_logits",
           "mse_loss", "l1_loss", "smooth_l1_loss", "kl_div", "margin_ranking_loss",
           "hinge_embedding_loss", "cosine_embedding_loss", "ctc_loss",
           "sigmoid_focal_loss", "square_error_cost", "log_loss",
           "triplet_margin_loss", "poisson_nll_loss", "huber_loss"]


def _reduce(out_fn, reduction):
    def wrap(a, *rest):
        out = out_fn(a, *rest)
        if reduction == "mean":
            return jnp.mean(out)
        if reduction == "sum":
            return jnp.sum(out)
        return out
    return wrap


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None) -> Tensor:
    input, label = ensure_tensor(input), ensure_tensor(label)
    tensors = [input, label]
    has_w = weight is not None
    if has_w:
        tensors.append(ensure_tensor(weight))

    def fn(logits, lbl, *w):
        logp = (jax.nn.log_softmax(logits, axis=axis) if use_softmax
                else jnp.log(jnp.clip(logits, 1e-12, None)))
        n_classes = logits.shape[axis]
        if soft_label or (lbl.ndim == logits.ndim
                          and lbl.shape[axis] == n_classes
                          and jnp.issubdtype(lbl.dtype, jnp.inexact)):
            soft = lbl
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(soft * logp, axis=axis)
            if reduction == "mean":
                return jnp.mean(loss)
            if reduction == "sum":
                return jnp.sum(loss)
            return loss
        lbl_i = lbl
        if lbl_i.ndim == logits.ndim:
            lbl_i = jnp.squeeze(lbl_i, axis=axis)
        lbl_i = lbl_i.astype(jnp.int32)
        valid = lbl_i != ignore_index
        safe = jnp.where(valid, lbl_i, 0)
        picked = -jnp.take_along_axis(
            logp, jnp.expand_dims(safe, axis), axis=axis).squeeze(axis)
        if label_smoothing > 0:
            uniform = -jnp.mean(logp, axis=axis)
            picked = (1 - label_smoothing) * picked + label_smoothing * uniform
        if has_w:
            wv = jnp.take(w[0], safe)
            picked = picked * wv
            denom = jnp.sum(jnp.where(valid, wv, 0.0))
        else:
            denom = jnp.sum(valid.astype(picked.dtype))
        picked = jnp.where(valid, picked, 0.0)
        if reduction == "mean":
            return jnp.sum(picked) / jnp.maximum(denom, 1e-12)
        if reduction == "sum":
            return jnp.sum(picked)
        return picked
    return apply_op("cross_entropy", fn, tuple(tensors), {})


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    loss = loss.unsqueeze(axis)
    if return_softmax:
        from .activation import softmax
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None) -> Tensor:
    input, label = ensure_tensor(input), ensure_tensor(label)
    tensors = [input, label]
    has_w = weight is not None
    if has_w:
        tensors.append(ensure_tensor(weight))
    def fn(logp, lbl, *w):
        lbl = lbl.astype(jnp.int32)
        valid = lbl != ignore_index
        safe = jnp.where(valid, lbl, 0)
        picked = -jnp.take_along_axis(logp, safe[:, None], axis=1)[:, 0]
        if has_w:
            wv = jnp.take(w[0], safe)
            picked *= wv
            denom = jnp.sum(jnp.where(valid, wv, 0.0))
        else:
            denom = jnp.sum(valid.astype(picked.dtype))
        picked = jnp.where(valid, picked, 0.0)
        if reduction == "mean":
            return jnp.sum(picked) / jnp.maximum(denom, 1e-12)
        if reduction == "sum":
            return jnp.sum(picked)
        return picked
    return apply_op("nll_loss", fn, tuple(tensors), {})


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None) -> Tensor:
    input, label = ensure_tensor(input), ensure_tensor(label)
    tensors = [input, label]
    has_w = weight is not None
    if has_w:
        tensors.append(ensure_tensor(weight))
    def fn(p, y, *w):
        p = jnp.clip(p, 1e-12, 1 - 1e-12)
        out = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if has_w:
            out = out * w[0]
        if reduction == "mean":
            return jnp.mean(out)
        if reduction == "sum":
            return jnp.sum(out)
        return out
    return apply_op("bce", fn, tuple(tensors), {})


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None) -> Tensor:
    logit, label = ensure_tensor(logit), ensure_tensor(label)
    tensors = [logit, label]
    has_w = weight is not None
    has_pw = pos_weight is not None
    if has_w:
        tensors.append(ensure_tensor(weight))
    if has_pw:
        tensors.append(ensure_tensor(pos_weight))
    def fn(z, y, *rest):
        log_sig = jax.nn.log_sigmoid(z)
        log_sig_neg = jax.nn.log_sigmoid(-z)
        i = 0
        if has_pw:
            pw = rest[-1]
            out = -(pw * y * log_sig + (1 - y) * log_sig_neg)
        else:
            out = -(y * log_sig + (1 - y) * log_sig_neg)
        if has_w:
            out = out * rest[0]
        if reduction == "mean":
            return jnp.mean(out)
        if reduction == "sum":
            return jnp.sum(out)
        return out
    return apply_op("bce_logits", fn, tuple(tensors), {})


def mse_loss(input, label, reduction="mean", name=None) -> Tensor:
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply_op("mse_loss", _reduce(lambda a, b: jnp.square(a - b),
                                        reduction), (input, label), {})


def square_error_cost(input, label) -> Tensor:
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply_op("square_error_cost", lambda a, b: jnp.square(a - b),
                    (input, label), {})


def l1_loss(input, label, reduction="mean", name=None) -> Tensor:
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply_op("l1_loss", _reduce(lambda a, b: jnp.abs(a - b), reduction),
                    (input, label), {})


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None) -> Tensor:
    input, label = ensure_tensor(input), ensure_tensor(label)
    def base(a, b):
        d = a - b
        ad = jnp.abs(d)
        return jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
    return apply_op("smooth_l1", _reduce(base, reduction), (input, label), {})


def huber_loss(input, label, delta=1.0, reduction="mean", name=None) -> Tensor:
    input, label = ensure_tensor(input), ensure_tensor(label)
    def base(a, b):
        d = a - b
        ad = jnp.abs(d)
        return jnp.where(ad <= delta, 0.5 * d * d, delta * (ad - 0.5 * delta))
    return apply_op("huber", _reduce(base, reduction), (input, label), {})


def kl_div(input, label, reduction="mean", log_target=False, name=None) -> Tensor:
    input, label = ensure_tensor(input), ensure_tensor(label)
    def base(logq, p):
        if log_target:
            return jnp.exp(p) * (p - logq)
        return p * (jnp.log(jnp.clip(p, 1e-12, None)) - logq)
    return apply_op("kl_div", _reduce(base, reduction), (input, label), {})


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None) -> Tensor:
    input, other, label = (ensure_tensor(input), ensure_tensor(other),
                           ensure_tensor(label))
    def base(a, b, y):
        return jnp.maximum(0.0, -y * (a - b) + margin)
    return apply_op("margin_ranking", _reduce(base, reduction),
                    (input, other, label), {})


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None) -> Tensor:
    input, label = ensure_tensor(input), ensure_tensor(label)
    def base(a, y):
        return jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
    return apply_op("hinge_embedding", _reduce(base, reduction),
                    (input, label), {})


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None) -> Tensor:
    input1, input2, label = (ensure_tensor(input1), ensure_tensor(input2),
                             ensure_tensor(label))
    def base(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        return jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
    return apply_op("cosine_embedding", _reduce(base, reduction),
                    (input1, input2, label), {})


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None) -> Tensor:
    logit, label = ensure_tensor(logit), ensure_tensor(label)
    tensors = [logit, label]
    has_n = normalizer is not None
    if has_n:
        tensors.append(ensure_tensor(normalizer))
    def fn(z, y, *n):
        p = jax.nn.sigmoid(z)
        ce = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        out = a_t * ((1 - p_t) ** gamma) * ce
        if has_n:
            out = out / n[0]
        if reduction == "mean":
            return jnp.mean(out)
        if reduction == "sum":
            return jnp.sum(out)
        return out
    return apply_op("focal", fn, tuple(tensors), {})


def log_loss(input, label, epsilon=1e-4, name=None) -> Tensor:
    input, label = ensure_tensor(input), ensure_tensor(label)
    return apply_op(
        "log_loss",
        lambda p, y: -(y * jnp.log(p + epsilon)
                       + (1 - y) * jnp.log(1 - p + epsilon)),
        (input, label), {})


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None) -> Tensor:
    input, positive, negative = (ensure_tensor(input), ensure_tensor(positive),
                                 ensure_tensor(negative))
    def base(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v + epsilon) ** p, axis=-1) ** (1.0 / p)
        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        return jnp.maximum(0.0, d_pos - d_neg + margin)
    return apply_op("triplet", _reduce(base, reduction),
                    (input, positive, negative), {})


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None) -> Tensor:
    input, label = ensure_tensor(input), ensure_tensor(label)
    def base(a, y):
        if log_input:
            out = jnp.exp(a) - y * a
        else:
            out = a - y * jnp.log(a + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(
                2 * jnp.pi * (y + epsilon))
            out = out + jnp.where(y > 1, stirling, 0.0)
        return out
    return apply_op("poisson_nll", _reduce(base, reduction), (input, label), {})


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False) -> Tensor:
    """CTC via the standard forward algorithm in log space (lax.scan over
    time) — the warpctc equivalent (third_party/warpctc in the reference)."""
    log_probs = ensure_tensor(log_probs)      # (T, B, C), already log-softmax?
    labels = ensure_tensor(labels)            # (B, S)
    input_lengths = ensure_tensor(input_lengths)
    label_lengths = ensure_tensor(label_lengths)

    def fn(lp, lbl, in_len, lbl_len):
        lp = jax.nn.log_softmax(lp, axis=-1)
        T, B, C = lp.shape
        S = lbl.shape[1]
        ext = jnp.full((B, 2 * S + 1), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lbl.astype(jnp.int32))
        L = 2 * S + 1
        neg_inf = -1e30

        emit = jnp.take_along_axis(
            jnp.moveaxis(lp, 0, 1), ext[:, None, :].repeat(T, 1), axis=2)
        # emit: (B, T, L)

        alpha0 = jnp.full((B, L), neg_inf)
        alpha0 = alpha0.at[:, 0].set(emit[:, 0, 0])
        alpha0 = alpha0.at[:, 1].set(jnp.where(S > 0, emit[:, 0, 1], neg_inf))

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, emit_t):
            a_shift1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            new = jnp.logaddexp(jnp.logaddexp(alpha, a_shift1), a_shift2) + emit_t
            return new, new

        _, alphas = jax.lax.scan(step, alpha0,
                                 jnp.moveaxis(emit[:, 1:, :], 1, 0))
        alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # (T, B, L)

        t_idx = (in_len.astype(jnp.int32) - 1)
        final = alphas[t_idx, jnp.arange(B)]          # (B, L)
        l_end = 2 * lbl_len.astype(jnp.int32)
        p_blank = jnp.take_along_axis(final, l_end[:, None], axis=1)[:, 0]
        p_label = jnp.take_along_axis(
            final, jnp.maximum(l_end - 1, 0)[:, None], axis=1)[:, 0]
        ll = jnp.logaddexp(p_blank, p_label)
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lbl_len.astype(loss.dtype), 1))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return apply_op("ctc_loss", fn,
                    (log_probs, labels, input_lengths, label_lengths), {})



def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """ArcFace-family margin softmax (loss.py margin_cross_entropy):
    cos(m1*theta + m2) - m3 applied to the target logit, then scaled
    cross entropy. Logits are cosine similarities in [-1, 1]."""
    import jax as _jax
    lg, lb = ensure_tensor(logits), ensure_tensor(label)

    def f(x, y):
        yi = y.astype(jnp.int32).reshape(-1)
        tgt = jnp.take_along_axis(x, yi[:, None], 1)[:, 0]
        theta = jnp.arccos(jnp.clip(tgt, -1 + 1e-7, 1 - 1e-7))
        tgt_m = jnp.cos(margin1 * theta + margin2) - margin3
        x_m = x.at[jnp.arange(x.shape[0]), yi].set(tgt_m)
        logp = _jax.nn.log_softmax(x_m * scale, axis=-1)
        loss = -jnp.take_along_axis(logp, yi[:, None], 1)[:, 0]
        if reduction == "mean":
            loss = jnp.mean(loss)
        elif reduction == "sum":
            loss = jnp.sum(loss)
        if return_softmax:
            return loss, jnp.exp(logp)
        return loss
    out = apply_op("margin_cross_entropy", f, (lg, lb), {})
    return out
