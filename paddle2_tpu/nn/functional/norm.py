"""Normalization functionals (python/paddle/nn/functional/norm.py parity).

layer_norm/rms_norm are single fused XLA reductions; batch_norm returns
updated running stats functionally (the Layer wrapper owns the buffers).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.tensor import Tensor
from ...ops.dispatch import apply_op, ensure_tensor

__all__ = ["batch_norm", "layer_norm", "instance_norm", "group_norm",
           "local_response_norm", "rms_norm"]


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW", use_global_stats=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    running_mean = ensure_tensor(running_mean)
    running_var = ensure_tensor(running_var)
    channel_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != channel_axis)
    use_batch_stats = training and not use_global_stats

    tensors = [x, running_mean, running_var]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        tensors.append(ensure_tensor(weight))
    if has_b:
        tensors.append(ensure_tensor(bias))

    def fn(a, rm, rv, *wb):
        shape = [1] * a.ndim
        shape[channel_axis] = a.shape[channel_axis]
        if use_batch_stats:
            mean = jnp.mean(a, axis=reduce_axes)
            var = jnp.var(a, axis=reduce_axes)
        else:
            mean, var = rm, rv
        out = (a - mean.reshape(shape)) * jax.lax.rsqrt(
            var.reshape(shape) + epsilon)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape); i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out.astype(a.dtype)

    out = apply_op("batch_norm", fn, tuple(tensors), {})

    if use_batch_stats:
        # update running stats in place on the buffer tensors (eager semantics;
        # the jit bridge captures these as extra outputs)
        a = x._data
        mean = jnp.mean(a, axis=reduce_axes)
        var = jnp.var(a, axis=reduce_axes)
        running_mean._replace_data(
            momentum * running_mean._data + (1 - momentum) * mean)
        running_var._replace_data(
            momentum * running_var._data + (1 - momentum) * var)
    return out


def _use_pallas_ln(x, n_axes, has_w, has_b) -> bool:
    from ...flags import flag_value
    if not flag_value("pallas_layer_norm") or n_axes != 1 \
            or not (has_w and has_b):
        return False
    try:
        if jax.devices()[0].platform.lower() == "cpu":
            return False
    except Exception:
        return False
    from ...kernels import pallas_ln
    return pallas_ln.supported(tuple(x.shape))


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None) -> Tensor:
    x = ensure_tensor(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(normalized_shape)
    axes = tuple(range(x.ndim - n_axes, x.ndim))

    tensors = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        tensors.append(ensure_tensor(weight))
    if has_b:
        tensors.append(ensure_tensor(bias))

    if _use_pallas_ln(x, n_axes, has_w, has_b):
        # fused one-pass Pallas kernel (kernels/pallas_ln.py); routed
        # through a cached jit wrapper — an eager pallas closure would
        # re-run the Mosaic compiler on every call
        from ...kernels import pallas_ln
        from ...kernels.pallas_flash import _cached_jit
        key = ("pallas_ln", tuple(x.shape), str(x._data.dtype),
               float(epsilon))
        fn = _cached_jit(key, lambda: _pallas_ln_fn(epsilon))
        return apply_op("layer_norm", fn, tuple(tensors), {})

    def fn(a, *wb):
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + epsilon)
        i = 0
        if has_w:
            out = out * wb[i]; i += 1
        if has_b:
            out = out + wb[i]
        return out.astype(a.dtype)
    return apply_op("layer_norm", fn, tuple(tensors), {})


def _pallas_ln_fn(epsilon):
    from ...kernels.pallas_ln import fused_layer_norm

    def run(a, w, b):
        return fused_layer_norm(a, w, b, float(epsilon))
    return run


def rms_norm(x, weight=None, epsilon=1e-6, name=None) -> Tensor:
    x = ensure_tensor(x)
    tensors = [x] if weight is None else [x, ensure_tensor(weight)]
    def fn(a, *w):
        # rms in f32 for bf16 stability, like fused_rms_norm kernels
        h = a.astype(jnp.float32)
        rms = jax.lax.rsqrt(jnp.mean(h * h, axis=-1, keepdims=True) + epsilon)
        out = h * rms
        if w:
            out = out * w[0].astype(jnp.float32)
        return out.astype(a.dtype)
    return apply_op("rms_norm", fn, tuple(tensors), {})


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None) -> Tensor:
    x = ensure_tensor(x)
    channel_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    reduce_axes = tuple(i for i in range(2, x.ndim)) if channel_axis == 1 else \
        tuple(i for i in range(1, x.ndim - 1))

    tensors = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        tensors.append(ensure_tensor(weight))
    if has_b:
        tensors.append(ensure_tensor(bias))

    def fn(a, *wb):
        mean = jnp.mean(a, axis=reduce_axes, keepdims=True)
        var = jnp.var(a, axis=reduce_axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + eps)
        shape = [1] * a.ndim
        shape[channel_axis] = a.shape[channel_axis]
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape); i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out.astype(a.dtype)
    return apply_op("instance_norm", fn, tuple(tensors), {})


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None) -> Tensor:
    x = ensure_tensor(x)
    channel_last = not data_format.startswith("NC")

    tensors = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        tensors.append(ensure_tensor(weight))
    if has_b:
        tensors.append(ensure_tensor(bias))

    def fn(a, *wb):
        if channel_last:
            a_t = jnp.moveaxis(a, -1, 1)
        else:
            a_t = a
        n, c = a_t.shape[0], a_t.shape[1]
        rest = a_t.shape[2:]
        g = a_t.reshape(n, num_groups, c // num_groups, *rest)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a_t.shape)
        shape = [1] * out.ndim
        shape[1] = c
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape); i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out.astype(a.dtype)
    return apply_op("group_norm", fn, tuple(tensors), {})


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None) -> Tensor:
    x = ensure_tensor(x)
    channel_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    def fn(a):
        sq = jnp.square(a)
        half = size // 2
        pad_cfg = [(0, 0)] * a.ndim
        pad_cfg[channel_axis] = (half, size - half - 1)
        padded = jnp.pad(sq, pad_cfg)
        window = [1] * a.ndim
        window[channel_axis] = size
        s = jax.lax.reduce_window(padded, 0.0, jax.lax.add, tuple(window),
                                  (1,) * a.ndim, "VALID")
        return a / jnp.power(k + alpha * s / size, beta)
    return apply_op("local_response_norm", fn, (x,), {})
