"""Pooling functionals (python/paddle/nn/functional/pooling.py parity) —
reduce_window lowerings."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.tensor import Tensor
from ...ops.dispatch import apply_op, ensure_tensor

__all__ = ["avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d",
           "max_pool2d", "max_pool3d", "adaptive_avg_pool1d",
           "adaptive_avg_pool2d", "adaptive_avg_pool3d", "adaptive_max_pool1d",
           "adaptive_max_pool2d", "adaptive_max_pool3d", "lp_pool2d"]


def _tuplize(v, n):
    return (v,) * n if isinstance(v, int) else tuple(int(x) for x in v)


def _pad_cfg(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]


def _pool(x, kernel, stride, padding, n, reducer, init, ceil_mode,
          channel_last, count_include_pad=True, name="pool"):
    x = ensure_tensor(x)
    kernel = _tuplize(kernel, n)
    stride = _tuplize(stride if stride is not None else kernel, n)
    pad = _pad_cfg(padding, n)
    nd = x.ndim
    if ceil_mode and not isinstance(pad, str):
        # extend hi padding so partial trailing windows are kept
        spatial = ([x.shape[a] for a in range(1, 1 + n)] if channel_last
                   else [x.shape[a] for a in range(nd - n, nd)])
        pad = list(pad)
        for i in range(n):
            eff = spatial[i] + pad[i][0] + pad[i][1]
            rem = (eff - kernel[i]) % stride[i]
            if rem:
                pad[i] = (pad[i][0], pad[i][1] + (stride[i] - rem))
    if channel_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        full_pad = ([(0, 0)] + pad + [(0, 0)]) if not isinstance(pad, str) else pad
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        full_pad = ([(0, 0), (0, 0)] + pad) if not isinstance(pad, str) else pad

    def fn(a):
        if reducer == "max":
            out = jax.lax.reduce_window(a, -jnp.inf, jax.lax.max, window,
                                        strides, full_pad)
            return out.astype(a.dtype)
        s = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, full_pad)
        if count_include_pad or isinstance(full_pad, str):
            denom = float(np.prod(kernel))
            return (s / denom).astype(a.dtype)
        ones = jnp.ones_like(a)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides,
                                       full_pad)
        return (s / counts).astype(a.dtype)
    return apply_op(name, fn, (x,), {})


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    out = _pool(x, kernel_size, stride, padding, 1, "max", None, ceil_mode,
                not data_format.startswith("NC"), name="max_pool1d")
    return (out, None) if return_mask else out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 2, "max", None, ceil_mode,
                not data_format.startswith("NC"), name="max_pool2d")
    return (out, None) if return_mask else out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 3, "max", None, ceil_mode,
                not data_format.startswith("NC"), name="max_pool3d")
    return (out, None) if return_mask else out


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, "avg", None, ceil_mode,
                 not data_format.startswith("NC"),
                 count_include_pad=not exclusive, name="avg_pool1d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, "avg", None, ceil_mode,
                 not data_format.startswith("NC"),
                 count_include_pad=not exclusive, name="avg_pool2d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, "avg", None, ceil_mode,
                 not data_format.startswith("NC"),
                 count_include_pad=not exclusive, name="avg_pool3d")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    p = float(norm_type)
    powed = apply_op("lp_pow", lambda a: jnp.abs(a) ** p, (x,), {})
    pooled = _pool(powed, kernel_size, stride, padding, 2, "avg", None,
                   ceil_mode, not data_format.startswith("NC"),
                   name="lp_pool2d")
    kernel = _tuplize(kernel_size, 2)
    scale = float(np.prod(kernel))
    return apply_op("lp_root", lambda a: (a * scale) ** (1.0 / p), (pooled,), {})


def _adaptive(x, output_size, n, reducer, channel_last, name):
    x = ensure_tensor(x)
    out_sizes = _tuplize(output_size, n)
    nd = x.ndim
    spatial_axes = (list(range(1, 1 + n)) if channel_last
                    else list(range(nd - n, nd)))

    def fn(a):
        out = a
        for i, ax in enumerate(spatial_axes):
            osz = out_sizes[i]
            if osz is None:
                continue
            isz = out.shape[ax]
            # split positions follow paddle: start = floor(i*I/O), end = ceil((i+1)*I/O)
            starts = [int(np.floor(j * isz / osz)) for j in range(osz)]
            ends = [int(np.ceil((j + 1) * isz / osz)) for j in range(osz)]
            pieces = []
            for s, e in zip(starts, ends):
                seg = jax.lax.slice_in_dim(out, s, e, axis=ax)
                if reducer == "max":
                    pieces.append(jnp.max(seg, axis=ax, keepdims=True))
                else:
                    pieces.append(jnp.mean(seg, axis=ax, keepdims=True))
            out = jnp.concatenate(pieces, axis=ax)
        return out
    return apply_op(name, fn, (x,), {})


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg", False, "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg",
                     not data_format.startswith("NC"), "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg",
                     not data_format.startswith("NC"), "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 1, "max", False, "adaptive_max_pool1d")
    return (out, None) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 2, "max", False, "adaptive_max_pool2d")
    return (out, None) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 3, "max", False, "adaptive_max_pool3d")
    return (out, None) if return_mask else out
