"""Pooling functionals (python/paddle/nn/functional/pooling.py parity) —
reduce_window lowerings."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.tensor import Tensor
from ...ops.dispatch import apply_op, ensure_tensor

__all__ = ["avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d",
           "max_pool2d", "max_pool3d", "adaptive_avg_pool1d",
           "adaptive_avg_pool2d", "adaptive_avg_pool3d", "adaptive_max_pool1d",
           "adaptive_max_pool2d", "adaptive_max_pool3d", "lp_pool1d",
           "lp_pool2d", "max_unpool1d", "max_unpool2d", "max_unpool3d",
           "fractional_max_pool2d", "fractional_max_pool3d"]


def _tuplize(v, n):
    return (v,) * n if isinstance(v, int) else tuple(int(x) for x in v)


def _pad_cfg(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]


def _pool(x, kernel, stride, padding, n, reducer, init, ceil_mode,
          channel_last, count_include_pad=True, name="pool"):
    x = ensure_tensor(x)
    kernel = _tuplize(kernel, n)
    stride = _tuplize(stride if stride is not None else kernel, n)
    pad = _pad_cfg(padding, n)
    nd = x.ndim
    if ceil_mode and not isinstance(pad, str):
        # extend hi padding so partial trailing windows are kept
        spatial = ([x.shape[a] for a in range(1, 1 + n)] if channel_last
                   else [x.shape[a] for a in range(nd - n, nd)])
        pad = list(pad)
        for i in range(n):
            eff = spatial[i] + pad[i][0] + pad[i][1]
            rem = (eff - kernel[i]) % stride[i]
            if rem:
                pad[i] = (pad[i][0], pad[i][1] + (stride[i] - rem))
    if channel_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        full_pad = ([(0, 0)] + pad + [(0, 0)]) if not isinstance(pad, str) else pad
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        full_pad = ([(0, 0), (0, 0)] + pad) if not isinstance(pad, str) else pad

    def fn(a):
        if reducer == "max":
            out = jax.lax.reduce_window(a, -jnp.inf, jax.lax.max, window,
                                        strides, full_pad)
            return out.astype(a.dtype)
        s = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, full_pad)
        if count_include_pad or isinstance(full_pad, str):
            denom = float(np.prod(kernel))
            return (s / denom).astype(a.dtype)
        ones = jnp.ones_like(a)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides,
                                       full_pad)
        return (s / counts).astype(a.dtype)
    return apply_op(name, fn, (x,), {})


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 1,
                                   ceil_mode,
                                   not data_format.startswith("NC"),
                                   "max_pool1d")
    return _pool(x, kernel_size, stride, padding, 1, "max", None, ceil_mode,
                 not data_format.startswith("NC"), name="max_pool1d")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 2,
                                   ceil_mode,
                                   not data_format.startswith("NC"),
                                   "max_pool2d")
    return _pool(x, kernel_size, stride, padding, 2, "max", None, ceil_mode,
                 not data_format.startswith("NC"), name="max_pool2d")


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 3,
                                   ceil_mode,
                                   not data_format.startswith("NC"),
                                   "max_pool3d")
    return _pool(x, kernel_size, stride, padding, 3, "max", None, ceil_mode,
                 not data_format.startswith("NC"), name="max_pool3d")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, "avg", None, ceil_mode,
                 not data_format.startswith("NC"),
                 count_include_pad=not exclusive, name="avg_pool1d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, "avg", None, ceil_mode,
                 not data_format.startswith("NC"),
                 count_include_pad=not exclusive, name="avg_pool2d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, "avg", None, ceil_mode,
                 not data_format.startswith("NC"),
                 count_include_pad=not exclusive, name="avg_pool3d")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    p = float(norm_type)
    powed = apply_op("lp_pow", lambda a: jnp.abs(a) ** p, (x,), {})
    pooled = _pool(powed, kernel_size, stride, padding, 2, "avg", None,
                   ceil_mode, not data_format.startswith("NC"),
                   name="lp_pool2d")
    kernel = _tuplize(kernel_size, 2)
    scale = float(np.prod(kernel))
    return apply_op("lp_root", lambda a: (a * scale) ** (1.0 / p), (pooled,), {})


def _adaptive(x, output_size, n, reducer, channel_last, name):
    x = ensure_tensor(x)
    out_sizes = _tuplize(output_size, n)
    nd = x.ndim
    spatial_axes = (list(range(1, 1 + n)) if channel_last
                    else list(range(nd - n, nd)))

    def fn(a):
        out = a
        for i, ax in enumerate(spatial_axes):
            osz = out_sizes[i]
            if osz is None:
                continue
            isz = out.shape[ax]
            # split positions follow paddle: start = floor(i*I/O), end = ceil((i+1)*I/O)
            starts = [int(np.floor(j * isz / osz)) for j in range(osz)]
            ends = [int(np.ceil((j + 1) * isz / osz)) for j in range(osz)]
            pieces = []
            for s, e in zip(starts, ends):
                seg = jax.lax.slice_in_dim(out, s, e, axis=ax)
                if reducer == "max":
                    pieces.append(jnp.max(seg, axis=ax, keepdims=True))
                else:
                    pieces.append(jnp.mean(seg, axis=ax, keepdims=True))
            out = jnp.concatenate(pieces, axis=ax)
        return out
    return apply_op(name, fn, (x,), {})


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg", False, "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg",
                     not data_format.startswith("NC"), "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg",
                     not data_format.startswith("NC"), "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 1, "max", False, "adaptive_max_pool1d")
    return (out, None) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 2, "max", False, "adaptive_max_pool2d")
    return (out, None) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 3, "max", False, "adaptive_max_pool3d")
    return (out, None) if return_mask else out


def _max_pool_with_mask(x, kernel, stride, padding, n, ceil_mode,
                        channel_last, name):
    """Max pool that also returns the reference's mask: per output
    element, the FLAT index into the input's spatial plane of the max
    (max_pool_with_index kernels). Patch-extraction route: taps
    materialize as a K axis, argmax picks the tap, tap -> input index."""
    x = ensure_tensor(x)
    kernel = _tuplize(kernel, n)
    stride = _tuplize(stride if stride is not None else kernel, n)
    pad = _pad_cfg(padding, n)
    if isinstance(pad, str):
        raise ValueError("mask mode needs explicit padding")
    if channel_last:
        raise NotImplementedError("return_mask expects NC-first layouts")

    def fn(a):
        nd = a.ndim
        spatial = a.shape[nd - n:]
        if ceil_mode:
            padc = list(pad)
            for i in range(n):
                eff = spatial[i] + padc[i][0] + padc[i][1]
                rem = (eff - kernel[i]) % stride[i]
                if rem:
                    padc[i] = (padc[i][0], padc[i][1] + (stride[i] - rem))
        else:
            padc = pad
        neg = jnp.finfo(jnp.float32).min
        ap = jnp.pad(a.astype(jnp.float32),
                     [(0, 0), (0, 0)] + list(padc), constant_values=neg)
        outs = [(ap.shape[2 + i] - kernel[i]) // stride[i] + 1
                for i in range(n)]
        K = int(np.prod(kernel))
        # window gather: for each tap, a strided slice; K is tiny/static
        taps = []
        tap_coord = []
        for t in range(K):
            idx = []
            rem = t
            for i in reversed(range(n)):
                idx.append(rem % kernel[i])
                rem //= kernel[i]
            idx = idx[::-1]
            tap_coord.append(idx)
            sl = [slice(None), slice(None)]
            for i in range(n):
                sl.append(slice(idx[i], idx[i] + (outs[i] - 1) * stride[i]
                                + 1, stride[i]))
            taps.append(ap[tuple(sl)])
        stack = jnp.stack(taps, axis=2)       # [N, C, K, *outs]
        out = jnp.max(stack, axis=2).astype(a.dtype)
        arg = jnp.argmax(stack, axis=2)       # tap index
        # tap -> input plane flat index (unpadded coordinates)
        coords = jnp.asarray(tap_coord, jnp.int32)   # [K, n]
        grids = jnp.meshgrid(*[jnp.arange(o) for o in outs],
                             indexing="ij")
        flat = jnp.zeros(arg.shape, jnp.int32)
        for i in range(n):
            pos = (grids[i][None, None] * stride[i]
                   + jnp.take(coords[:, i], arg) - padc[i][0])
            flat = flat * spatial[i] + pos
        return out, flat

    out, mask = apply_op(name, fn, (x,), {})
    return out, mask


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    x = ensure_tensor(x)
    p = float(norm_type)
    powed = apply_op("lp_pow", lambda a: jnp.abs(a) ** p, (x,), {})
    pooled = _pool(powed, kernel_size, stride, padding, 1, "avg", None,
                   ceil_mode, not data_format.startswith("NC"),
                   name="lp_pool1d")
    kernel = _tuplize(kernel_size, 1)
    scale = float(np.prod(kernel))
    return apply_op("lp_root", lambda a: (a * scale) ** (1.0 / p),
                    (pooled,), {})


def _max_unpool(x, indices, n, kernel_size, stride, padding, output_size,
                data_format, name):
    """Scatter pooled values back to their argmax positions
    (unpool kernels); non-max positions are zero."""
    x = ensure_tensor(x)
    idx = ensure_tensor(indices)
    kernel = _tuplize(kernel_size, n)
    stride = _tuplize(stride if stride is not None else kernel_size, n)
    pads = _pad_cfg(padding, n)
    if output_size is None:
        out_sp = tuple(
            (x.shape[2 + i] - 1) * stride[i] - 2 * pads[i][0] + kernel[i]
            for i in range(n))
    else:
        out_sp = tuple(output_size[-n:])

    def fn(a, ind):
        N, C = a.shape[:2]
        P = int(np.prod(a.shape[2:]))
        plane = int(np.prod(out_sp))
        flat = jnp.zeros((N, C, plane), a.dtype)
        ii = ind.reshape(N, C, P)
        vals = a.reshape(N, C, P)
        flat = flat.at[
            jnp.arange(N)[:, None, None],
            jnp.arange(C)[None, :, None], ii].set(vals)
        return flat.reshape((N, C) + out_sp)

    return apply_op(name, fn, (x, idx), {})


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool(x, indices, 1, kernel_size, stride, padding,
                       output_size, data_format, "max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, 2, kernel_size, stride, padding,
                       output_size, data_format, "max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, 3, kernel_size, stride, padding,
                       output_size, data_format, "max_unpool3d")


def _fractional_bounds(in_size, out_size, u):
    """Graham fractional pooling boundaries (pooling.py:2105):
    start_i = ceil(alpha*(i+u) - 1), end_i = ceil(alpha*(i+1+u) - 1),
    first window clamped to 0, last to in_size."""
    alpha = in_size / out_size
    starts = [max(0, int(np.ceil(alpha * (i + u) - 1)))
              for i in range(out_size)]
    ends = [min(in_size, int(np.ceil(alpha * (i + 1 + u) - 1)))
            for i in range(out_size)]
    ends[-1] = in_size
    starts[0] = 0
    return starts, ends


def _fractional_max_pool(x, output_size, n, kernel_size, random_u,
                         return_mask, name):
    x = ensure_tensor(x)
    if random_u is None:
        from ...framework import random as fr
        random_u = float(jax.random.uniform(fr.next_key(), ()))
    u = float(random_u)
    if not 0.0 < u < 1.0:
        raise ValueError(f"random_u must be in (0, 1), got {u}")
    nd = x.ndim
    spatial = [x.shape[nd - n + i] for i in range(n)]
    out_sizes = _tuplize(output_size, n)
    out_sizes = tuple(out_sizes[i] if out_sizes[i] is not None
                      else spatial[i] for i in range(n))
    kern = _tuplize(kernel_size, n) if kernel_size is not None else None
    bounds = []
    for i in range(n):
        s, e = _fractional_bounds(spatial[i], out_sizes[i], u)
        if kern is not None:
            # overlapping mode: fixed kernel extent from each start
            e = [min(spatial[i], st + kern[i]) for st in s]
        bounds.append((s, e))

    def fn(a):
        out = a
        # reduce one spatial axis at a time (out sizes are static)
        for i in range(n):
            ax = a.ndim - n + i
            s_list, e_list = bounds[i]
            pieces = []
            for s, e in zip(s_list, e_list):
                seg = jax.lax.slice_in_dim(out, s, e, axis=ax)
                pieces.append(jnp.max(seg, axis=ax, keepdims=True))
            out = jnp.concatenate(pieces, axis=ax)
        return out

    res = apply_op(name, fn, (x,), {})
    if not return_mask:
        return res
    # mask: recompute flat argmax per output cell (host-static bounds)
    def mask_fn(a):
        N, C = a.shape[:2]
        idx_grids = []
        cells = [list(zip(*bounds[i])) for i in range(n)]
        plane_mul = [int(np.prod(spatial[i + 1:])) for i in range(n)]
        out = np.zeros((N, C) + tuple(out_sizes), np.int32)
        an = np.asarray(a)
        for pos in np.ndindex(*out_sizes):
            sl = tuple(slice(cells[i][pos[i]][0], cells[i][pos[i]][1])
                       for i in range(n))
            seg = an[(slice(None), slice(None)) + sl]
            seg2 = seg.reshape(N, C, -1)
            arg = seg2.argmax(-1)
            # unravel within the window, offset by window start
            sizes = [cells[i][pos[i]][1] - cells[i][pos[i]][0]
                     for i in range(n)]
            flat = np.zeros((N, C), np.int64)
            rem = arg
            local = []
            for i in reversed(range(n)):
                local.append(rem % sizes[i])
                rem = rem // sizes[i]
            local = local[::-1]
            for i in range(n):
                flat = flat * spatial[i] + (local[i]
                                            + cells[i][pos[i]][0])
            out[(slice(None), slice(None)) + pos] = flat
        return jnp.asarray(out)

    return res, Tensor(mask_fn(x._data))


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    return _fractional_max_pool(x, output_size, 2, kernel_size, random_u,
                                return_mask, "fractional_max_pool2d")


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    return _fractional_max_pool(x, output_size, 3, kernel_size, random_u,
                                return_mask, "fractional_max_pool3d")
