"""affine_grid + grid_sample (reference nn/functional/vision.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops.dispatch import apply_op, ensure_tensor

__all__ = ["affine_grid", "grid_sample"]


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """vision.py affine_grid: theta [N, 2, 3] -> grid [N, H, W, 2]."""
    if hasattr(out_shape, "numpy"):
        out_shape = [int(v) for v in out_shape.numpy()]
    n, c, h, w = [int(v) for v in out_shape]

    def f(th):
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1
            xs = (jnp.arange(w) * 2 + 1) / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)      # [H, W, 3]
        return jnp.einsum("hwk,nik->nhwi", base, th)   # [N, H, W, 2]
    return apply_op("affine_grid", f, (ensure_tensor(theta),), {})


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """vision.py grid_sample: x [N,C,H,W], grid [N,Ho,Wo,2] in [-1,1]."""
    def f(a, g):
        n, c, h, w = a.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        def pix(yy, xx):
            """[N,Ho,Wo] int coords -> [N,C,Ho,Wo] values with zero pad."""
            inside = ((yy >= 0) & (yy < h) & (xx >= 0) & (xx < w))
            yc = jnp.clip(yy, 0, h - 1)
            xc = jnp.clip(xx, 0, w - 1)
            batch = jnp.arange(n)[:, None, None]
            vals = a[batch, :, yc, xc]                 # [N,Ho,Wo,C]
            vals = jnp.moveaxis(vals, -1, 1)           # [N,C,Ho,Wo]
            if padding_mode == "zeros":
                vals = vals * inside[:, None].astype(vals.dtype)
            return vals

        if mode == "nearest":
            return pix(jnp.round(fy).astype(jnp.int32),
                       jnp.round(fx).astype(jnp.int32))
        x0 = jnp.floor(fx).astype(jnp.int32)
        y0 = jnp.floor(fy).astype(jnp.int32)
        wx = (fx - x0)[:, None]
        wy = (fy - y0)[:, None]
        return (pix(y0, x0) * (1 - wy) * (1 - wx)
                + pix(y0, x0 + 1) * (1 - wy) * wx
                + pix(y0 + 1, x0) * wy * (1 - wx)
                + pix(y0 + 1, x0 + 1) * wy * wx)
    return apply_op("grid_sample", f,
                    (ensure_tensor(x), ensure_tensor(grid)), {})
