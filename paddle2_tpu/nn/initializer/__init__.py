"""Weight initializers (python/paddle/nn/initializer/ parity).

Initializers are callables applied to a shape/dtype at parameter creation,
drawing from the framework PRNG (framework/random.py).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import core
from ...framework import random as fr

__all__ = ["Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
           "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
           "Assign", "Orthogonal", "Dirac", "calculate_gain",
           "set_global_initializer"]


def _fan_in_out(shape: Sequence[int]):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle Linear weights are (in_features, out_features)
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    # conv weight (out_channels, in_channels/groups, *kernel)
    return shape[1] * receptive, shape[0] * receptive


def calculate_gain(nonlinearity: str, param=None) -> float:
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    if nonlinearity not in gains:
        raise ValueError(f"unsupported nonlinearity {nonlinearity!r}")
    return gains[nonlinearity]


class Initializer:
    def __call__(self, shape, dtype=None):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, shape, dtype=None):
        dtype = core.convert_dtype(dtype) or core.get_default_dtype()
        return jnp.full(tuple(shape), self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        dtype = core.convert_dtype(dtype) or core.get_default_dtype()
        return (self.mean
                + self.std * jax.random.normal(fr.next_key(), tuple(shape), dtype))


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0, a: float = -2.0,
                 b: float = 2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype=None):
        dtype = core.convert_dtype(dtype) or core.get_default_dtype()
        z = jax.random.truncated_normal(fr.next_key(), self.a, self.b,
                                        tuple(shape), dtype)
        return self.mean + self.std * z


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=None):
        dtype = core.convert_dtype(dtype) or core.get_default_dtype()
        return jax.random.uniform(fr.next_key(), tuple(shape), dtype,
                                  minval=self.low, maxval=self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        dtype = core.convert_dtype(dtype) or core.get_default_dtype()
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(fr.next_key(), tuple(shape), dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None):
        dtype = core.convert_dtype(dtype) or core.get_default_dtype()
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(fr.next_key(), tuple(shape), dtype,
                                  minval=-limit, maxval=limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0,
                 nonlinearity: str = "relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=None):
        dtype = core.convert_dtype(dtype) or core.get_default_dtype()
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(fr.next_key(), tuple(shape), dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0,
                 nonlinearity: str = "relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=None):
        dtype = core.convert_dtype(dtype) or core.get_default_dtype()
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(fr.next_key(), tuple(shape), dtype,
                                  minval=-limit, maxval=limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=None):
        dtype = core.convert_dtype(dtype) or core.get_default_dtype()
        arr = np.asarray(self.value if not hasattr(self.value, "numpy")
                         else self.value.numpy())
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(tuple(shape))
        return jnp.asarray(arr, dtype)


class Orthogonal(Initializer):
    def __init__(self, gain: float = 1.0):
        self.gain = gain

    def __call__(self, shape, dtype=None):
        dtype = core.convert_dtype(dtype) or core.get_default_dtype()
        return self.gain * jax.nn.initializers.orthogonal()(
            fr.next_key(), tuple(shape), dtype)


class Dirac(Initializer):
    """Identity-preserving conv init (paddle.nn.initializer.Dirac)."""

    def __init__(self, groups: int = 1):
        self.groups = groups

    def __call__(self, shape, dtype=None):
        dtype = core.convert_dtype(dtype) or core.get_default_dtype()
        shape = tuple(shape)
        out = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        mins = min(oc // self.groups, ic)
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(mins):
                idx = (g * (oc // self.groups) + i, i) + tuple(centers)
                out[idx] = 1.0
        return jnp.asarray(out, dtype)


_global_weight_init: Optional[Initializer] = None
_global_bias_init: Optional[Initializer] = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def get_global_initializer():
    return _global_weight_init, _global_bias_init


class Bilinear(Initializer):
    """initializer/Bilinear: transposed-conv upsampling kernels
    (each [kh, kw] slice is the bilinear interpolation stencil)."""

    def __call__(self, param, block=None):
        import numpy as np
        import jax.numpy as jnp
        shape = tuple(param.shape)
        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects a 4-D weight")
        kh, kw = shape[2], shape[3]
        f_h = (kh + 1) // 2
        f_w = (kw + 1) // 2
        og = np.ogrid[:kh, :kw]
        center_h = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        center_w = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        filt = ((1 - np.abs(og[0] / f_h - center_h))
                * (1 - np.abs(og[1] / f_w - center_w)))
        w = np.zeros(shape, np.float32)
        for i in range(shape[0]):
            for j in range(shape[1]):
                w[i, j] = filt
        param._replace_data(jnp.asarray(w))
        return param


__all__.append("Bilinear")
