"""Activation layers (python/paddle/nn/layer/activation.py parity)."""

from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["ReLU", "ReLU6", "ELU", "SELU", "CELU", "GELU", "Sigmoid", "Silu",
           "Swish", "Mish", "Softplus", "Softshrink", "Hardshrink",
           "Tanhshrink", "Hardtanh", "Hardsigmoid", "Hardswish", "LeakyReLU",
           "LogSigmoid", "LogSoftmax", "Softmax", "Softsign", "Tanh", "Maxout",
           "PReLU", "RReLU", "GLU", "ThresholdedReLU"]


def _simple(name, fn, *defaults):
    class _Act(Layer):
        def __init__(self, *args, name=None, **kwargs):
            super().__init__()
            self.args = args if args else defaults
            self.kwargs = kwargs

        def forward(self, x):
            return fn(x, *self.args, **self.kwargs)
    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _simple("ReLU", F.relu)
ReLU6 = _simple("ReLU6", F.relu6)
ELU = _simple("ELU", F.elu)
SELU = _simple("SELU", F.selu)
CELU = _simple("CELU", F.celu)
GELU = _simple("GELU", F.gelu)
Sigmoid = _simple("Sigmoid", F.sigmoid)
Silu = _simple("Silu", F.silu)
Swish = _simple("Swish", F.swish)
Mish = _simple("Mish", F.mish)
Softplus = _simple("Softplus", F.softplus)
Softshrink = _simple("Softshrink", F.softshrink)
Hardshrink = _simple("Hardshrink", F.hardshrink)
Tanhshrink = _simple("Tanhshrink", F.tanhshrink)
Hardtanh = _simple("Hardtanh", F.hardtanh)
Hardsigmoid = _simple("Hardsigmoid", F.hardsigmoid)
Hardswish = _simple("Hardswish", F.hardswish)
LeakyReLU = _simple("LeakyReLU", F.leaky_relu)
LogSigmoid = _simple("LogSigmoid", F.log_sigmoid)
Softsign = _simple("Softsign", F.softsign)
Tanh = _simple("Tanh", F.tanh)
GLU = _simple("GLU", F.glu)
ThresholdedReLU = _simple("ThresholdedReLU", F.thresholded_relu)
RReLU = _simple("RReLU", F.rrelu)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self.axis)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)
