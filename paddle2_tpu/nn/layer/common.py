"""Common layers: Linear, Embedding, Dropout, Pad, Upsample, Flatten, etc.
(python/paddle/nn/layer/common.py parity)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ...framework import core
from ...framework.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer, ParamAttr

__all__ = ["FeatureAlphaDropout", "Softmax2D", "Unflatten", "ZeroPad1D", "ZeroPad3D",
           "PairwiseDistance", "Linear", "Embedding", "Dropout", "Dropout2D", "Dropout3D",
           "AlphaDropout", "Flatten", "Pad1D", "Pad2D", "Pad3D", "ZeroPad2D",
           "Upsample", "UpsamplingBilinear2D", "UpsamplingNearest2D",
           "PixelShuffle", "PixelUnshuffle", "ChannelShuffle",
           "CosineSimilarity", "Bilinear", "Identity", "Unfold", "Fold",
           "LinearLowPrecision"]


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, input):
        return input


class Linear(Layer):
    """y = xW + b with W:(in_features, out_features)
    (python/paddle/nn/layer/common.py Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0))
        if self.bias is not None:
            self.add_parameter("bias", self.bias)

    def forward(self, input):
        return F.linear(input, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


LinearLowPrecision = Linear  # alias; precision comes from amp/bf16 params


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = (padding_idx if padding_idx is None or padding_idx >= 0
                             else num_embeddings + padding_idx)
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        if self._padding_idx is not None:
            data = self.weight._data.at[self._padding_idx].set(0.0)
            self.weight._replace_data(data)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.axis, self.mode = p, axis, mode

    def forward(self, input):
        return F.dropout(input, p=self.p, axis=self.axis,
                         training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, input):
        return F.dropout2d(input, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, input):
        return F.dropout3d(input, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, input):
        return F.alpha_dropout(input, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, input):
        from ...ops.manipulation import flatten
        return flatten(input, self.start_axis, self.stop_axis)


class _PadNd(Layer):
    n = 2

    def __init__(self, padding, mode="constant", value=0.0, data_format=None,
                 name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format or {1: "NCL", 2: "NCHW", 3: "NCDHW"}[self.n]

    def forward(self, input):
        df = {"NCL": "NCW", "NLC": "NWC"}.get(self.data_format, self.data_format)
        return F.pad(input, self.padding, mode=self.mode, value=self.value,
                     data_format=df)


class Pad1D(_PadNd):
    n = 1


class Pad2D(_PadNd):
    n = 2


class Pad3D(_PadNd):
    n = 3


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, mode="constant", value=0.0,
                         data_format=data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.align_mode, self.data_format = align_mode, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor, self.data_format = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor, self.data_format = downscale_factor, data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups, self.data_format = groups, data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, self.output_sizes, *self.args)



class Softmax2D(Layer):
    """nn.Softmax2D: softmax over the channel dim of NCHW (layer/
    activation.py Softmax2D parity)."""

    def forward(self, x):
        from .. import functional as F
        return F.softmax(x, axis=-3)


class Unflatten(Layer):
    """nn.Unflatten (common.py Unflatten parity)."""

    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = shape

    def forward(self, x):
        from ...ops.extra import unflatten
        return unflatten(x, self.axis, self.shape)


class _ZeroPadNd(Layer):
    n_spatial = 1

    def __init__(self, padding, data_format=None, name=None):
        super().__init__()
        if isinstance(padding, int):
            padding = [padding, padding] * self.n_spatial
        self.padding = list(padding)

    def forward(self, x):
        from .. import functional as F
        return F.pad(x, self.padding, mode="constant", value=0.0)


class ZeroPad1D(_ZeroPadNd):
    n_spatial = 1


class ZeroPad3D(_ZeroPadNd):
    n_spatial = 3


class PairwiseDistance(Layer):
    """nn.PairwiseDistance (distance.py parity)."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        from .. import functional as F
        return F.pairwise_distance(x, y, self.p, self.epsilon,
                                   self.keepdim)



class FeatureAlphaDropout(Layer):
    """nn.FeatureAlphaDropout: alpha dropout over whole channels."""

    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        from .. import functional as F
        return F.feature_alpha_dropout(x, self.p, self.training)
